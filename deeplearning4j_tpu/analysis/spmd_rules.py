"""The G010-G015 + G018 SPMD-divergence / fleet-robustness AST rules
(graftlint stage 3, AST side).

PR 4's multi-process runtime made rank-divergence the most expensive bug
class in the repo: a program that issues different collective sequences
on different processes deadlocks the whole fleet, and on this jax
generation the death is a SIGABRT ("Deadline Exceeded") with no Python
traceback (ARCHITECTURE.md §Distributed runtime failure matrix). These
rules catch the statically-visible shapes of that bug; the trace-level
twin (analysis/collective_audit.py) catches what only shows up in the
jaxpr.

Like G001-G009 the rules are pure stdlib — importing this module must
NOT import jax, so `tools/graftlint.py --stage ast` stays a pre-commit
fast path. Helpers shared with ast_rules.py are imported lazily inside
the rule functions (ast_rules registers these rules at its module
bottom, so a top-level import either way would be circular).

Each rule errs toward precision over recall, same contract as G001-G009:

- G010: rank-dependent control flow (`jax.process_index()`,
  `process_id`, the DL4J_TPU_PROCESS_ID env contract) guarding code that
  issues collectives, jit calls, or mesh construction — the deadlock
  shape. Not caught: rank-divergent programs reached through calls the
  AST cannot see into (those are collective_audit's job).
- G011: host nondeterminism (time.*, os.urandom, unseeded np.random,
  uuid, id()/hash()) flowing into jax calls or mesh/spec construction in
  distributed/, parallel/, nn/ — a per-process value baked into the
  traced program diverges the replicas' jaxprs. Not caught: taint
  through attributes or across function boundaries.
- G012: collective calls whose literal axis_name is not bound by an
  enclosing shard_map/pmap/mesh in the same function (or received as a
  parameter) — an unbound axis raises at trace time at best, and at
  worst silently binds to a different caller's axis. Not caught:
  axis names threaded through containers.
- G013: blocking host syncs (block_until_ready, device_get, .item())
  inside rank-conditional blocks — one process stalls on a value whose
  producing collective the other processes may never reach.
"""

from __future__ import annotations

import ast
import re

# Collective-issuing calls, canonical (the per-file import table resolves
# `from jax import lax` / `import jax.lax as lax` spellings to these).
COLLECTIVE_CALLS = frozenset(
    {"jax.lax." + n for n in (
        "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
        "all_gather", "all_to_all", "psum_scatter")}
    | {"jax.lax.pcast", "deeplearning4j_tpu.util.compat.pcast_varying"})

# Mesh construction — every process must build the identical mesh, so a
# rank-guarded construction is the same deadlock shape as a collective.
MESH_CTORS = frozenset({
    "jax.sharding.Mesh", "jax.make_mesh",
    "deeplearning4j_tpu.parallel.mesh.make_mesh",
    "deeplearning4j_tpu.distributed.global_mesh.make_global_mesh",
})

# Calls that BIND axis names for G012: collecting the string constants
# inside these calls yields the axis names visibly in scope.
_AXIS_BINDERS = frozenset({
    "jax.pmap", "jax.sharding.NamedSharding", "jax.sharding.PartitionSpec",
}) | MESH_CTORS

_RANK_NAMES = frozenset({"process_id", "process_index"})

_G011_SCOPE = ("/distributed/", "/parallel/", "/nn/")

# Host calls whose value differs per process (or per interpreter run —
# str hash is randomized by PYTHONHASHSEED, id() is an address).
_NONDET_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "os.urandom",
    "uuid.uuid1", "uuid.uuid4", "id", "hash",
})
# np.random entry points that are deterministic given their (seed) args.
_NONDET_SEEDABLE = frozenset({
    "numpy.random.default_rng", "numpy.random.RandomState",
})
_NONDET_EXEMPT_TAILS = frozenset({"seed", "default_rng", "RandomState",
                                  "Random", "get_state", "set_state"})

_BLOCKING_ATTRS = frozenset({"block_until_ready", "item"})
_BLOCKING_CALLS = frozenset({"jax.block_until_ready", "jax.device_get"})

SPMD_RULE_IDS = frozenset({"G010", "G011", "G012", "G013", "G014",
                           "G015", "G018"})


def _env_rank_var() -> str:
    """The env contract's process-id variable, imported from its single
    spelling (distributed/bootstrap.py — the G009 contract; bootstrap is
    stdlib-only so this keeps the AST stage jax-free)."""
    from deeplearning4j_tpu.distributed.bootstrap import ENV_PROCESS_ID

    return ENV_PROCESS_ID


def _is_rank_expr(expr: ast.AST, imports) -> bool:
    """Does `expr` read this process's rank? Recognized spellings:
    jax.process_index(), names/attrs/keys `process_id`/`process_index`,
    and the DL4J_TPU_PROCESS_ID env contract (literal or the imported
    ENV_PROCESS_ID constant)."""
    rank_env = _env_rank_var()
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            if imports.canon(node.func) == "jax.process_index":
                return True
        elif isinstance(node, ast.Name):
            if node.id in _RANK_NAMES:
                return True
            canon = imports.canon(node) or ""
            if canon.endswith(".ENV_PROCESS_ID"):
                return True
        elif isinstance(node, ast.Attribute) and node.attr in _RANK_NAMES:
            return True
        elif isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value in (rank_env, "process_id"):
            return True
    return False


def _iter_executed(stmts):
    """Nodes that EXECUTE when the given statements run — skips nested
    def/lambda bodies (defining a function under a rank guard issues
    nothing; calling it elsewhere is out of AST scope)."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _rank_conditionals(tree, imports):
    """Every if/while whose test reads the process rank."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.If, ast.While)) and \
                _is_rank_expr(node.test, imports):
            yield node


# --------------------------------------------------------------- G010

def g010_rank_divergent_control_flow(tree, imports, path):
    """Rank-dependent control flow around collectives / jit / mesh
    construction: the processes issue different SPMD programs and the
    first collective deadlocks the fleet (jax 0.4.x: SIGABRT "Deadline
    Exceeded", no Python traceback). Rank-guarded host-side effects
    (logging, checkpoint IO) are deliberately NOT flagged."""
    out = []
    for cond in _rank_conditionals(tree, imports):
        for node in _iter_executed(cond.body + cond.orelse):
            if not isinstance(node, ast.Call):
                continue
            name = imports.canon(node.func)
            if name in COLLECTIVE_CALLS:
                what = f"collective `{name}`"
            elif name in MESH_CTORS:
                what = f"mesh construction `{name}`"
            else:
                from deeplearning4j_tpu.analysis.ast_rules import _JIT_NAMES

                if name not in _JIT_NAMES:
                    continue
                what = f"jit call `{name}`"
            out.append(("G010", cond,
                        f"rank-dependent control flow guards {what} "
                        f"(line {node.lineno}) — processes issue different "
                        "collective sequences and the fleet deadlocks "
                        "(SIGABRT \"Deadline Exceeded\")",
                        "issue the identical collective/jit/mesh program "
                        "on every process; keep rank branches to host-side "
                        "effects (logging, checkpoint IO)"))
    return out


# --------------------------------------------------------------- G011

def _is_nondet_call(node: ast.Call, imports) -> bool:
    name = imports.canon(node.func) or ""
    if name in _NONDET_CALLS:
        return True
    if name in _NONDET_SEEDABLE:
        return not (node.args or node.keywords)  # unseeded
    if name.startswith(("numpy.random.", "random.")):
        return name.rsplit(".", 1)[-1] not in _NONDET_EXEMPT_TAILS
    return False


def _walk_scope(scope):
    """Nodes of one lexical scope, not descending into nested defs."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def g011_host_nondeterminism(tree, imports, path):
    """Host nondeterminism flowing into jax calls or mesh/spec
    construction in distributed/, parallel/, nn/: a time.*/os.urandom/
    unseeded-np.random/id()/hash() value differs per process, so baking
    it into a traced value (or a mesh/PartitionSpec) silently diverges
    the replicas' programs — the G010 deadlock without a visible branch.
    Taint tracking is per-scope and name-based (attributes and
    cross-function flow are out of scope)."""
    if not any(frag in path for frag in _G011_SCOPE):
        return []
    out = []
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for scope in scopes:
        tainted: set[str] = set()
        for _ in range(4):  # bounded fixpoint, order-insensitive
            before = len(tainted)
            for node in _walk_scope(scope):
                if not isinstance(node, ast.Assign):
                    continue
                dirty = any(
                    (isinstance(c, ast.Call)
                     and _is_nondet_call(c, imports))
                    or (isinstance(c, ast.Name)
                        and isinstance(c.ctx, ast.Load)
                        and c.id in tainted)
                    for c in ast.walk(node.value))
                if dirty:
                    for tgt in node.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
            if len(tainted) == before:
                break
        for node in _walk_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            name = imports.canon(node.func) or ""
            if not (name.startswith("jax.") or name in MESH_CTORS):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                dirty = any(
                    (isinstance(c, ast.Call)
                     and _is_nondet_call(c, imports))
                    or (isinstance(c, ast.Name)
                        and isinstance(c.ctx, ast.Load)
                        and c.id in tainted)
                    for c in ast.walk(arg))
                if dirty:
                    out.append(("G011", node,
                                f"host nondeterminism flows into `{name}` "
                                "— the value differs per process, so the "
                                "traced program / mesh diverges across "
                                "ranks (rank-divergent constant in the "
                                "jaxpr)",
                                "derive the value deterministically (seed "
                                "it, or broadcast rank-0's value through "
                                "the env contract) before it reaches jax"))
                    break
    return out


# --------------------------------------------------------------- G012

def _literal_axes(call: ast.Call):
    """String-constant axis names of a collective call: the `axis_name`
    keyword or the conventional second positional arg."""
    value = None
    for kw in call.keywords:
        if kw.arg == "axis_name":
            value = kw.value
    if value is None and len(call.args) >= 2:
        value = call.args[1]
    if value is None:
        return []
    elts = value.elts if isinstance(value, (ast.Tuple, ast.List)) else [value]
    return [e.value for e in elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)]


def _binder_strings(node: ast.AST, imports) -> set[str]:
    """String constants inside shard_map/pmap/mesh/spec calls under
    `node` — the axis names those calls visibly bind."""
    bound: set[str] = set()
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        name = imports.canon(sub.func) or ""
        if name in _AXIS_BINDERS or name == "shard_map" \
                or name.endswith(".shard_map"):
            bound |= {c.value for c in ast.walk(sub)
                      if isinstance(c, ast.Constant)
                      and isinstance(c.value, str)}
    return bound


def g012_unbound_axis_name(tree, imports, path):
    """Collective calls naming a literal axis that no enclosing
    shard_map/pmap/mesh in the same function chain binds (and that is
    not wrapped as a shard_map/pmap target elsewhere in the module):
    at best a NameError-at-trace, at worst the literal silently binds a
    different caller's axis. Axis names received as parameters (or any
    non-literal expression) are trusted."""
    from deeplearning4j_tpu.analysis.ast_rules import _parents

    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = imports.canon(node.func) or ""
        if name not in COLLECTIVE_CALLS:
            continue
        axes = _literal_axes(node)
        if not axes:
            continue
        chain = [p for p in _parents(node)
                 if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda))]
        bound: set[str] = set()
        for fn in chain:
            bound |= _binder_strings(fn, imports)
        # functions wrapped as shard_map/pmap targets elsewhere in the
        # module bind their axes at the wrap site
        chain_names = {fn.name for fn in chain
                       if isinstance(fn, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        for sub in ast.walk(tree):
            if not isinstance(sub, ast.Call) or not sub.args:
                continue
            sname = imports.canon(sub.func) or ""
            if not (sname == "shard_map" or sname.endswith(".shard_map")
                    or sname == "jax.pmap"):
                continue
            target = sub.args[0]
            if isinstance(target, ast.Call):  # partial(fn, ...)
                target = target.args[0] if target.args else target
            if isinstance(target, ast.Name) and target.id in chain_names:
                bound |= {c.value for c in ast.walk(sub)
                          if isinstance(c, ast.Constant)
                          and isinstance(c.value, str)}
        for ax in axes:
            if ax not in bound:
                out.append(("G012", node,
                            f"collective `{name}` names axis {ax!r} but "
                            "no enclosing shard_map/pmap/mesh in this "
                            "function binds it",
                            f"run the collective under a shard_map/mesh "
                            f"that binds {ax!r}, or accept the axis name "
                            "as a parameter"))
    return out


# --------------------------------------------------------------- G013

def g013_rank_conditional_host_sync(tree, imports, path):
    """Blocking host syncs (block_until_ready / device_get / .item())
    under a rank condition: the blocking process waits on a value whose
    producing collective the other ranks may never issue — the passive
    half of the G010 deadlock, and even when it resolves, it skews step
    pacing across the fleet."""
    out = []
    for cond in _rank_conditionals(tree, imports):
        for node in _iter_executed(cond.body + cond.orelse):
            if not isinstance(node, ast.Call):
                continue
            name = imports.canon(node.func) or ""
            blocking = name in _BLOCKING_CALLS or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_ATTRS and not node.args)
            if blocking:
                what = name if name in _BLOCKING_CALLS \
                    else f".{node.func.attr}()"
                out.append(("G013", node,
                            f"blocking host sync `{what}` inside a "
                            "rank-conditional block — the blocked rank "
                            "waits on device work the other ranks may "
                            "never schedule, skewing (or deadlocking) "
                            "the fleet",
                            "sync on every rank, or defer the host read "
                            "until after the collective step completes"))
    return out


# --------------------------------------------------------------- G014

# Calls whose failure must never be silently swallowed: a collective or
# rendezvous error is the fleet telling you a peer is gone — an
# overbroad handler that eats it turns a recoverable death into a
# divergent fleet (some ranks "succeeded", some are gone). Deliberately
# NOT included: jax.distributed.shutdown and teardown paths, where
# best-effort `except: pass` is the correct idiom.
_G014_SWALLOW_TRIGGERS = (COLLECTIVE_CALLS
                          | {"jax.distributed.initialize",
                             "deeplearning4j_tpu.distributed.bootstrap."
                             "initialize"})

_G014_OVERBROAD = frozenset({"Exception", "BaseException"})


def _handler_is_overbroad(handler: ast.ExceptHandler, imports) -> bool:
    if handler.type is None:  # bare `except:`
        return True
    name = imports.canon(handler.type)
    return name in _G014_OVERBROAD


def g014_swallowed_fleet_errors(tree, imports, path):
    """(a) bare/overbroad `except` that swallows (never re-raises)
    around collective or rendezvous-initialize calls — package-wide; and
    (b) `while True` retry loops in distributed/ that sleep inside an
    exception handler with no raise anywhere in the loop body — an
    uncapped retry (the bounded idiom is `bootstrap.Backoff`, whose
    exhausted budget makes the caller raise). Not caught: swallowing
    through helper functions the AST cannot see into, and loops bounded
    by non-`while True` conditions (those carry their own exit)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Try):
            trigger = None
            for sub in _iter_executed(node.body):
                if isinstance(sub, ast.Call) and \
                        imports.canon(sub.func) in _G014_SWALLOW_TRIGGERS:
                    trigger = imports.canon(sub.func)
                    break
            if trigger is None:
                continue
            for handler in node.handlers:
                if not _handler_is_overbroad(handler, imports):
                    continue
                if any(isinstance(s, ast.Raise)
                       for s in ast.walk(handler)):
                    continue
                out.append(("G014", handler,
                            f"overbroad `except` swallows failures of "
                            f"`{trigger}` — a dead peer's error "
                            "disappears and the fleet diverges instead "
                            "of recovering",
                            "catch the narrow exception, or re-raise "
                            "after cleanup so the elastic supervisor "
                            "can classify the death"))
        elif isinstance(node, ast.While) and "/distributed/" in \
                path.replace("\\", "/"):
            test = node.test
            if not (isinstance(test, ast.Constant) and test.value is True):
                continue
            body_nodes = list(_iter_executed(node.body))
            has_handler = any(isinstance(s, ast.ExceptHandler)
                              for s in body_nodes)
            sleeps = any(isinstance(s, ast.Call)
                         and imports.canon(s.func) == "time.sleep"
                         for s in body_nodes)
            raises = any(isinstance(s, ast.Raise) for s in body_nodes)
            if has_handler and sleeps and not raises:
                out.append(("G014", node,
                            "`while True` retry loop sleeps on failure "
                            "with no raise path — an unreachable "
                            "coordinator retries forever instead of "
                            "dying classifiably",
                            "use bootstrap.Backoff (full jitter + "
                            "max-elapsed cap) and raise when pause() "
                            "returns False"))
    return out


# --------------------------------------------------------------- G015

# The two files allowed to issue collectives on gradient pytrees: the
# bucket planner (parallel/overlap.py — bucketed_reduce and the
# unbucketed reduce_gradients routing) and the train-step assembly that
# consumes it. Everything else must route through them, so the bucket
# schedule stays the single source of the per-rank gradient-collective
# sequence the stage-3 audit freezes.
_G015_BLESSED = ("deeplearning4j_tpu/parallel/overlap.py",
                 "deeplearning4j_tpu/nn/training.py")

# Identifier shapes that mean "this value is a gradient pytree" —
# precision over recall: `g`, `delta`, or `update` alone do not flag.
_G015_GRAD_NAME = re.compile(r"(?:^|_)(d?grads?|gradients?)(?:_|$|\d)",
                             re.IGNORECASE)


def _names_gradients(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        ident = None
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        if ident is not None and _G015_GRAD_NAME.search(ident):
            return True
    return False


def g015_handrolled_gradient_collective(tree, imports, path):
    """A collective call whose operand is a gradient pytree, outside the
    blessed bucket-planner sites (parallel/overlap.py, nn/training.py):
    hand-rolled gradient reductions fork the per-rank collective
    sequence away from the frozen bucket schedule — the C001/C003 drift
    class at its source. Detection is name-based (an operand expression
    mentioning grads/gradients); collectives on losses, params, or
    activations never flag."""
    norm = path.replace("\\", "/")
    if any(norm.endswith(b) for b in _G015_BLESSED):
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = imports.canon(node.func) or ""
        if name not in COLLECTIVE_CALLS:
            continue
        operands = list(node.args) + [k.value for k in node.keywords
                                      if k.arg not in ("axis_name",)]
        if any(_names_gradients(arg) for arg in operands):
            out.append(("G015", node,
                        f"hand-rolled collective `{name}` on a gradient "
                        "pytree outside parallel/overlap.py / "
                        "nn/training.py — gradient reductions must route "
                        "through the bucket planner so every rank issues "
                        "the frozen per-bucket collective sequence",
                        "call parallel/overlap.bucketed_reduce (or "
                        "reduce_gradients for the unbucketed tree mean) "
                        "instead of issuing the collective directly"))
    return out


# --------------------------------------------------------------- G018

# The blessed full-tree host-materialization sites: the portable
# resharding engine and the two checkpoint formats. Everywhere else, a
# whole-param-tree host materialization defeats the resharding engine's
# guarantee (spanning-mesh restores never materialize full params on
# host) and reintroduces the gather-everything-to-host scaling wall the
# reshard/ subsystem exists to remove.
_G018_BLESSED = ("deeplearning4j_tpu/reshard/",
                 "deeplearning4j_tpu/util/orbax_checkpoint.py",
                 "deeplearning4j_tpu/util/model_serializer.py")

# identifiers that denote a WHOLE param/optimizer tree (a bare name or
# a terminal attribute like `net.params`); subscripts (`params["W"]`)
# and calls are single leaves / derived values and never flag.
_G018_TREE_NAMES = frozenset({"params", "opt_state", "param_tree",
                              "params_tree", "opt_tree"})

_G018_MATERIALIZERS = frozenset({"numpy.asarray", "numpy.array",
                                 "jax.device_get"})
_G018_TREE_MAP = frozenset({"jax.tree.map", "jax.tree_util.tree_map"})


def _g018_is_whole_tree(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in _G018_TREE_NAMES
    if isinstance(expr, ast.Attribute):
        return expr.attr in _G018_TREE_NAMES
    return False


def _g018_is_materializer(expr: ast.AST, imports) -> bool:
    name = imports.canon(expr) or ""
    if name in _G018_MATERIALIZERS:
        return True
    return isinstance(expr, ast.Attribute) and expr.attr in ("asarray",
                                                             "device_get")


def g018_full_tree_host_materialization(tree, imports, path):
    """Full-parameter host materialization outside the blessed
    reshard/ + checkpoint paths: (a) any `host_materialize(...)` call,
    (b) `jax.device_get`/`np.asarray` whose operand IS a whole
    params/opt_state tree (a bare name or `net.params`-style attribute),
    (c) `jax.tree.map(np.asarray | jax.device_get, <tree>)` — the
    leaf-at-a-time spelling of the same gather. Single-leaf reads
    (`params["W"]`), derived values, and per-leaf loops are deliberately
    not caught (precision over recall); route tree-level moves through
    `reshard/` (live) or `ShardedCheckpointer.restore(target_mesh=...)`
    (checkpoint) instead."""
    norm = path.replace("\\", "/")
    if any(b in norm for b in _G018_BLESSED):
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = imports.canon(node.func) or ""
        is_hm = (name.endswith(".host_materialize")
                 or name == "host_materialize")
        if is_hm:
            out.append(("G018", node,
                        "`host_materialize` outside the blessed reshard/"
                        " + checkpoint paths gathers the full param tree "
                        "to host — the scaling wall the portable "
                        "resharding engine removes",
                        "restore/move through reshard/ (ShardedCheck"
                        "pointer.restore(net, target_mesh=...) or "
                        "reshard.executor.reshard_net_live)"))
            continue
        if name in _G018_MATERIALIZERS:
            if any(_g018_is_whole_tree(a) for a in node.args):
                out.append(("G018", node,
                            f"`{name}` over a whole param/optimizer tree "
                            "materializes every shard on host",
                            "keep the tree on device; reshard through "
                            "reshard/ or read single leaves explicitly"))
            continue
        if name in _G018_TREE_MAP and len(node.args) >= 2 \
                and _g018_is_materializer(node.args[0], imports) \
                and any(_g018_is_whole_tree(a) for a in node.args[1:]):
            out.append(("G018", node,
                        "tree-mapped host materialization "
                        "(`jax.tree.map(np.asarray, <param tree>)`) — "
                        "the leaf-at-a-time spelling of a full-tree "
                        "host gather",
                        "keep the tree on device; reshard through "
                        "reshard/ instead of materializing"))
    return out


SPMD_RULES = [g010_rank_divergent_control_flow, g011_host_nondeterminism,
              g012_unbound_axis_name, g013_rank_conditional_host_sync,
              g014_swallowed_fleet_errors,
              g015_handrolled_gradient_collective,
              g018_full_tree_host_materialization]

SPMD_RULE_DOCS = {
    "G010": "rank-dependent control flow guarding collectives/jit/mesh "
            "(fleet deadlock shape)",
    "G011": "host nondeterminism (time/urandom/unseeded rng/id/hash) "
            "flowing into traced values or mesh construction",
    "G012": "collective axis_name not bound by an enclosing "
            "shard_map/pmap/mesh or a parameter",
    "G013": "blocking host sync (.item/device_get/block_until_ready) "
            "inside rank-conditional blocks",
    "G014": "overbroad except swallowing collective/rendezvous errors; "
            "uncapped retry loops in distributed/",
    "G015": "hand-rolled collective on a gradient pytree outside "
            "parallel/overlap.py / nn/training.py (the blessed bucket-"
            "planner sites)",
    "G018": "full-parameter host materialization (host_materialize / "
            "device_get / np.asarray over whole param trees) outside "
            "the blessed reshard/ + checkpoint paths",
}
