"""graftlint stage 5, AST side: the G031-G034 precision-discipline rules.

The mixed-precision surface (bf16 MXU operands + f32 accumulators, the
int8 paged KV cache with per-(row, page, head) scales) is enforced by
convention in the kernels — and a convention is exactly what a refactor
silently drops. These rules freeze the statically visible half of the
dtype policy; the trace-level half (accumulation dtypes, quantize
pairing, convert churn along real dataflow) is analysis/
precision_audit.py, which sees what the AST cannot.

Pure stdlib ``ast`` like stages 1/4 — importing this module must NOT
import jax, so `tools/graftlint.py --stage ast` (and `--changed`) stays
a sub-second pre-commit path even with jax poisoned. ``ast_rules``
registers these rules into ALL_RULES/RULE_DOCS at its module bottom
(the spmd_rules/concurrency_rules pattern); shared helpers are imported
lazily inside the rule bodies to keep that cycle clean.

- G031: a `jnp.einsum`/`jnp.matmul`/`jnp.dot`/`lax.dot_general` in the
  kernel dirs (ops/, embedding/) without `preferred_element_type`, or a
  bare `@` (which cannot carry one) — the accumulator dtype left to the
  backend default instead of declared. On TPU a bf16 operand pair
  accumulated at the default output dtype is the silent-precision bug
  class the f32-accumulation policy exists to prevent.
- G032: float64 entering DEVICE code — `jnp.float64`, `.astype` to
  float64, or `dtype=float64` fed to a jax call, plus `np.float64`
  constructors in the device dirs. Host-side numpy analytics
  (clustering/, graph/, util/ math helpers) legitimately run f64 and
  stay out of scope; declarative name->dtype registry tables (a dict
  literal keyed by dtype-name strings) are exempt — the drift happens
  where a literal f64 dtype is APPLIED, which stage 2's J003 then
  proves at trace level. This rule promotes that check to pre-commit.
- G033: hand-rolled quantization scale math — the symmetric-int8
  constants 127/127.0 in mul/div/clip arithmetic (or a float 128.0
  scale) outside the blessed `ops/decode_attention.py` quantize
  helpers. A second spelling of `maxabs/127` is how a cache writer and
  its reader disagree about scales (the q8-scale-mismatch serving
  failure class). Integer 128 alone is the lane tile (G016's
  structural exemption) and round-up expressions like `(n + 127) //
  128` never flag: only Mult/Div/clip contexts count.
- G034: a dtype cast applied to a WHOLE params/opt_state tree —
  `params.astype(...)`, `lax.convert_element_type(params, ...)`, or a
  `jax.tree.map` whose mapped function casts — outside the blessed
  reshard/ + checkpoint paths that own the dtype policy. A wholesale
  tree cast silently rewrites every accumulator's dtype (the optimizer
  moments included), bypassing reshard/'s per-leaf policy and the
  checkpoint restore contract. Single-leaf casts (`params["W"]
  .astype(...)`) never flag.
"""

from __future__ import annotations

import ast

PRECISION_RULE_IDS = frozenset({"G031", "G032", "G033", "G034"})

# kernel dirs whose contractions must declare their accumulator dtype
_G031_SCOPE = ("/ops/", "/embedding/")

_G031_DOT_CALLS = frozenset({
    "jax.numpy.einsum", "jax.numpy.matmul", "jax.numpy.dot",
    "jax.numpy.tensordot", "jax.lax.dot", "jax.lax.dot_general",
})

# device-side dirs for the np.float64-constructor half of G032 (host
# analytics dirs — clustering/, graph/, util math — legitimately run
# f64 and are deliberately out of scope)
_G032_DEVICE_DIRS = ("/ops/", "/nn/", "/parallel/", "/embedding/",
                     "/distributed/", "/serving/", "/models/",
                     "/reshard/", "/eval/")

_F64_CANON = frozenset({"jax.numpy.float64", "numpy.float64"})
_F64_STRINGS = frozenset({"float64", "f64", ">f8", "<f8", "f8"})

# the finite-difference harness deliberately runs f64 (tests enable
# x64); it is the one blessed f64 consumer of the jax API surface
_G032_BLESSED_DIRS = ("/gradientcheck/",)

_G033_BLESSED = "ops/decode_attention.py"
_G033_CONSTS = (127, 127.0, -127, -127.0)

_G034_BLESSED = ("deeplearning4j_tpu/reshard/",
                 "deeplearning4j_tpu/util/orbax_checkpoint.py",
                 "deeplearning4j_tpu/util/model_serializer.py")
_G034_TREE_NAMES = frozenset({"params", "opt_state", "param_tree",
                              "params_tree", "opt_tree"})
_G034_TREE_MAP = frozenset({"jax.tree.map", "jax.tree_util.tree_map"})


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _in_dirs(path: str, fragments) -> bool:
    norm = "/" + _norm(path)
    return any(frag in norm for frag in fragments)


# ------------------------------------------------------------------ G031

def g031_undeclared_accumulator(tree, imports, path):
    """Contractions in ops/ + embedding/ without an explicit
    `preferred_element_type` (or spelled `@`, which cannot carry one)."""
    if not _in_dirs(path, _G031_SCOPE):
        return []
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            canon = imports.canon(node.func)
            if canon in _G031_DOT_CALLS and not any(
                    kw.arg == "preferred_element_type"
                    for kw in node.keywords):
                short = canon.split(".")[-1]
                out.append((
                    "G031", node,
                    f"`{short}` in a kernel dir without "
                    "preferred_element_type — the accumulator dtype is "
                    "left to the backend default (bf16 operands then "
                    "accumulate sub-f32)",
                    "pass preferred_element_type=jnp.float32 (the f32-"
                    "accumulation policy ops/flash_attention.py "
                    "follows)"))
        elif isinstance(node, ast.BinOp) and isinstance(node.op,
                                                        ast.MatMult):
            out.append((
                "G031", node,
                "`@` matmul in a kernel dir — the operator cannot "
                "declare an accumulator dtype",
                "spell the contraction as jnp.einsum/lax.dot_general "
                "with preferred_element_type=jnp.float32"))
    return out


# ------------------------------------------------------------------ G032

def _is_dtype_registry_value(node) -> bool:
    """Is `node` a VALUE in a dict literal keyed by dtype-name strings
    (a declarative name->dtype registry, e.g. nn/multilayer._DTYPES)?
    The registry itself introduces no f64 — selecting from it does."""
    from deeplearning4j_tpu.analysis.ast_rules import _parents

    for parent in _parents(node):
        if isinstance(parent, ast.Dict):
            keys = [k for k in parent.keys if k is not None]
            if keys and all(isinstance(k, ast.Constant)
                            and isinstance(k.value, str) for k in keys):
                return node in parent.values or any(
                    v is node or node in ast.walk(v)
                    for v in parent.values)
        elif isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            return False
    return False


def _names_f64(node, imports) -> bool:
    if isinstance(node, (ast.Name, ast.Attribute)):
        return imports.canon(node) in _F64_CANON
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in _F64_STRINGS
    return False


def g032_float64_device_drift(tree, imports, path):
    """float64 entering device code: `jnp.float64` anywhere,
    `.astype(float64)` / `dtype=float64` on jax calls, and np.float64
    constructors in the device dirs."""
    if _in_dirs(path, _G032_BLESSED_DIRS):
        return []
    out = []
    device_dir = _in_dirs(path, _G032_DEVICE_DIRS)
    fixit = ("keep device math float32/bfloat16 (the TPU dtype policy); "
             "pin host constants with an explicit f32 dtype")
    for node in ast.walk(tree):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if imports.canon(node) == "jax.numpy.float64" \
                    and not _is_dtype_registry_value(node):
                out.append((
                    "G032", node,
                    "jnp.float64 — a float64 dtype aimed at the traced "
                    "program (stage 2's J003 class, caught pre-commit)",
                    fixit))
        elif isinstance(node, ast.Call):
            canon = imports.canon(node.func)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "astype" and node.args \
                    and _names_f64(node.args[0], imports) and device_dir:
                out.append((
                    "G032", node,
                    ".astype(float64) — an explicit widen to f64 in a "
                    "device dir",
                    fixit))
            elif canon == "numpy.float64" and device_dir:
                out.append((
                    "G032", node,
                    "np.float64 constructor in a device dir — the "
                    "scalar widens every jnp expression it meets",
                    fixit))
            elif canon and (canon.startswith("jax.")
                            or canon.startswith("jax.numpy.")):
                for kw in node.keywords:
                    if kw.arg == "dtype" and _names_f64(kw.value,
                                                        imports):
                        out.append((
                            "G032", node,
                            "dtype=float64 on a jax call — float64 "
                            "built directly into the traced program",
                            fixit))
    return out


# ------------------------------------------------------------------ G033

def _is_q8_const(node) -> bool:
    """127 in any numeric spelling; 128 only as a FLOAT (int 128 is the
    lane tile — G016's structural constant — and round-up expressions
    like `(n + 127) // 128 * 128` must never flag)."""
    if not isinstance(node, ast.Constant) or isinstance(node.value, bool):
        return False
    v = node.value
    if isinstance(v, int):
        return v in (127, -127)
    if isinstance(v, float):
        return v in (127.0, -127.0, 128.0, -128.0)
    return False


def g033_hardcoded_quant_scale(tree, imports, path):
    """Symmetric-int8 scale constants (127 in mul/div/clip, float
    128.0) outside the blessed decode_attention quantize helpers."""
    if _norm(path).endswith(_G033_BLESSED):
        return []
    out = []
    fixit = ("route scale math through ops/decode_attention.py's "
             "quantize_pages/dequantize_pages/quantized_cache_update — "
             "a second spelling of maxabs/127 is how a cache writer and "
             "reader disagree (q8-scale-mismatch)")
    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp) \
                and isinstance(node.op, (ast.Mult, ast.Div)) \
                and (_is_q8_const(node.left) or _is_q8_const(node.right)):
            out.append((
                "G033", node,
                "hand-rolled int8 quantization scale math (127/128 "
                "mul-div) outside the blessed quantize helpers",
                fixit))
        elif isinstance(node, ast.Call) \
                and (imports.canon(node.func) or "").endswith(".clip") \
                and any(_is_q8_const(a) for a in node.args):
            out.append((
                "G033", node,
                "hand-rolled int8 code clamp (clip to ±127) outside "
                "the blessed quantize helpers",
                fixit))
    return out


# ------------------------------------------------------------------ G034

def _is_tree_expr(expr) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in _G034_TREE_NAMES
    if isinstance(expr, ast.Attribute):
        return expr.attr in _G034_TREE_NAMES
    return False


def _casts_inside(fn_node) -> bool:
    """Does a mapped function (lambda or named ref is not resolvable —
    lambdas only) cast its argument's dtype?"""
    if not isinstance(fn_node, ast.Lambda):
        return False
    for sub in ast.walk(fn_node.body):
        if isinstance(sub, ast.Call):
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "astype":
                return True
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "convert_element_type":
                return True
    return False


def g034_whole_tree_dtype_cast(tree, imports, path):
    """Wholesale dtype casts of params/opt_state trees outside the
    blessed reshard/ + checkpoint dtype-policy paths."""
    norm = _norm(path)
    if any(b in norm if b.endswith("/") else norm.endswith(b)
           for b in _G034_BLESSED):
        return []
    out = []
    fixit = ("cast per-leaf inside the blessed dtype-policy paths "
             "(reshard/, util/orbax_checkpoint.py, "
             "util/model_serializer.py) — a wholesale tree cast "
             "rewrites the optimizer accumulators' dtype too")
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        canon = imports.canon(node.func)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype" \
                and _is_tree_expr(node.func.value):
            out.append((
                "G034", node,
                "whole params/opt_state tree .astype(...) — a "
                "dtype-mutating cast of every leaf (accumulators "
                "included) outside the blessed dtype-policy paths",
                fixit))
        elif canon == "jax.lax.convert_element_type" and node.args \
                and _is_tree_expr(node.args[0]):
            out.append((
                "G034", node,
                "lax.convert_element_type over a whole params/"
                "opt_state tree outside the blessed dtype-policy paths",
                fixit))
        elif canon in _G034_TREE_MAP and len(node.args) >= 2 \
                and any(_is_tree_expr(a) for a in node.args[1:]) \
                and _casts_inside(node.args[0]):
            out.append((
                "G034", node,
                "jax.tree.map casting a whole params/opt_state tree's "
                "dtype outside the blessed dtype-policy paths",
                fixit))
    return out


PRECISION_RULES = [g031_undeclared_accumulator, g032_float64_device_drift,
                   g033_hardcoded_quant_scale, g034_whole_tree_dtype_cast]

PRECISION_RULE_DOCS = {
    "G031": "accumulator discipline (ops/ + embedding/): "
            "einsum/matmul/dot/dot_general without "
            "preferred_element_type, or a bare `@` which cannot carry "
            "one — declare f32 accumulation where the contraction is "
            "written",
    "G032": "float64 drift into device code: jnp.float64, "
            ".astype(float64), dtype=float64 on jax calls, np.float64 "
            "constructors in device dirs (stage 2's J003 promoted to "
            "pre-commit; host analytics dirs, gradientcheck/'s f64 "
            "finite differences, and name->dtype registry tables "
            "exempt)",
    "G033": "hand-rolled int8 quantization scale math — 127/127.0 in "
            "mul/div/clip or a float 128.0 scale outside the blessed "
            "ops/decode_attention.py quantize helpers (the "
            "q8-scale-mismatch class); lane-tile 128 and (n+127)//128 "
            "round-ups never flag",
    "G034": "dtype-mutating cast of a WHOLE params/opt_state tree "
            "(.astype / convert_element_type / tree.map of a cast) "
            "outside the blessed reshard/ + checkpoint dtype-policy "
            "paths; single-leaf casts never flag",
}
