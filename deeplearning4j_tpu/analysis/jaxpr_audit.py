"""Stage 2: jaxpr audit of the public jitted entry points.

Traces each entry point with abstract/CPU inputs (no FLOPs execute) and
audits the closed jaxpr:

- J001: forbidden primitive — device_put / callback / host-transfer ops
  inside the traced program. At production scale these are per-step
  host<->device syncs; they must be structurally absent, not "rare".
- J002: op-count budget exceeded — every entry point has a frozen upper
  bound in analysis/jaxpr_budget.json. Silent graph bloat (a retrace
  that doubled the program, an accidentally unrolled loop) trips this
  long before a TPU run notices.
- J003: float64 value in the traced program — dtype drift; everything
  compute-side is float32/bfloat16 by design.
- J004: entry point missing from the budget file (run
  `tools/graftlint.py --update-budget`).

Import note: jax and bench load lazily so stage 1 stays jax-free.
"""

from __future__ import annotations

import json
import os

from deeplearning4j_tpu.analysis.core import Finding

BUDGET_PATH = os.path.join(os.path.dirname(__file__), "jaxpr_budget.json")

FORBIDDEN_PRIMITIVES = frozenset({
    "device_put", "copy", "pure_callback", "io_callback", "debug_callback",
    "callback", "outside_call", "infeed", "outfeed",
})

# op-count bounds get this headroom over the observed count when
# (re)generated, then stay FROZEN until explicitly regenerated.
_BUDGET_HEADROOM = 1.25
_BUDGET_QUANTUM = 25

_LM_STEP_PREFIX = "lm_step/"


def entry_names() -> list[str]:
    """All auditable entry points (stable order). Safe to call without
    jax — used for test parametrization."""
    names = [
        "flash_attention/causal",
        "flash_attention/masked",
        "flash_attention/dropout",
        "flash_attention/grad",
        "flash_attention_qkv/causal",
        "chunked_flash_attention/seq4096",
        "fused_layer_norm",
        "softmax_xent_head",
    ]
    import bench  # repo-root module; cheap (no jax work at import)
    names += [_LM_STEP_PREFIX + mode for mode in sorted(bench.LM_MODE_DIMS)]
    return names


def _sds(shape, dtype):
    import jax
    return jax.ShapeDtypeStruct(shape, dtype)


def _build(name):
    """-> (fn, args tuple) for one entry point, with abstract inputs
    wherever jax.make_jaxpr accepts them."""
    import jax
    import jax.numpy as jnp

    if name.startswith(_LM_STEP_PREFIX):
        import bench
        mode = name[len(_LM_STEP_PREFIX):]
        net, ds, _cfg = bench.lm_mode_net_ds(mode, force_tpu_dims=True)
        batch = net._batch_dict(net._to_mds(ds))
        step = net._get_train_step()
        return step, (net.params, net.opt_state, net.state,
                      jax.random.PRNGKey(0), batch)

    from deeplearning4j_tpu.ops.flash_attention import (
        chunked_flash_attention, flash_attention, flash_attention_qkv)
    from deeplearning4j_tpu.ops.fused_layernorm import fused_layer_norm
    from deeplearning4j_tpu.ops.fused_softmax_xent import softmax_xent_head

    f32 = jnp.float32
    B, H, T, D = 2, 2, 512, 64
    qkv3 = tuple(_sds((B, H, T, D), f32) for _ in range(3))
    if name == "flash_attention/causal":
        return (lambda q, k, v: flash_attention(q, k, v, causal=True)), qkv3
    if name == "flash_attention/masked":
        return (lambda q, k, v, m: flash_attention(q, k, v, causal=True,
                                                   mask=m)), \
            qkv3 + (_sds((B, T), f32),)
    if name == "flash_attention/dropout":
        return (lambda q, k, v, key: flash_attention(
            q, k, v, causal=True, dropout=0.1, dropout_rng=key)), \
            qkv3 + (jax.random.PRNGKey(0),)
    if name == "flash_attention/grad":
        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).sum()
        return jax.grad(loss, argnums=(0, 1, 2)), qkv3
    if name == "flash_attention_qkv/causal":
        # d_model 256 / 2 heads -> D=128, the packed-qkv regime
        return (lambda qkv: flash_attention_qkv(qkv, 2, causal=True)), \
            (_sds((B, T, 3 * 256), f32),)
    if name == "chunked_flash_attention/seq4096":
        shapes = tuple(_sds((1, 2, 4096, D), f32) for _ in range(3))
        return (lambda q, k, v: chunked_flash_attention(
            q, k, v, causal=True)), shapes
    if name == "fused_layer_norm":
        return fused_layer_norm, (_sds((1024, 512), f32),
                                  _sds((512,), f32), _sds((512,), f32))
    if name == "softmax_xent_head":
        return softmax_xent_head, (
            _sds((1024, 256), f32), _sds((256, 10000), f32),
            _sds((10000,), f32), _sds((1024,), jnp.int32))
    raise KeyError(name)


_CLOSED_CACHE: dict = {}


def closed_jaxpr(name):
    """Memoised closed jaxpr for one stage-2 entry point. Stage 2 and
    the stage-5 precision audit walk the SAME entries, so under
    `--stage all` each entry is traced exactly once (the LM-step traces
    dominate the suite's wall time)."""
    closed = _CLOSED_CACHE.get(name)
    if closed is None:
        import jax

        fn, args = _build(name)
        closed = _CLOSED_CACHE[name] = jax.make_jaxpr(fn)(*args)
    return closed


def _iter_eqns(jaxpr):
    """Every eqn, recursing into sub-jaxprs (pjit bodies, scan, cond
    branches, custom_vjp calls...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            for sub in (val if isinstance(val, (list, tuple)) else [val]):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    yield from _iter_eqns(inner)
                elif hasattr(sub, "eqns"):
                    yield from _iter_eqns(sub)


def trace_entry(name):
    """-> (op_count, findings-without-budget-check). Traces on the
    current (CPU) backend with abstract inputs; nothing executes."""
    import numpy as np

    closed = closed_jaxpr(name)
    count = 0
    findings = []
    seen_f64: set[str] = set()
    for eqn in _iter_eqns(closed.jaxpr):
        count += 1
        prim = eqn.primitive.name
        if prim in FORBIDDEN_PRIMITIVES:
            findings.append(Finding(
                "J001", name, 0, 0,
                f"traced program contains `{prim}` (host/device transfer "
                "or callback inside the step)",
                "hoist the transfer/callback out of the jitted path",
                snippet=prim, stage="jaxpr"))
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and dtype == np.float64 and \
                    prim not in seen_f64:
                seen_f64.add(prim)
                findings.append(Finding(
                    "J003", name, 0, 0,
                    f"`{prim}` produces float64 — dtype drift into the "
                    "traced program",
                    "pin the dtype at the source (np.float32 constant / "
                    "explicit dtype=)", snippet=f"f64:{prim}",
                    stage="jaxpr"))
    return count, findings


def load_budget(path: str = BUDGET_PATH) -> dict[str, int]:
    try:
        with open(path) as fh:
            return {k: int(v) for k, v in json.load(fh)["ops"].items()}
    except FileNotFoundError:
        return {}


def audit(names=None, budget_path: str = BUDGET_PATH):
    """Run the full stage-2 audit -> (findings, {entry: op_count})."""
    budget = load_budget(budget_path)
    findings, counts = [], {}
    for name in names if names is not None else entry_names():
        count, fs = trace_entry(name)
        counts[name] = count
        findings.extend(fs)
        bound = budget.get(name)
        if bound is None:
            findings.append(Finding(
                "J004", name, 0, 0,
                f"entry point has no frozen op budget (traced {count} "
                "ops)",
                "run `python tools/graftlint.py --update-budget`",
                snippet="missing-budget", stage="jaxpr"))
        elif count > bound:
            findings.append(Finding(
                "J002", name, 0, 0,
                f"jaxpr has {count} ops, over the frozen bound of "
                f"{bound} — retrace/bloat regression",
                "find what grew the traced program; only then refresh "
                "the budget (--update-budget)", snippet="over-budget",
                stage="jaxpr"))
    return findings, counts


def write_budget(counts: dict[str, int], path: str = BUDGET_PATH) -> None:
    ops = {}
    for name, count in sorted(counts.items()):
        padded = int(count * _BUDGET_HEADROOM)
        ops[name] = padded + (-padded % _BUDGET_QUANTUM)
    with open(path, "w") as fh:
        json.dump(
            {"comment": "frozen jaxpr op-count upper bounds per entry "
                        "point (graftlint stage 2). Regenerate only when "
                        "a legitimate change grows the program: "
                        "tools/graftlint.py --update-budget",
             "ops": ops}, fh, indent=1)
        fh.write("\n")
