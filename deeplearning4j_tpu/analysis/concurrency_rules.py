"""graftlint stage 4, part 1: host-concurrency AST rules (G025-G028).

The serving/data/fleet runtime is a threaded host program: worker loops,
dispatcher threads, checkpoint watchers and supervisors all share
mutable state under ad-hoc ``threading`` locks. These rules police the
race and liveness classes that the device-side stages (jaxpr budgets,
collective audit) cannot see:

G025  shared-attribute race — an attribute mutated with a
      read-modify-write (``+=``) on the thread side of a class and also
      touched from public methods with no common lock guard. The guard
      is *inferred*: a lock group "guards" an attribute when >= 90% of
      its mutation sites sit inside ``with self.<lock>:``; the stray
      sites (and unguarded public reads) are the findings. Plain
      wholesale assignment (``self.x = value``) is exempt from the
      read-side check — a single reference store/load is atomic under
      the GIL, which is exactly the WeightStore lock-free-reader design.

G026  blocking call under a held lock — ``queue.get/put``,
      ``Condition.wait`` (on a condition other than the held one),
      ``Thread.join``, ``Event.wait``, sockets/HTTP, ``subprocess``,
      ``time.sleep`` and jax device syncs inside a ``with <lock>:``
      body on the request/decode paths (serving/, data/, telemetry/).
      Invoking registered callbacks (sinks, collectors, listeners)
      while holding a lock is flagged too: the callback can block, or
      re-enter the lock (the D002 shape, caught here at the AST level).

G027  wait/notify/sleep discipline in serving/ and data/:
      ``Condition.wait`` outside a while-predicate loop (spurious
      wakeups), ``notify`` without holding the owning lock, and bare
      ``time.sleep`` polling loops — the spin-loop class the Channel
      rewrite removed; this rule keeps it out.

G028  thread-lifecycle discipline — a class that spawns a non-daemon
      thread must ``join`` it somewhere (or every interpreter exit
      hangs); a class that spawns a daemon thread must expose a
      stop/drain/close handle so externally visible resources (an open
      Recorder file, reserved PagePool pages) are released
      deterministically.

Everything here is pure stdlib ``ast`` — the stage runs with jax
poisoned, like stage 1. ``ast_rules`` registers these rules into
ALL_RULES/RULE_DOCS at its module bottom (same pattern as
spmd_rules); helpers are imported lazily to keep that cycle clean.

The attribute->lock inference is public API (``guard_map`` /
``guard_map_for_file``): tests/test_concurrency_lint.py pins the
inferred maps for PagePool, WeightStore and Channel exactly, so a
refactor that silently drops a guard fails by name.
"""

from __future__ import annotations

import ast
import re

# ------------------------------------------------------------------ model

_LOCK_CTORS = frozenset({"threading.Lock", "threading.RLock",
                         "threading.Semaphore",
                         "threading.BoundedSemaphore"})
_COND_CTORS = frozenset({"threading.Condition"})
_QUEUE_CTORS = frozenset({"queue.Queue", "queue.LifoQueue",
                          "queue.PriorityQueue", "queue.SimpleQueue"})
_EVENT_CTORS = frozenset({"threading.Event"})
_THREAD_CTORS = frozenset({"threading.Thread"})

_GUARD_RATIO = 0.9
_SINKISH = re.compile(r"sink|callback|listener|hook|collector|subscriber",
                      re.IGNORECASE)
_MUTATOR_METHODS = frozenset({"append", "appendleft", "extend", "insert",
                              "pop", "popleft", "remove", "discard", "add",
                              "clear", "update", "setdefault", "popitem"})
_HANDLE_NAMES = frozenset({"stop", "close", "drain", "shutdown", "retire",
                           "terminate", "cancel", "join"})

_G026_PATHS = ("serving/", "data/", "telemetry/")
_G027_PATHS = ("serving/", "data/")

_BLOCKING_CALLS = frozenset({
    "time.sleep",
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "socket.create_connection", "urllib.request.urlopen",
    "jax.device_get", "jax.block_until_ready", "jax.effects_barrier",
})


def _in_paths(path: str, prefixes) -> bool:
    p = path.replace("\\", "/")
    return any(seg in p for seg in prefixes)


def _own_nodes(fn: ast.AST):
    """Nodes of *fn* without descending into nested def/class/lambda
    bodies (a nested worker loop runs on another thread, later)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _parents(node: ast.AST):
    from deeplearning4j_tpu.analysis.ast_rules import _parents as p
    return p(node)


def _self_attr(node: ast.AST) -> str | None:
    """'x' for a `self.x` attribute expression, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _Site:
    """One mutation (or public read) of a shared attribute."""

    __slots__ = ("node", "kind", "fn", "method", "held", "in_init")

    def __init__(self, node, kind, fn, method, held, in_init):
        self.node = node        # the AST node, for line info
        self.kind = kind        # "aug" | "assign" | "call"
        self.fn = fn            # nearest enclosing function def
        self.method = method    # enclosing top-level method name
        self.held = held        # frozenset of lock-group names held
        self.in_init = in_init  # directly in the __init__ body


class ClassModel:
    """Locks, threads and shared-attribute sites of one class."""

    def __init__(self, node: ast.ClassDef, imports):
        self.node = node
        self.name = node.name
        self.methods: dict[str, ast.FunctionDef] = {
            item.name: item for item in node.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.lock_groups: dict[str, str] = {}   # lock attr -> group name
        self.attr_types: dict[str, str] = {}    # attr -> queue/thread/...
        self.thread_sites: list[tuple] = []     # (call, daemon, method)
        self.entries: set[int] = set()          # id() of thread-side fns
        self._entry_nodes: list[tuple] = []     # (fn node, encl method)
        self._collect_locks(imports)
        self._collect_threads(imports)
        self._close_entries()
        self.sites: dict[str, list[_Site]] = {}
        self.public_reads: dict[str, list[_Site]] = {}
        self._collect_sites()

    # -- locks -------------------------------------------------------
    def _collect_locks(self, imports) -> None:
        # token -> attr names sharing one underlying lock; a Condition
        # built from an existing Lock joins that lock's token, so
        # Channel's two conditions over one Lock become ONE group.
        token_attrs: dict[tuple, set[str]] = {}
        for fn in self.methods.values():
            local_tokens: dict[str, tuple] = {}
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign) or \
                        len(node.targets) != 1 or \
                        not isinstance(node.value, ast.Call):
                    continue
                ctor = imports.canon(node.value.func)
                tgt = node.targets[0]
                attr = _self_attr(tgt)
                if ctor in _LOCK_CTORS or ctor in _COND_CTORS:
                    token: tuple | None = None
                    if ctor in _COND_CTORS and node.value.args:
                        arg = node.value.args[0]
                        ref = _self_attr(arg)
                        if ref is not None:
                            token = ("attr", ref)
                        elif isinstance(arg, ast.Name):
                            token = local_tokens.get(arg.id)
                    if attr is not None:
                        if token is None:
                            token = ("attr", attr)
                        token_attrs.setdefault(token, set()).add(attr)
                        self.attr_types[attr] = (
                            "condition" if ctor in _COND_CTORS else "lock")
                    elif isinstance(tgt, ast.Name):
                        local_tokens[tgt.id] = ("local", id(fn), tgt.id)
                        token_attrs.setdefault(local_tokens[tgt.id], set())
                elif attr is not None:
                    if ctor in _QUEUE_CTORS:
                        self.attr_types[attr] = "queue"
                    elif ctor in _EVENT_CTORS:
                        self.attr_types[attr] = "event"
                    elif ctor in _THREAD_CTORS:
                        self.attr_types[attr] = "thread"
        for attrs in token_attrs.values():
            if not attrs:
                continue
            group = "|".join(sorted(attrs))
            for a in attrs:
                self.lock_groups[a] = group

    def group_of_expr(self, expr: ast.AST) -> str | None:
        attr = _self_attr(expr)
        if attr is not None:
            return self.lock_groups.get(attr)
        return None

    def held_groups(self, node: ast.AST) -> frozenset:
        """Lock groups lexically held at *node* (within its function)."""
        held = set()
        for p in _parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                break
            if isinstance(p, ast.With):
                for item in p.items:
                    g = self.group_of_expr(item.context_expr)
                    if g:
                        held.add(g)
        return frozenset(held)

    # -- threads -----------------------------------------------------
    def _collect_threads(self, imports) -> None:
        for base in self.node.bases:
            if imports.canon(base) in _THREAD_CTORS and \
                    "run" in self.methods:
                fn = self.methods["run"]
                self._entry_nodes.append((fn, fn))
                self.thread_sites.append((self.node, True, "run"))
        for mname, fn in self.methods.items():
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and imports.canon(node.func) in _THREAD_CTORS):
                    continue
                daemon = any(
                    kw.arg == "daemon"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in node.keywords)
                if not daemon:
                    daemon = self._daemon_via_attr(node, fn)
                self.thread_sites.append((node, daemon, mname))
                target = next((kw.value for kw in node.keywords
                               if kw.arg == "target"), None)
                if target is None:
                    continue
                tattr = _self_attr(target)
                if tattr is not None and tattr in self.methods:
                    self._entry_nodes.append((self.methods[tattr], fn))
                elif isinstance(target, ast.Name):
                    for sub in ast.walk(fn):
                        if isinstance(sub, ast.FunctionDef) and \
                                sub.name == target.id:
                            self._entry_nodes.append((sub, fn))
                            break

    @staticmethod
    def _daemon_via_attr(call: ast.Call, fn: ast.AST) -> bool:
        # `t = threading.Thread(...)` then `t.daemon = True`
        parent = getattr(call, "_gl_parent", None)
        if not (isinstance(parent, ast.Assign)
                and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)):
            return False
        var = parent.targets[0].id
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr == "daemon" and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == var and \
                        isinstance(node.value, ast.Constant) and \
                        node.value.value is True:
                    return True
        return False

    def _close_entries(self) -> None:
        # Transitive closure: from each thread entry, follow self.m()
        # calls (and sibling nested defs) -> those run thread-side too.
        work = list(self._entry_nodes)
        while work:
            fn, scope = work.pop()
            if id(fn) in self.entries:
                continue
            self.entries.add(id(fn))
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                attr = _self_attr(node.func)
                if attr is not None and attr in self.methods:
                    work.append((self.methods[attr], self.methods[attr]))
                elif isinstance(node.func, ast.Name):
                    for sub in ast.walk(scope):
                        if isinstance(sub, ast.FunctionDef) and \
                                sub.name == node.func.id and sub is not fn:
                            work.append((sub, scope))
                            break

    # -- shared-attribute sites --------------------------------------
    def _enclosing(self, node: ast.AST):
        """(nearest def, enclosing top-level method name) of *node*."""
        fn, method = None, None
        for p in _parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if fn is None:
                    fn = p
                if p.name in self.methods and self.methods[p.name] is p:
                    method = p.name
                    break
            elif isinstance(p, ast.ClassDef):
                break
        return fn, method

    def _collect_sites(self) -> None:
        init = self.methods.get("__init__")
        for node in ast.walk(self.node):
            attr, kind = None, None
            if isinstance(node, ast.AugAssign):
                attr, kind = _self_attr(node.target), "aug"
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    a = _self_attr(tgt)
                    if a is not None:
                        attr, kind = a, "assign"
                    elif isinstance(tgt, ast.Subscript):
                        a = _self_attr(tgt.value)
                        if a is not None:
                            attr, kind = a, "call"
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATOR_METHODS:
                a = _self_attr(node.func.value)
                if a is not None:
                    attr, kind = a, "call"
            if attr is None or attr in self.lock_groups:
                continue
            fn, method = self._enclosing(node)
            if fn is None:
                continue
            self.sites.setdefault(attr, []).append(_Site(
                node, kind, fn, method, self.held_groups(node),
                in_init=(init is not None and fn is init)))
        # public reads: Load of self.<attr> inside a public method
        for mname, fn in self.methods.items():
            if mname.startswith("_"):
                continue
            for node in ast.walk(fn):
                a = _self_attr(node)
                if a is None or a in self.lock_groups or \
                        not isinstance(node.ctx, ast.Load):
                    continue
                nfn, _ = self._enclosing(node)
                self.public_reads.setdefault(a, []).append(_Site(
                    node, "read", nfn, mname, self.held_groups(node),
                    in_init=False))

    # -- inference ---------------------------------------------------
    def guard_map(self) -> dict[str, str]:
        """attr -> lock-group name, for attrs whose non-__init__
        mutation sites are >= 90% under one lock group."""
        out = {}
        for attr, sites in sorted(self.sites.items()):
            live = [s for s in sites if not s.in_init]
            if not live:
                continue
            counts: dict[str, int] = {}
            for s in live:
                for g in s.held:
                    counts[g] = counts.get(g, 0) + 1
            if not counts:
                continue
            best = max(sorted(counts), key=lambda g: counts[g])
            if counts[best] / len(live) >= _GUARD_RATIO:
                out[attr] = best
        return out


def _models(tree: ast.AST, imports) -> list[ClassModel]:
    cached = getattr(tree, "_gl_conc_models", None)
    if cached is None:
        cached = [ClassModel(n, imports) for n in ast.walk(tree)
                  if isinstance(n, ast.ClassDef)]
        tree._gl_conc_models = cached  # type: ignore[attr-defined]
    return cached


def _module_locks(tree: ast.AST, imports) -> set[str]:
    """Names of module-level `X = threading.Lock()` style globals."""
    out = set()
    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call) and \
                imports.canon(node.value.func) in (_LOCK_CTORS |
                                                   _COND_CTORS):
            out.add(node.targets[0].id)
    return out


# ------------------------------------------------------------------ public

def guard_map(source: str) -> dict[str, dict[str, str]]:
    """{class name: {attr: guard lock group}} for *source* — the
    inference G025 runs on, exposed so tests can pin real classes."""
    from deeplearning4j_tpu.analysis.ast_rules import (Imports,
                                                       _walk_with_parents)
    tree = _walk_with_parents(ast.parse(source))
    imports = Imports(tree)
    return {m.name: m.guard_map() for m in _models(tree, imports)
            if m.guard_map()}


def guard_map_for_file(path: str) -> dict[str, dict[str, str]]:
    with open(path, encoding="utf-8") as fh:
        return guard_map(fh.read())


# ------------------------------------------------------------------ G025

def g025_shared_attribute_race(tree, imports, path):
    out = []
    for model in _models(tree, imports):
        guards = model.guard_map()
        threaded = bool(model.entries)
        for attr, sites in sorted(model.sites.items()):
            live = [s for s in sites if not s.in_init]
            if not live:
                continue
            guard = guards.get(attr)
            if guard is not None:
                for s in live:
                    if guard not in s.held:
                        out.append((
                            "G025", s.node,
                            f"{model.name}.{attr} is guarded by "
                            f"`{guard}` at every other mutation site, "
                            f"but this one mutates it without the lock",
                            f"wrap the access in `with self."
                            f"{guard.split('|')[0]}:`"))
                if threaded and any(s.kind == "aug" for s in live):
                    for r in model.public_reads.get(attr, []):
                        if guard not in r.held:
                            out.append((
                                "G025", r.node,
                                f"{model.name}.{attr} (guard "
                                f"`{guard}`) is read in public method "
                                f"{r.method}() without the lock — "
                                f"read-modify-write state must be read "
                                f"under its guard",
                                f"wrap the read in `with self."
                                f"{guard.split('|')[0]}:`"))
            elif threaded:
                tside = [s for s in live
                         if s.kind == "aug" and id(s.fn) in model.entries]
                readers = sorted({r.method for r in
                                  model.public_reads.get(attr, [])})
                writers = sorted({s.method for s in live
                                  if s.method and
                                  not s.method.startswith("_")})
                if tside and (readers or writers):
                    s = tside[0]
                    who = ", ".join(f"{m}()" for m in
                                    (readers or writers))
                    out.append((
                        "G025", s.node,
                        f"{model.name}.{attr} is mutated with `+=` on "
                        f"the worker thread and accessed from {who} "
                        f"with no common lock — read-modify-write on a "
                        f"bare attribute loses updates under "
                        f"concurrency",
                        "guard every access with one dedicated lock "
                        "(`with self._lock:`), as PagePool does for "
                        "its counters"))
    return out


# ------------------------------------------------------------------ G026

def _local_ctor_types(fn: ast.AST, imports) -> dict[str, str]:
    out = {}
    for node in _own_nodes(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            ctor = imports.canon(node.value.func)
            if ctor in _QUEUE_CTORS:
                out[node.targets[0].id] = "queue"
            elif ctor in _EVENT_CTORS:
                out[node.targets[0].id] = "event"
            elif ctor in _THREAD_CTORS:
                out[node.targets[0].id] = "thread"
            elif ctor in _COND_CTORS:
                out[node.targets[0].id] = "condition"
    return out


def _recv_type(expr, model, local_types) -> tuple[str | None, str | None]:
    """(kind, attr-or-var name) of a call receiver, best effort."""
    attr = _self_attr(expr)
    if attr is not None:
        if model is not None and attr in model.attr_types:
            return model.attr_types[attr], attr
        return None, attr
    if isinstance(expr, ast.Name):
        return local_types.get(expr.id), expr.id
    return None, None


def _callback_loop_attr(call: ast.Call) -> str | None:
    """Attr name when *call* invokes a loop variable drawn from
    `for cb in self.<sinks-ish>:` — dynamic fan-out under a lock."""
    if not isinstance(call.func, ast.Name):
        return None
    for p in _parents(call):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return None
        if isinstance(p, ast.For) and isinstance(p.target, ast.Name) \
                and p.target.id == call.func.id:
            attr = _self_attr(p.iter)
            if attr is not None and _SINKISH.search(attr):
                return attr
    return None


def g026_blocking_under_lock(tree, imports, path):
    if not _in_paths(path, _G026_PATHS):
        return []
    out = []
    mod_locks = _module_locks(tree, imports)
    by_class = {id(m.node): m for m in _models(tree, imports)}

    def enclosing_model(node):
        for p in _parents(node):
            if isinstance(p, ast.ClassDef):
                return by_class.get(id(p))
        return None

    for w in ast.walk(tree):
        if not isinstance(w, ast.With):
            continue
        model = enclosing_model(w)
        held = set()
        for item in w.items:
            if model is not None:
                g = model.group_of_expr(item.context_expr)
                if g:
                    held.add(g)
            if isinstance(item.context_expr, ast.Name) and \
                    item.context_expr.id in mod_locks:
                held.add(item.context_expr.id)
        if not held:
            continue
        lock_desc = "/".join(sorted(held))
        fn = None
        for p in _parents(w):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = p
                break
        local_types = _local_ctor_types(fn, imports) if fn else {}
        for body_stmt in w.body:
            if isinstance(body_stmt, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                continue  # defined under the lock, runs later
            for node in [body_stmt] + list(_own_nodes(body_stmt)):
                if not isinstance(node, ast.Call):
                    continue
                label = None
                canon = imports.canon(node.func)
                if canon in _BLOCKING_CALLS:
                    label = canon
                elif canon is not None and \
                        canon.endswith(".block_until_ready"):
                    label = "block_until_ready"
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("get", "put", "join", "wait",
                                           "wait_for"):
                    kind, name = _recv_type(node.func.value, model,
                                            local_types)
                    meth = node.func.attr
                    if kind == "queue" and meth in ("get", "put", "join"):
                        if not any(kw.arg == "block" and
                                   isinstance(kw.value, ast.Constant) and
                                   kw.value.value is False
                                   for kw in node.keywords):
                            label = f"{name}.{meth}"
                    elif kind == "thread" and meth == "join":
                        label = f"{name}.join"
                    elif kind == "condition" and meth in ("wait",
                                                          "wait_for"):
                        # waiting on the lock you hold is the one
                        # correct blocking-under-lock pattern
                        grp = (model.lock_groups.get(name)
                               if model else None)
                        if grp is None or grp not in held:
                            label = f"{name}.{meth}"
                    elif kind == "event" and meth == "wait":
                        label = f"{name}.wait"
                if label is None:
                    cb_attr = _callback_loop_attr(node)
                    if cb_attr is not None:
                        out.append((
                            "G026", node,
                            f"registered callbacks from "
                            f"`self.{cb_attr}` are invoked while "
                            f"holding `{lock_desc}` — a callback that "
                            f"blocks or re-acquires a lock stalls or "
                            f"deadlocks every thread contending for it",
                            "snapshot the callback list under the "
                            "lock, then invoke outside it (the "
                            "Recorder sink fan-out pattern)"))
                        continue
                if label is not None:
                    out.append((
                        "G026", node,
                        f"blocking call `{label}` while holding "
                        f"`{lock_desc}` — every thread contending for "
                        f"the lock stalls behind this wait on the "
                        f"request/decode path",
                        "move the blocking call outside the `with` "
                        "block, or use the non-blocking variant and "
                        "retry at the batch boundary"))
    return out


# ------------------------------------------------------------------ G027

def g027_wait_discipline(tree, imports, path):
    if not _in_paths(path, _G027_PATHS):
        return []
    out = []
    by_class = {id(m.node): m for m in _models(tree, imports)}

    def enclosing_model(node):
        for p in _parents(node):
            if isinstance(p, ast.ClassDef):
                return by_class.get(id(p))
        return None

    def in_while(node):
        for p in _parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return False
            if isinstance(p, ast.While):
                return True
        return False

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        canon = imports.canon(node.func)
        if canon == "time.sleep":
            if in_while(node):
                out.append((
                    "G027", node,
                    "bare time.sleep polling loop — burns a core "
                    "re-checking state and adds up to one full "
                    "interval of latency per item",
                    "block on the state change instead: "
                    "Condition.wait in a while-predicate loop, or "
                    "Event.wait(timeout) for stop-flag loops (the "
                    "Channel pattern)"))
            continue
        if not isinstance(node.func, ast.Attribute):
            continue
        model = enclosing_model(node)
        if model is None:
            continue
        attr = _self_attr(node.func.value)
        if attr is None or \
                model.attr_types.get(attr) != "condition":
            continue
        if node.func.attr == "wait" and not in_while(node):
            out.append((
                "G027", node,
                f"`{attr}.wait()` outside a while-predicate loop — "
                f"condition waits wake spuriously and on stale "
                f"notifies; the predicate must be re-checked",
                "wrap in `while not <predicate>: "
                f"self.{attr}.wait(...)` (or use wait_for)"))
        elif node.func.attr in ("notify", "notify_all"):
            grp = model.lock_groups.get(attr)
            if grp is not None and grp not in model.held_groups(node):
                out.append((
                    "G027", node,
                    f"`{attr}.{node.func.attr}()` without holding the "
                    f"owning lock — raises RuntimeError at runtime "
                    f"and races the waiter's predicate check",
                    f"notify inside `with self.{attr}:`"))
    return out


# ------------------------------------------------------------------ G028

def g028_thread_lifecycle(tree, imports, path):
    out = []
    for model in _models(tree, imports):
        if not model.thread_sites:
            continue
        has_join = any(
            isinstance(n, ast.Call) and
            isinstance(n.func, ast.Attribute) and n.func.attr == "join"
            for n in ast.walk(model.node))
        has_handle = has_join or any(
            m in _HANDLE_NAMES for m in model.methods)
        for call, daemon, mname in model.thread_sites:
            if not daemon and not has_join:
                out.append((
                    "G028", call,
                    f"{model.name}.{mname} starts a non-daemon thread "
                    f"and the class never join()s it — interpreter "
                    f"shutdown blocks forever on the live thread",
                    "join the thread on the shutdown path, or mark it "
                    "daemon AND give the class a stop/close handle"))
            elif daemon and not has_handle:
                out.append((
                    "G028", call,
                    f"{model.name}.{mname} starts a daemon thread but "
                    f"the class has no stop/close/drain/join handle — "
                    f"resources the thread holds (open files, "
                    f"reserved pages) are torn down mid-operation at "
                    f"exit",
                    "add a stop()/close() that signals the loop and "
                    "joins the thread (CheckpointWatcher pattern)"))
    return out


# ------------------------------------------------------------------ registry

CONC_RULES = [g025_shared_attribute_race, g026_blocking_under_lock,
              g027_wait_discipline, g028_thread_lifecycle]

CONC_RULE_IDS = frozenset({"G025", "G026", "G027", "G028"})

CONC_RULE_DOCS = {
    "G025": "shared-attribute race: an attribute `+=`-mutated on the "
            "thread side of a class and touched from public methods "
            "with no common lock; guards are inferred (>=90% of "
            "mutation sites under one `with self._lock:` group) and "
            "the stray sites are the findings",
    "G026": "blocking call (queue.get/put, Condition.wait, join, "
            "Event.wait, socket/HTTP, subprocess, sleep, jax device "
            "sync) or registered-callback fan-out inside a held-lock "
            "body on the serving//data//telemetry/ request paths",
    "G027": "wait/notify/sleep discipline in serving/ and data/: "
            "Condition.wait outside a while-predicate loop, notify "
            "without the owning lock, bare time.sleep polling loops "
            "(the r6 spin-loop class the Channel rewrite removed)",
    "G028": "thread-lifecycle discipline: non-daemon threads never "
            "joined on any shutdown path; daemon threads with no "
            "stop/drain/close handle for the resources they hold",
}
