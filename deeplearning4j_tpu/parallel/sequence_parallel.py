"""Sequence-parallel training — shard the TIME dimension over the mesh.

The reference's only long-sequence mechanisms are truncated BPTT and
masking (SURVEY.md §5 "long-context"); this is the TPU-era capability that
replaces them at scale: activations are sharded over a mesh axis along
time, attention runs as the ppermute ring (parallel/ring_attention.py), and
every shard holds params replicas that stay bit-identical because gradients
are pmean'd before the (deterministic) updater runs.

Usage:

    mesh = make_mesh({"seq": 8})
    net = transformer_lm(..., seq_parallel_axis="seq")   # conf-driven
    trainer = SequenceParallelTrainer(net, mesh)
    trainer.fit(iterator, epochs=3)

The model conf carries the axis name (SelfAttentionLayer/
PositionalEncodingLayer.seq_parallel_axis) so the layer impls know they run
inside shard_map: attention becomes the ring, positional encodings offset
by the shard's global position. Works combined with a 'data' axis
(batch × sequence 2-D mesh): pass data_axis="data".

Constraints: the global sequence length must divide the seq-axis size, no
padding masks (pad to full length), no attention dropout.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.datasets.api import DataSet, MultiDataSet


def make_sp_train_step(net, mesh: Mesh, seq_axis: str = "seq",
                       data_axis: Optional[str] = None,
                       model_axis: Optional[str] = None):
    """Jitted (params, opt_state, state, features, labels) -> (params,
    opt_state, state, loss) with time sharded over `seq_axis` (and batch
    over `data_axis` when given). Params/optimizer state are replicated
    over seq/data; grads are pmean'd over those axes so shards stay in
    lockstep. A `model_axis` composes as GSPMD-AUTO (the shard_map is
    manual over seq/data only): Megatron TP placements on the params
    propagate through the per-shard compute and XLA inserts the model
    psums — the same partial-manual composition the PP schedule uses."""
    from deeplearning4j_tpu.util.compat import shard_map

    axes = (seq_axis,) if data_axis is None else (data_axis, seq_axis)
    # [B, T] int tokens / [B, T] labels: batch over data, time over seq
    tok_spec = P(data_axis, seq_axis)
    repl = P()

    def local_step(params, opt_state, state, rng, x, y):
        # decorrelate dropout masks across shards: each shard folds its
        # mesh position into the step key (same key everywhere would apply
        # identical mask patterns to different token blocks)
        for ax in axes:
            rng = jax.random.fold_in(rng, lax.axis_index(ax))

        def loss_fn(p):
            batch = {"features": (x,), "labels": (y,)}
            loss, (new_state, _extras) = net._loss(p, state, rng, batch,
                                                   train=True)
            return loss, new_state

        (loss, new_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        # every shard's loss is a mean over its local tokens; shards are
        # equal-sized, so pmean of means == the global mean, and pmean'd
        # grads drive identical updates on every replica. Mutable layer
        # state computed from local shards (e.g. batchnorm running stats
        # over a shard's time block) is pmean'd too so the state leaving
        # the step is the global average, not one shard's view; integer
        # leaves (step counters) advance identically on every shard and
        # pass through untouched.
        def _avg_state(a):
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
                out = a
                for ax in axes:
                    out = lax.pmean(out, ax)
                return out
            return a

        new_state = jax.tree.map(_avg_state, new_state)
        for ax in axes:
            loss = lax.pmean(loss, ax)
        # gradient collectives route through the blessed site (G015);
        # per-axis tree pmean — the identical primitive sequence this
        # step always issued (frozen stage-3 signature unchanged)
        from deeplearning4j_tpu.parallel.overlap import reduce_gradients

        grads = reduce_gradients(grads, axes)
        updates, new_opt = net.tx.update(grads, opt_state, params)
        import optax

        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, new_state, loss

    fn = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(repl, repl, repl, repl, tok_spec, tok_spec),
        out_specs=(repl, repl, repl, repl),
        check_vma=False,
        axis_names=set(axes),  # model (if any) stays GSPMD-auto
    )
    return jax.jit(fn)


class SequenceParallelTrainer:
    """fit()-style wrapper (API symmetry with DataParallelTrainer): every
    DataSet batch is one SP step over the mesh."""

    def __init__(self, net, mesh: Mesh, seq_axis: str = "seq",
                 data_axis: Optional[str] = None):
        self.net = net
        self.mesh = mesh
        self.seq_axis = seq_axis
        self.data_axis = data_axis
        self._step = None

    def fit(self, iterator, epochs: int = 1):
        if self._step is None:
            self._step = make_sp_train_step(self.net, self.mesh,
                                            self.seq_axis, self.data_axis)
        net = self.net
        for _ in range(epochs):
            iterator.reset()
            for ds in iterator:
                if isinstance(ds, MultiDataSet):
                    x, y = ds.features[0], ds.labels[0]
                else:
                    x, y = ds.features, ds.labels
                net.params, net.opt_state, net.state, loss = self._step(
                    net.params, net.opt_state, net.state, net._next_rng(),
                    jnp.asarray(x), jnp.asarray(y))
                net.score_value = loss  # lazy host sync
                net.iteration_count += 1
                for lst in net.listeners:
                    lst.iteration_done(net, net.iteration_count)
        return net
