"""Expert parallelism (EP): MoE experts sharded over a mesh 'expert' axis.

Each device holds E/n_dev experts (the stacked expert tensors We1/be1/
We2/be2 are sharded on their leading expert axis); the router runs
replicated; every device computes the gate-weighted partial combine for
ITS experts over all tokens, and one psum over the axis produces the
exact dense-path result — gates are zero outside the top-k, so the
partial sums are disjoint. Compiler-friendly EP: no capacity factors, no
token dropping, no all-to-all; the collective rides ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from deeplearning4j_tpu.util.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn.layers.moe import moe_expert_outputs, moe_gates

_EXPERT_SHARDED = ("We1", "be1", "We2", "be2")


def shard_expert_params(params, mesh, axis: str = "expert"):
    """Place stacked expert tensors one-shard-per-device; router replicated."""
    out = {}
    for k, v in params.items():
        spec = P(axis) if k in _EXPERT_SHARDED else P()
        out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out


def expert_parallel_apply(params, x, *, mesh, top_k, activation="gelu",
                          axis: str = "expert"):
    """MoE forward with experts sharded over `axis`; exact dense parity."""
    shape = x.shape
    x2d = x.reshape(-1, shape[-1])
    E = params["We1"].shape[0]
    n_dev = mesh.shape[axis]
    if E % n_dev:
        raise ValueError(f"{E} experts not divisible over {n_dev} devices")

    def program(p, xt):
        gates = moe_gates(xt, p["Wg"], top_k)              # [N, E] replicated
        # this device's expert slice
        lo = jax.lax.axis_index(axis) * (E // n_dev)
        local_gates = jax.lax.dynamic_slice_in_dim(gates, lo, E // n_dev, 1)
        local = {k: p[k] for k in _EXPERT_SHARDED}
        outs = moe_expert_outputs(local, xt, activation)   # [N, E/n, O]
        partial = jnp.einsum("ne,neo->no", local_gates, outs)
        return jax.lax.psum(partial, axis)

    in_specs = ({k: (P(axis) if k in _EXPERT_SHARDED else P())
                 for k in params}, P())
    y = shard_map(program, mesh=mesh, in_specs=in_specs,
                  out_specs=P())(params, x2d)
    return y.reshape(*shape[:-1], y.shape[-1])
