"""Tensor parallelism — Megatron-style sharding rules applied as pjit
shardings on the param pytree (new capability, SURVEY.md §2.4: the
reference has no TP; this is additive for the Transformer north star).

The pjit idiom: place params with NamedShardings, jit the (unchanged) train
step, and XLA SPMD propagates shardings through the computation, inserting
the allreduces where the contracted dimension is sharded — column-parallel
QKV/FF1 followed by row-parallel Out/FF2 yields exactly one psum per block
per direction, riding ICI.
"""

from __future__ import annotations

import re

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# {param-name regex -> PartitionSpec} for the transformer_lm param tree.
# Column-parallel: hidden/output dim sharded; row-parallel: input dim sharded.
# Specs are written against the ROLE name "model"; resolve_rules() renames
# them to the caller's actual mesh axis.
TRANSFORMER_TP_RULES = [
    (r".*_attn/Wqkv$", P(None, "model")),   # column: heads sharded
    (r".*_attn/bqkv$", P("model")),
    (r".*_attn/Wo$", P("model", None)),     # row: contraction sharded → psum
    (r".*_attn/bo$", P()),
    (r".*_ff1/W$", P(None, "model")),       # column
    (r".*_ff1/b$", P("model")),
    (r".*_ff2/W$", P("model", None)),       # row
    (r".*_ff2/b$", P()),
    (r"embed/W$", P(None, "model")),        # vocab embedding sharded on d_model
    (r"out/W$", P(None, "model")),          # lm head vocab-sharded on output
    (r"out/b$", P("model")),
]

# Expert parallelism as placement rules (role axis "expert"): the stacked
# expert tensors of nn/layers/moe.py shard their leading E dim; the router
# Wg stays replicated. GSPMD shards the all-experts einsum over E and
# inserts the psum for the gate-weighted combine — the same math the
# manual shard_map in expert_parallel.py proves exact, now differentiable
# and composable with data/model axes in one jitted train step.
MOE_EP_RULES = [
    (r".*/We1$", P("expert", None, None)),
    (r".*/be1$", P("expert", None)),
    (r".*/We2$", P("expert", None, None)),
    (r".*/be2$", P("expert", None)),
]

_ROLE_RULES = {"model": TRANSFORMER_TP_RULES, "expert": MOE_EP_RULES}


def _rename_spec(spec: P, mapping: dict) -> P:
    return P(*(mapping.get(ax, ax) if isinstance(ax, str) else ax
               for ax in spec))


def resolve_rules(axes: dict, custom_rules=None):
    """Build the active placement rule list for a role->mesh-axis mapping
    (e.g. {"data": "data", "model": "mdl", "expert": "expert"}). Role rule
    sets activate when their role is present; specs are renamed to the
    mapped mesh axis names. custom_rules (role-named) take precedence."""
    mapping = {role: ax for role, ax in axes.items() if isinstance(ax, str)}
    rules = []
    for pat, spec in (custom_rules or []):
        rules.append((pat, _rename_spec(spec, mapping)))
    for role in ("model", "expert"):
        if role in axes:
            for pat, spec in _ROLE_RULES[role]:
                rules.append((pat, _rename_spec(spec, mapping)))
    return rules


def _flatten_names(params, prefix=""):
    out = {}
    for k, v in params.items():
        name = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_names(v, name + "/"))
        else:
            out[name] = v
    return out


def sharding_for(name: str, mesh: Mesh, rules=None) -> NamedSharding:
    """Resolve the sharding for one param name (replicated if no rule
    matches or a rule references a mesh axis that is absent/size-1)."""
    rules = rules if rules is not None else TRANSFORMER_TP_RULES
    for pat, spec in rules:
        if re.match(pat, name):
            if all(ax in mesh.axis_names and mesh.shape[ax] > 1
                   for ax in spec if isinstance(ax, str)):
                return NamedSharding(mesh, spec)
            break
    return NamedSharding(mesh, P())


def shard_params(params, mesh: Mesh, rules=None):
    """device_put every param with its rule's sharding. Returns the same
    pytree, now laid out for TP; jit of the train step with these as inputs
    lets XLA propagate and insert the collectives."""
    def place(path_name, leaf):
        return jax.device_put(leaf, sharding_for(path_name, mesh, rules))

    def walk(tree, prefix=""):
        out = {}
        for k, v in tree.items():
            name = f"{prefix}{k}"
            if isinstance(v, dict):
                out[k] = walk(v, name + "/")
            else:
                out[k] = place(name, v)
        return out

    return walk(params)


def param_shardings(params, mesh: Mesh, rules=None):
    """Pytree of NamedShardings matching `params` (for jit in_shardings)."""
    def walk(tree, prefix=""):
        out = {}
        for k, v in tree.items():
            name = f"{prefix}{k}"
            if isinstance(v, dict):
                out[k] = walk(v, name + "/")
            else:
                out[k] = sharding_for(name, mesh, rules)
        return out

    return walk(params)
