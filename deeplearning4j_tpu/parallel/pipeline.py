"""Container-integrated pipeline parallelism (PP).

Builds pipeline stages from the REAL network conf — the builder-API
ComputationGraph (reference ComputationGraphConfiguration.GraphBuilder,
nn/conf/ComputationGraphConfiguration.java:446) — instead of requiring a
hand-stacked homogeneous stage_fn (the retired r2 demo
`pipeline_parallel.py` — its schedule ideas live in the scan body below;
ARCHITECTURE.md §The five parallel axes has the history):

- **Partitioning**: the DAG's topological order is scanned for single-value
  cuts (positions where exactly one activation is live); the longest run of
  structurally identical cut-to-cut segments (fingerprinted on vertex
  types, configs, wiring, and param shapes) becomes the pipelined body —
  e.g. the n_layers pre-norm transformer blocks. Everything before the run
  (embedding, positional encoding) is the heterogeneous PRE segment;
  everything after (final LN, LM head + loss) is the POST segment.

- **Schedule**: a GPipe microbatch schedule as one `lax.scan` of per-tick
  stage compute inside a `shard_map` that is MANUAL over the 'pipe' mesh
  axis ONLY (`axis_names={pipe}`): 'data' and 'model'/'expert' axes stay
  AUTO, so batch sharding and Megatron TP / MoE EP placements propagate
  through the per-stage compute via GSPMD — dp x tp x pp composes inside
  ONE jitted train step, with XLA inserting the collectives.

- **Heterogeneous ends without SPMD waste**: the PRE segment runs
  replicated-over-pipe at each injection tick (an embedding gather —
  negligible FLOPs); the POST segment + loss runs ONCE per microbatch,
  balanced round-robin across pipe devices via a second "done lane" ring:
  the last stage injects finished activations into the lane, each device
  captures the microbatches assigned to it (j % S == device), and computes
  the head loss for its share after the scan. Head FLOPs are never
  duplicated per stage, and no device stores more than M/S microbatches of
  final activations (the r2 review's full-batch-memory critique).

- **Memory layout**: stage parameters live STACKED on a leading [S] axis
  sharded over 'pipe' (each device holds one stage's blocks), composed
  with the TP/EP dim rules on the remaining axes. The token/label
  microbatch stream is replicated over pipe — int32 tokens are ~d_model x
  smaller than activations, so only activations ride the rings.

Differentiability is free: `ppermute`/`scan`/`dynamic_update_slice` all
have transpose rules, so `jax.grad` of the scheduled loss yields the
reverse (backward) pipeline schedule automatically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.util.compat import pcast_varying, shard_map
from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertexConf
from deeplearning4j_tpu.nn.conf.layers import BaseOutputLayer
from deeplearning4j_tpu.nn.layers import l1_l2_penalty


def _chain_cuts(conf):
    """Positions in topo order after which exactly ONE activation is live
    (single-edge cuts of the DAG — valid pipeline stage boundaries)."""
    topo = [n for n in conf.topological_order()
            if n not in conf.network_inputs]
    pos = {n: i for i, n in enumerate(topo)}
    INF = len(topo) + 1
    # last position consuming each value; network outputs live to the end
    last_use = {}
    for n in topo:
        for src in conf.vertex_inputs[n]:
            last_use[src] = max(last_use.get(src, -1), pos[n])
    for out in conf.network_outputs:
        last_use[out] = INF
    cuts = []
    for i, n in enumerate(topo):
        live = [v for v in topo[:i + 1] if last_use.get(v, -1) > i]
        live += [v for v in conf.network_inputs if last_use.get(v, -1) > i]
        if live == [n]:
            cuts.append(i)
    return topo, cuts


def _conf_repr(obj):
    """Structural repr of a (possibly nested) vertex/layer config with
    identity fields ('name') stripped — two blocks differing only in layer
    names must fingerprint equal."""
    import dataclasses

    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        fields = ", ".join(
            f"{f.name}={_conf_repr(getattr(obj, f.name))}"
            for f in dataclasses.fields(obj) if f.name != "name")
        return f"{type(obj).__name__}({fields})"
    return repr(obj)


def _fingerprint(conf, params, seg, ext):
    """Structural identity of one cut-to-cut segment: vertex kinds +
    configs + segment-local wiring + param leaf shapes/dtypes. Segments
    with equal fingerprints can be stacked into pipeline stages."""
    pos = {n: j for j, n in enumerate(seg)}
    entries = []
    for n in seg:
        v = conf.vertices[n]
        wires = tuple(("ext",) if i == ext else ("local", pos[i])
                      for i in conf.vertex_inputs[n])
        p = params.get(n, {})
        shapes = tuple(
            (tuple(leaf.shape), str(leaf.dtype))
            for leaf in jax.tree.leaves(p))
        entries.append((type(v).__name__, _conf_repr(v), wires, shapes))
    return tuple(entries)


def _longest_periodic_run(fps):
    """Find (lo, n_units, period): the maximal-coverage run of consecutive
    REPEAT UNITS of `period` segments each with identical per-unit
    fingerprints (a transformer block may span several single-value cuts —
    e.g. an attention half and an FF half)."""
    n = len(fps)
    best = (0, 1, 1)  # lo, units, period
    for p in range(1, n // 2 + 1):
        for lo in range(0, n - p + 1):
            unit = tuple(fps[lo:lo + p])
            c = 1
            while (lo + (c + 1) * p <= n
                   and tuple(fps[lo + c * p:lo + (c + 1) * p]) == unit):
                c += 1
            if c > 1 and c * p > best[1] * best[2]:
                best = (lo, c, p)
    return best


class PipelinePlan:
    """Partition of a ComputationGraph into pre / S stages / post, with the
    param-tree restructuring between the canonical per-layer layout and the
    pipelined {pre, stages(stacked leaves), post} layout."""

    def __init__(self, net, n_stages: int):
        conf = net.conf
        if len(conf.network_inputs) != 1 or len(conf.network_outputs) != 1:
            raise ValueError(
                "pipeline parallelism supports single-input single-output "
                f"graphs; got {len(conf.network_inputs)} inputs / "
                f"{len(conf.network_outputs)} outputs")
        self.net = net
        self.S = n_stages
        self.input_name = conf.network_inputs[0]
        out_name = conf.network_outputs[0]
        out_v = conf.vertices[out_name]
        if not (isinstance(out_v, LayerVertexConf)
                and isinstance(out_v.layer, BaseOutputLayer)):
            raise ValueError("pipeline parallelism needs an output layer "
                             "as the single network output")
        if net.params is None:
            net.init()

        topo, cuts = _chain_cuts(conf)
        if not cuts:
            raise ValueError("graph has no single-activation cut points — "
                             "cannot partition into pipeline stages")
        # segments between consecutive cuts; segment i spans
        # (cuts[i-1], cuts[i]]; a leading segment before the first cut
        bounds = [-1] + cuts
        segs = [topo[bounds[i] + 1:bounds[i + 1] + 1]
                for i in range(len(bounds) - 1)]
        if bounds[-1] != len(topo) - 1:
            segs.append(topo[bounds[-1] + 1:])
        ext_of = [self.input_name] + [s[-1] for s in segs[:-1]]
        fps = [_fingerprint(conf, net.params, s, e)
               for s, e in zip(segs, ext_of)]
        # longest periodic run of identical repeat units = pipelined body
        lo, units, period = _longest_periodic_run(fps)
        if units % n_stages:
            raise ValueError(
                f"the {units} repeated blocks do not divide into "
                f"{n_stages} pipeline stages. The GPipe schedule runs one "
                "stage program over params stacked on a [S] axis, so the "
                "pipelined body must be a run of structurally IDENTICAL "
                "blocks (uniform transformer blocks qualify; VGG/ResNet-"
                "style conv stacks whose channel widths grow between "
                "stages do not — their per-stage compute differs, which "
                "would need a heterogeneous-stage schedule; shard those "
                "over the data axis instead)")
        per_stage = units // n_stages
        hi = lo + units * period
        body_segs = segs[lo:hi]
        seg_per_stage = per_stage * period
        self.stage_groups = [
            sum(body_segs[g * seg_per_stage:(g + 1) * seg_per_stage], [])
            for g in range(n_stages)]
        self.pre_names = sum(segs[:lo], [])
        post = sum(segs[hi:], [])
        if post and post[-1] == out_name:
            post = post[:-1]  # the loss layer runs via post_loss, not here
        self.post_names = post
        self.out_name = out_name
        self.out_vconf = out_v

        # external input value feeding each region
        self.pre_ext = self.input_name
        self.body_ext = (segs[lo - 1][-1] if lo > 0
                         else self.input_name)
        self.post_ext = body_segs[-1][-1] if body_segs else self.body_ext
        # consistency: the value feeding the loss layer
        loss_in = conf.vertex_inputs[out_name][0]
        self.loss_ext = loss_in

        self._steps_pre = self._build_steps(self.pre_names, self.pre_ext)
        self._steps_stage = self._build_steps(self.stage_groups[0],
                                              self.body_ext)
        self._steps_post = self._build_steps(self.post_names, self.post_ext)

        # per-layer (name, treedef, n_leaves) template for stage stacking,
        # in TOPO order within the group (stable across groups, unlike
        # lexicographic sort — 'blk10' < 'blk9' would misalign leaves)
        self.group_layers = [
            [n for n in g if isinstance(conf.vertices[n], LayerVertexConf)]
            for g in self.stage_groups]
        self.stage_template = self._make_template(net.params)
        self.pre_layers = [n for n in self.pre_names
                           if isinstance(conf.vertices[n], LayerVertexConf)]
        self.post_layers = [n for n in self.post_names
                            if isinstance(conf.vertices[n], LayerVertexConf)
                            ] + [out_name]
        # mutable layer state (BatchNorm running stats) threads the same
        # pipelined layout as params: per-stage state rides the tick scan
        # carry, updated only on real-microbatch ticks
        self.state_template = self._make_template(net.state, default={})
        self.has_state = bool(jax.tree.leaves(net.state))

        # leaf paths for TP/EP rule matching on stacked leaves, named by
        # the template (group-0) layer names
        self.stage_leaf_names = []
        for name, _, _ in self.stage_template:
            flat = jax.tree_util.tree_flatten_with_path(
                net.params[name])[0]
            for path, _leaf in flat:
                suffix = "/".join(str(getattr(k, "key", k)) for k in path)
                self.stage_leaf_names.append(f"{name}/{suffix}")

    # ------------------------------------------------------------ executors
    def _build_steps(self, names, ext_value):
        conf = self.net.conf
        pos = {n: j for j, n in enumerate(names)}
        steps = []
        for n in names:
            v = conf.vertices[n]
            refs = tuple(("ext", None) if i == ext_value else ("local", pos[i])
                         for i in conf.vertex_inputs[n])
            steps.append((n, v, refs))
        return steps

    def _make_template(self, tree, default=None):
        """Per-layer (name, treedef, n_leaves) stacking template for any
        per-layer-keyed pytree sharing the params' layer names."""
        tmpl = []
        for name in self.group_layers[0]:
            sub = tree[name] if default is None else tree.get(name, default)
            leaves, treedef = jax.tree.flatten(sub)
            tmpl.append((name, treedef, len(leaves)))
        return tmpl

    def _apply_steps(self, steps, params, state, x, *, train, rng,
                     mask=None):
        """Run a region's vertices on one activation. Returns (final
        activation, new_state). params/state: {template_layer_name:
        subtree}; `mask` is the [B, T] features mask threaded to every
        layer apply (the non-PP _forward contract)."""
        net = self.net
        cdtype = net.compute_dtype
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            x = jnp.asarray(x, cdtype)
        acts = {}
        new_state = {}
        keys = (jax.random.split(rng, max(len(steps), 1))
                if rng is not None else [None] * len(steps))
        out = x
        for (n, v, refs), k in zip(steps, keys):
            ins = [x if r[0] == "ext" else acts[steps[r[1]][0]] for r in refs]
            if isinstance(v, LayerVertexConf):
                xi = ins[0]
                if v.preprocessor is not None:
                    xi = v.preprocessor.pre_process(xi)
                p = params.get(n, {})
                if cdtype != net.param_dtype:
                    p = jax.tree.map(
                        lambda a: a.astype(cdtype)
                        if jnp.issubdtype(a.dtype, jnp.floating) else a, p)
                y, s = net.impls[n].apply(
                    v.layer, p, state.get(n, {}), xi, train=train, rng=k,
                    mask=mask)
                new_state[n] = s
            else:
                y = net._vertex_forward(n, v, ins, params, {}, train, k,
                                        {}, acts)
            acts[n] = y
            out = y
        return out, new_state

    def pre_apply(self, pre_params, pre_state, x, *, train, rng, mask=None):
        if not self._steps_pre:
            x = jnp.asarray(x, self.net.compute_dtype) \
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x
            return x, dict(pre_state)
        return self._apply_steps(self._steps_pre, pre_params, pre_state, x,
                                 train=train, rng=rng, mask=mask)

    def stage_apply(self, stage_params, stage_state, x, *, train, rng,
                    mask=None):
        return self._apply_steps(self._steps_stage, stage_params,
                                 stage_state, x, train=train, rng=rng,
                                 mask=mask)

    def post_loss(self, post_params, post_state, h, labels, *, train, rng,
                  mask=None, feat_mask=None):
        """POST region + output-layer loss for a batch of finished
        activations. Returns (loss, new_post_state)."""
        net = self.net
        new_state = dict(post_state)
        if self._steps_post:
            h, new_state = self._apply_steps(
                self._steps_post, post_params, post_state, h, train=train,
                rng=rng, mask=feat_mask)
        v = self.out_vconf
        if v.preprocessor is not None:
            h = v.preprocessor.pre_process(h)
        # same compute-dtype policy as the non-PP loss path: the head
        # weight must not stream through the loss kernels in f32 for a
        # bf16 model
        p_out = post_params[self.out_name]
        if net.compute_dtype != net.param_dtype:
            from deeplearning4j_tpu.nn.training import tree_cast

            p_out = tree_cast(p_out, net.compute_dtype)
        loss = net.impls[self.out_name].loss(
            v.layer, p_out, h, labels, train=train, rng=rng, mask=mask)
        new_state.setdefault(self.out_name, post_state.get(self.out_name, {}))
        return loss, new_state

    # ----------------------------------------------------- tree restructure
    def _stage_local(self, tmpl, stacked, g=None):
        tree = {}
        i = 0
        for name, treedef, n in tmpl:
            leaves = [stacked[i + j] if g is None else stacked[i + j][g]
                      for j in range(n)]
            tree[name] = jax.tree.unflatten(treedef, leaves)
            i += n
        return tree

    def stage_local(self, stacked, g=None):
        """Rebuild {template_name: subtree} from a tuple of stacked leaves.
        g=None: leaves already have the stage axis stripped (inside
        shard_map); integer g: take stage g's slice (tracing-safe)."""
        return self._stage_local(self.stage_template, stacked, g)

    def stage_local_state(self, stacked, g=None):
        return self._stage_local(self.state_template, stacked, g)

    def _to_pipelined(self, tree, default=None):
        def get(n):
            return tree[n] if default is None else tree.get(n, default)

        pre = {n: get(n) for n in self.pre_layers}
        post = {n: get(n) for n in self.post_layers}
        per_group = []
        for g in self.group_layers:
            per_group.append([leaf for name in g
                              for leaf in jax.tree.leaves(get(name))])
        stages = tuple(jnp.stack([per_group[g][i]
                                  for g in range(self.S)])
                       for i in range(len(per_group[0])))
        return {"pre": pre, "stages": stages, "post": post}

    def _to_canonical(self, pp, tmpl):
        tree = {}
        tree.update(pp["pre"])
        tree.update(pp["post"])
        for g, names in enumerate(self.group_layers):
            local = self._stage_local(tmpl, pp["stages"], g=g)
            for tmpl_name, name in zip(self.group_layers[0], names):
                tree[name] = local[tmpl_name]
        return tree

    def to_pipelined(self, params):
        return self._to_pipelined(params)

    def to_canonical(self, pp):
        return self._to_canonical(pp, self.stage_template)

    def to_pipelined_state(self, state):
        return self._to_pipelined(state, default={})

    def to_canonical_state(self, pp_state, full_state=None):
        """Canonical per-layer state from the pipelined layout; layers
        outside the plan's regions (none today) fall back to full_state."""
        out = dict(full_state or {})
        out.update(self._to_canonical(pp_state, self.state_template))
        return out

    # --------------------------------------------------------- param place
    def placements(self, mesh: Mesh, axes: dict, rules):
        """Pipelined-tree pytree of NamedShardings: stacked stage leaves
        shard their leading [S] dim over the pipe axis composed with the
        TP/EP dim rules; pre/post follow the rules, replicated over pipe."""
        from deeplearning4j_tpu.parallel.tensor_parallel import sharding_for

        pipe = axes["pipe"]

        def leaf_spec(name):
            base = sharding_for(name, mesh, rules).spec
            return NamedSharding(mesh, P(pipe, *base))

        stage_sh = tuple(leaf_spec(n) for n in self.stage_leaf_names)

        def place_named(subtree, prefix):
            flat, treedef = jax.tree_util.tree_flatten_with_path(subtree)
            shs = []
            for path, _leaf in flat:
                suffix = "/".join(str(getattr(k, "key", k)) for k in path)
                shs.append(sharding_for(f"{prefix}{suffix}", mesh, rules))
            return jax.tree.unflatten(treedef, shs)

        src = self.net.params
        if isinstance(src, dict) and "stages" in src:
            src = self.to_canonical(src)
        pre_sh = {n: place_named(src[n], f"{n}/") for n in self.pre_layers}
        post_sh = {n: place_named(src[n], f"{n}/") for n in self.post_layers}
        return {"pre": pre_sh, "stages": stage_sh, "post": post_sh}


def check_pp_supported(net):
    """Configuration modes the PP step cannot honor raise up front."""
    from deeplearning4j_tpu.nn.conf.enums import (
        BackpropType,
        GradientNormalization,
        OptimizationAlgorithm,
    )

    g = net.conf.conf
    if str(g.optimization_algo) != str(
            OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT):
        raise ValueError("pipeline parallelism supports SGD-family "
                         "training only (no second-order solvers)")
    if str(net.conf.backprop_type) in (str(BackpropType.TRUNCATED_BPTT),
                                       "truncated_bptt"):
        raise ValueError("pipeline parallelism does not support TBPTT")
    for name, v in net.layer_vertices.items():
        lc = v.layer
        gn = getattr(lc, "gradient_normalization", None)
        if gn not in (None, GradientNormalization.NONE, "none"):
            raise ValueError(
                f"per-layer gradient normalization on '{name}' is not "
                "supported under pipeline parallelism")
        if (getattr(lc, "updater", None) not in (None, g.updater)
                or getattr(lc, "learning_rate", None) is not None):
            raise ValueError(
                f"per-layer updater/learning-rate override on '{name}' is "
                "not supported under pipeline parallelism (the optimizer "
                "runs on the stacked stage tree)")


def make_pp_train_step(net, plan: PipelinePlan, mesh: Mesh, axes: dict,
                       n_microbatches: int, rules):
    """Jitted train step over the pipelined param tree, standard container
    contract: step(pp_params, opt_state, state, rng, batch) ->
    (pp_params, opt_state, new_state, loss, {}).

    batch: {"features": (tokens [B, ...],), "labels": (labels [B, ...],)}
    with B divisible into n_microbatches x (data-axis multiple). [B, T]
    feature/label masks ride the (replicated) microbatch stream: the
    features mask reaches every stage's layer apply for its current
    microbatch, the labels mask reaches the head loss. Mutable layer state
    (BatchNorm running stats) threads the tick scan per stage, updated
    only on real-microbatch ticks; MoE router aux losses are accumulated
    across stages/microbatches and added to the training loss.
    """
    import optax

    from deeplearning4j_tpu.nn.layers.base import pop_aux_losses

    pipe = axes["pipe"]
    data = axes.get("data")
    seq = axes.get("seq")
    S, M = plan.S, n_microbatches
    if M % S:
        raise ValueError(f"{M} microbatches do not divide over {S} stages")
    k_slots = M // S
    T_total = M + 2 * S - 2
    ring = [(i, (i + 1) % S) for i in range(S)]
    # the data axis runs MANUAL alongside pipe (model/expert stay auto):
    # GSPMD's subgroup partitioner CHECK-fails composing an auto data
    # axis with expert-sharded stage leaves inside a manual-pipe region
    # (spmd_partitioner_util.cc:495 on a data x pipe x expert mesh), and
    # manual data costs nothing — the batch is embarrassingly parallel
    # and the loss/state combines below psum/pmean over both axes.
    # 'seq' rides the same mechanism as 'data': an embarrassingly-
    # parallel content axis run manual alongside pipe. Its shards hold
    # time blocks instead of batch rows — the SP-configured layers'
    # ring collectives (ring attention, offset posenc) bind against it
    # inside the stage bodies, and the loss/state combines below treat
    # it exactly like a second data axis (equal shards; the masked-mean
    # weights already make the combine exact for unequal valid counts).
    manual = ({pipe} | ({data} if data is not None else set())
              | ({seq} if seq is not None else set()))
    extra = tuple(a for a in (data, seq) if a is not None)
    dax = (pipe,) + extra
    d_only = extra

    def _pmean_floats(tree, ax):
        if not ax:
            return tree
        return jax.tree.map(
            lambda a: (lax.pmean(a, ax)
                       if jnp.issubdtype(a.dtype, jnp.floating) else a),
            tree)

    def _local_shard(arr_m, idx):
        """Device idx's share of a [M, mb, ...] stream: microbatches
        j = s*S + idx, flattened to [k_slots*mb, ...]."""
        r = arr_m.reshape((k_slots, S) + arr_m.shape[1:])
        local = lax.dynamic_index_in_dim(jnp.moveaxis(r, 1, 0), idx, 0,
                                         False)
        return local.reshape((k_slots * arr_m.shape[1],) + arr_m.shape[2:])

    def make_program(has_f, has_l):
        def program(pre_p, stages_p, post_p, stages_s, pre_s, post_s,
                    toks, labs, fm, lm, key):
            if seq is not None:
                # decorrelate dropout streams across time shards (the SP
                # step does the same): one key would mask identical
                # positions in every shard's local block
                key = jax.random.fold_in(key, lax.axis_index(seq))
            # local stage slice: shard_map strips the leading [S] axis to 1
            stage_p = plan.stage_local(tuple(a[0] for a in stages_p))
            stage_s0 = plan.stage_local_state(
                tuple(a[0] for a in stages_s))
            idx = lax.axis_index(pipe)
            u = (idx + 1) % S  # done-lane hops from the last stage to here

            probe, _ = plan.pre_apply(
                pre_p, pre_s, toks[0], train=True,
                rng=jax.random.fold_in(key, 0),
                mask=(fm[0] if has_f else None))
            zero = jnp.zeros_like(probe)

            def tick(carry, t):
                (inflight, done_lane, store, st_stage, st_pre,
                 aux_stage, aux_pre) = carry
                kt = jax.random.fold_in(key, t)
                # stage 0 injects microbatch t while t < M (the PRE
                # segment is an embedding-scale gather — computing it
                # replicated over pipe is far cheaper than ringing the
                # token stream)
                inject = jnp.where(t < M, t, 0)
                fm_in = (lax.dynamic_index_in_dim(fm, inject, 0, False)
                         if has_f else None)
                x0, pre_new = plan.pre_apply(
                    pre_p, st_pre,
                    lax.dynamic_index_in_dim(toks, inject, 0, False),
                    train=True, rng=jax.random.fold_in(kt, S), mask=fm_in)
                aux0, pre_new = pop_aux_losses(pre_new)
                real_pre = t < M
                st_pre = jax.tree.map(
                    lambda a, b: jnp.where(real_pre, a, b), pre_new, st_pre)
                aux_pre = aux_pre + jnp.where(real_pre, aux0, 0.0)
                x_in = jnp.where(idx == 0,
                                 jnp.where(t < M, x0, zero), inflight)
                # this device's stage processes microbatch t - idx
                jb = t - idx
                real = (jb >= 0) & (jb < M)
                fm_b = (lax.dynamic_index_in_dim(
                    fm, jnp.clip(jb, 0, M - 1), 0, False)
                    if has_f else None)
                y, st_new = plan.stage_apply(
                    stage_p, st_stage, x_in, train=True,
                    rng=jax.random.fold_in(kt, idx), mask=fm_b)
                auxb, st_new = pop_aux_losses(st_new)
                st_stage = jax.tree.map(
                    lambda a, b: jnp.where(real, a, b), st_new, st_stage)
                aux_stage = aux_stage + jnp.where(real, auxb, 0.0)
                # done lane: last stage injects its finished microbatch;
                # each device captures the ones assigned to it (j%S == idx)
                done_in = jnp.where(idx == S - 1, y, done_lane)
                j = t - (S - 1) - u
                cap = (j % S == idx) & (j >= 0) & (j < M)
                slot = jnp.clip(j // S, 0, k_slots - 1)
                store = jnp.where(cap, store.at[slot].set(done_in), store)
                done_lane = lax.ppermute(done_in, pipe, ring)
                inflight = lax.ppermute(y, pipe, ring)
                return (inflight, done_lane, store, st_stage, st_pre,
                        aux_stage, aux_pre), None

            store0 = jnp.zeros((k_slots,) + probe.shape, probe.dtype)
            carry0 = jax.tree.map(
                lambda a: pcast_varying(a, (pipe,)),
                (zero, zero, store0, stage_s0, pre_s,
                 jnp.zeros(()), jnp.zeros(())))
            (_, _, store, st_stage, st_pre, aux_stage, aux_pre), _ = (
                lax.scan(tick, carry0, jnp.arange(T_total)))

            # POST + loss once per microbatch, balanced over pipe devices:
            # device d holds microbatches j = s*S + d in slots s
            h = store.reshape((k_slots * toks.shape[1],) + store.shape[2:])
            labs_local = _local_shard(labs, idx)
            lm_local = _local_shard(lm, idx) if has_l else None
            fm_local = _local_shard(fm, idx) if has_f else None
            local, post_new = plan.post_loss(
                post_p, post_s, h, labs_local, train=True,
                rng=jax.random.fold_in(key, T_total), mask=lm_local,
                feat_mask=fm_local)
            auxp, post_new = pop_aux_losses(post_new)
            # post/pre/stage state shards differ per device (disjoint
            # microbatch/data shards) — pmean is the EMA combine;
            # non-float leaves keep the local copy (update counters,
            # identical across devices)
            post_new = _pmean_floats(post_new, dax)
            st_pre = _pmean_floats(st_pre, d_only)
            st_stage = _pmean_floats(st_stage, d_only)
            # equal shard sizes: global mean = pmean of local means. With a
            # labels mask the local losses are masked means (sum/valid), so
            # the exact global combine weights each shard by its valid
            # count: psum(local*w)/psum(w) == sum(per*m)/sum(m) over all.
            if has_l:
                w = jnp.maximum(jnp.sum(lm_local.astype(jnp.float32)), 1.0)
                data_loss = lax.psum(local * w, dax) / lax.psum(w, dax)
            else:
                data_loss = lax.pmean(local, dax)
            # aux accounting: each microbatch visits every stage device
            # once -> psum over pipe / M is the per-batch mean aux summed
            # over all blocks (then averaged over data shards); the
            # replicated-over-pipe PRE contributes via pmean. POST runs
            # ONCE per device over its k_slots-microbatch shard, so its
            # per-shard aux values combine as a pmean over pipe — /M
            # would underweight them by k_slots.
            aux_total = (lax.psum(aux_stage, pipe) / M
                         + lax.pmean(aux_pre, pipe) / M
                         + lax.pmean(auxp, pipe))
            if d_only:
                aux_total = lax.pmean(aux_total, d_only)
            loss = data_loss + aux_total
            # re-stack the local stage state with its [1] pipe axis for
            # the P(pipe) out_spec
            flat_stage_state = []
            for name, treedef, n in plan.state_template:
                flat_stage_state.extend(
                    jax.tree.leaves(st_stage[name]))
            st_stage_out = tuple(a[None] for a in flat_stage_state)
            return loss, st_stage_out, st_pre, post_new

        return program

    def run_sm(pp, pp_state, rng, toks_m, labs_m, fm_m, lm_m):
        has_f, has_l = fm_m is not None, lm_m is not None
        program = make_program(has_f, has_l)
        operands = (pp["pre"], pp["stages"], pp["post"],
                    pp_state["stages"], pp_state["pre"], pp_state["post"],
                    toks_m, labs_m,
                    fm_m if has_f else (), lm_m if has_l else (), rng)
        # stream leaves are [M, mb, T, ...]: microbatch x batch x time
        stream = P(None, data, seq) if seq is not None else (
            P(None, data) if data is not None else P())
        sm = shard_map(
            program, mesh=mesh,
            in_specs=(P(), P(pipe), P(), P(pipe), P(), P(),
                      stream, stream, stream if has_f else P(),
                      stream if has_l else P(), P()),
            out_specs=(P(), P(pipe), P(), P()),
            axis_names=manual, check_vma=False)
        loss, st_stage, st_pre, st_post = sm(*operands)
        new_pp_state = {"pre": st_pre, "stages": st_stage, "post": st_post}
        return loss, new_pp_state

    def loss_fn(pp, pp_state, rng, toks_m, labs_m, fm_m, lm_m):
        loss, new_pp_state = run_sm(pp, pp_state, rng, toks_m, labs_m,
                                    fm_m, lm_m)
        # L1/L2 penalties (stacked leaves sum over stages exactly like the
        # canonical per-block sum — all blocks share one conf)
        for name in plan.pre_layers + plan.post_layers:
            src = pp["pre"] if name in pp["pre"] else pp["post"]
            loss = loss + l1_l2_penalty(
                net.layer_vertices[name].layer, src[name])
        i = 0
        stage_tree = {}
        for tname, treedef, n in plan.stage_template:
            stage_tree[tname] = jax.tree.unflatten(
                treedef, list(pp["stages"][i:i + n]))
            i += n
        for tname in stage_tree:
            loss = loss + l1_l2_penalty(
                net.layer_vertices[tname].layer, stage_tree[tname])
        return loss, new_pp_state

    def _first_mask(ms):
        return next((m for m in (ms or []) if m is not None), None)

    def step(pp_params, opt_state, state, rng, batch):
        toks = batch["features"][0]
        labs = batch["labels"][0]
        fmask = _first_mask(batch.get("features_masks"))
        lmask = _first_mask(batch.get("labels_masks"))
        B = toks.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible into {M} microbatches")
        mb = B // M
        if data is not None and mb % mesh.shape[data]:
            raise ValueError(
                f"microbatch size {mb} not divisible over the "
                f"{mesh.shape[data]}-way data axis")
        if seq is not None and toks.shape[1] % mesh.shape[seq]:
            raise ValueError(
                f"sequence length {toks.shape[1]} not divisible over the "
                f"{mesh.shape[seq]}-way seq axis")

        def to_stream(a):
            if a is None:
                return None
            return a.reshape((M, mb) + a.shape[1:])

        toks_m, labs_m, fm_m, lm_m = map(to_stream,
                                         (toks, labs, fmask, lmask))
        pp_state = plan.to_pipelined_state(state)
        (loss, new_pp_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(pp_params, pp_state, rng, toks_m,
                                   labs_m, fm_m, lm_m)
        updates, opt_state = net.tx.update(grads, opt_state, pp_params)
        pp_params = optax.apply_updates(pp_params, updates)
        new_state = (plan.to_canonical_state(new_pp_state, state)
                     if plan.has_state else state)
        return pp_params, opt_state, new_state, loss, {}

    return jax.jit(step, donate_argnums=(0, 1))
