"""Container-integrated pipeline parallelism (PP).

Builds pipeline stages from the REAL network conf — the builder-API
ComputationGraph (reference ComputationGraphConfiguration.GraphBuilder,
nn/conf/ComputationGraphConfiguration.java:446) — instead of requiring a
hand-stacked homogeneous stage_fn (the r2 demo in pipeline_parallel.py):

- **Partitioning**: the DAG's topological order is scanned for single-value
  cuts (positions where exactly one activation is live); the longest run of
  structurally identical cut-to-cut segments (fingerprinted on vertex
  types, configs, wiring, and param shapes) becomes the pipelined body —
  e.g. the n_layers pre-norm transformer blocks. Everything before the run
  (embedding, positional encoding) is the heterogeneous PRE segment;
  everything after (final LN, LM head + loss) is the POST segment.

- **Schedule**: a GPipe microbatch schedule as one `lax.scan` of per-tick
  stage compute inside a `shard_map` that is MANUAL over the 'pipe' mesh
  axis ONLY (`axis_names={pipe}`): 'data' and 'model'/'expert' axes stay
  AUTO, so batch sharding and Megatron TP / MoE EP placements propagate
  through the per-stage compute via GSPMD — dp x tp x pp composes inside
  ONE jitted train step, with XLA inserting the collectives.

- **Heterogeneous ends without SPMD waste**: the PRE segment runs
  replicated-over-pipe at each injection tick (an embedding gather —
  negligible FLOPs); the POST segment + loss runs ONCE per microbatch,
  balanced round-robin across pipe devices via a second "done lane" ring:
  the last stage injects finished activations into the lane, each device
  captures the microbatches assigned to it (j % S == device), and computes
  the head loss for its share after the scan. Head FLOPs are never
  duplicated per stage, and no device stores more than M/S microbatches of
  final activations (the r2 review's full-batch-memory critique).

- **Memory layout**: stage parameters live STACKED on a leading [S] axis
  sharded over 'pipe' (each device holds one stage's blocks), composed
  with the TP/EP dim rules on the remaining axes. The token/label
  microbatch stream is replicated over pipe — int32 tokens are ~d_model x
  smaller than activations, so only activations ride the rings.

Differentiability is free: `ppermute`/`scan`/`dynamic_update_slice` all
have transpose rules, so `jax.grad` of the scheduled loss yields the
reverse (backward) pipeline schedule automatically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertexConf
from deeplearning4j_tpu.nn.conf.layers import BaseOutputLayer
from deeplearning4j_tpu.nn.layers import l1_l2_penalty


def _chain_cuts(conf):
    """Positions in topo order after which exactly ONE activation is live
    (single-edge cuts of the DAG — valid pipeline stage boundaries)."""
    topo = [n for n in conf.topological_order()
            if n not in conf.network_inputs]
    pos = {n: i for i, n in enumerate(topo)}
    INF = len(topo) + 1
    # last position consuming each value; network outputs live to the end
    last_use = {}
    for n in topo:
        for src in conf.vertex_inputs[n]:
            last_use[src] = max(last_use.get(src, -1), pos[n])
    for out in conf.network_outputs:
        last_use[out] = INF
    cuts = []
    for i, n in enumerate(topo):
        live = [v for v in topo[:i + 1] if last_use.get(v, -1) > i]
        live += [v for v in conf.network_inputs if last_use.get(v, -1) > i]
        if live == [n]:
            cuts.append(i)
    return topo, cuts


def _conf_repr(obj):
    """Structural repr of a (possibly nested) vertex/layer config with
    identity fields ('name') stripped — two blocks differing only in layer
    names must fingerprint equal."""
    import dataclasses

    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        fields = ", ".join(
            f"{f.name}={_conf_repr(getattr(obj, f.name))}"
            for f in dataclasses.fields(obj) if f.name != "name")
        return f"{type(obj).__name__}({fields})"
    return repr(obj)


def _fingerprint(conf, params, seg, ext):
    """Structural identity of one cut-to-cut segment: vertex kinds +
    configs + segment-local wiring + param leaf shapes/dtypes. Segments
    with equal fingerprints can be stacked into pipeline stages."""
    pos = {n: j for j, n in enumerate(seg)}
    entries = []
    for n in seg:
        v = conf.vertices[n]
        wires = tuple(("ext",) if i == ext else ("local", pos[i])
                      for i in conf.vertex_inputs[n])
        p = params.get(n, {})
        shapes = tuple(
            (tuple(leaf.shape), str(leaf.dtype))
            for leaf in jax.tree.leaves(p))
        entries.append((type(v).__name__, _conf_repr(v), wires, shapes))
    return tuple(entries)


def _longest_periodic_run(fps):
    """Find (lo, n_units, period): the maximal-coverage run of consecutive
    REPEAT UNITS of `period` segments each with identical per-unit
    fingerprints (a transformer block may span several single-value cuts —
    e.g. an attention half and an FF half)."""
    n = len(fps)
    best = (0, 1, 1)  # lo, units, period
    for p in range(1, n // 2 + 1):
        for lo in range(0, n - p + 1):
            unit = tuple(fps[lo:lo + p])
            c = 1
            while (lo + (c + 1) * p <= n
                   and tuple(fps[lo + c * p:lo + (c + 1) * p]) == unit):
                c += 1
            if c > 1 and c * p > best[1] * best[2]:
                best = (lo, c, p)
    return best


class PipelinePlan:
    """Partition of a ComputationGraph into pre / S stages / post, with the
    param-tree restructuring between the canonical per-layer layout and the
    pipelined {pre, stages(stacked leaves), post} layout."""

    def __init__(self, net, n_stages: int):
        conf = net.conf
        if len(conf.network_inputs) != 1 or len(conf.network_outputs) != 1:
            raise ValueError(
                "pipeline parallelism supports single-input single-output "
                f"graphs; got {len(conf.network_inputs)} inputs / "
                f"{len(conf.network_outputs)} outputs")
        self.net = net
        self.S = n_stages
        self.input_name = conf.network_inputs[0]
        out_name = conf.network_outputs[0]
        out_v = conf.vertices[out_name]
        if not (isinstance(out_v, LayerVertexConf)
                and isinstance(out_v.layer, BaseOutputLayer)):
            raise ValueError("pipeline parallelism needs an output layer "
                             "as the single network output")
        if net.params is None:
            net.init()
        for name, sub in net.state.items():
            if jax.tree.leaves(sub):
                raise ValueError(
                    f"pipeline parallelism requires stateless layers; "
                    f"'{name}' carries mutable state (e.g. batchnorm "
                    "running stats) which cannot thread a microbatch ring")

        topo, cuts = _chain_cuts(conf)
        if not cuts:
            raise ValueError("graph has no single-activation cut points — "
                             "cannot partition into pipeline stages")
        # segments between consecutive cuts; segment i spans
        # (cuts[i-1], cuts[i]]; a leading segment before the first cut
        bounds = [-1] + cuts
        segs = [topo[bounds[i] + 1:bounds[i + 1] + 1]
                for i in range(len(bounds) - 1)]
        if bounds[-1] != len(topo) - 1:
            segs.append(topo[bounds[-1] + 1:])
        ext_of = [self.input_name] + [s[-1] for s in segs[:-1]]
        fps = [_fingerprint(conf, net.params, s, e)
               for s, e in zip(segs, ext_of)]
        # longest periodic run of identical repeat units = pipelined body
        lo, units, period = _longest_periodic_run(fps)
        if units % n_stages:
            raise ValueError(
                f"the {units} repeated blocks do not divide into "
                f"{n_stages} pipeline stages")
        per_stage = units // n_stages
        hi = lo + units * period
        body_segs = segs[lo:hi]
        seg_per_stage = per_stage * period
        self.stage_groups = [
            sum(body_segs[g * seg_per_stage:(g + 1) * seg_per_stage], [])
            for g in range(n_stages)]
        self.pre_names = sum(segs[:lo], [])
        post = sum(segs[hi:], [])
        if post and post[-1] == out_name:
            post = post[:-1]  # the loss layer runs via post_loss, not here
        self.post_names = post
        self.out_name = out_name
        self.out_vconf = out_v

        # external input value feeding each region
        self.pre_ext = self.input_name
        self.body_ext = (segs[lo - 1][-1] if lo > 0
                         else self.input_name)
        self.post_ext = body_segs[-1][-1] if body_segs else self.body_ext
        # consistency: the value feeding the loss layer
        loss_in = conf.vertex_inputs[out_name][0]
        self.loss_ext = loss_in

        self._steps_pre = self._build_steps(self.pre_names, self.pre_ext)
        self._steps_stage = self._build_steps(self.stage_groups[0],
                                              self.body_ext)
        self._steps_post = self._build_steps(self.post_names, self.post_ext)

        # per-layer (name, treedef, n_leaves) template for stage stacking,
        # in TOPO order within the group (stable across groups, unlike
        # lexicographic sort — 'blk10' < 'blk9' would misalign leaves)
        self.group_layers = [
            [n for n in g if isinstance(conf.vertices[n], LayerVertexConf)]
            for g in self.stage_groups]
        tmpl = []
        for name in self.group_layers[0]:
            leaves, treedef = jax.tree.flatten(net.params[name])
            tmpl.append((name, treedef, len(leaves)))
        self.stage_template = tmpl
        self.pre_layers = [n for n in self.pre_names
                           if isinstance(conf.vertices[n], LayerVertexConf)]
        self.post_layers = [n for n in self.post_names
                            if isinstance(conf.vertices[n], LayerVertexConf)
                            ] + [out_name]

        # leaf paths for TP/EP rule matching on stacked leaves, named by
        # the template (group-0) layer names
        self.stage_leaf_names = []
        for name, _, _ in tmpl:
            flat = jax.tree_util.tree_flatten_with_path(
                net.params[name])[0]
            for path, _leaf in flat:
                suffix = "/".join(str(getattr(k, "key", k)) for k in path)
                self.stage_leaf_names.append(f"{name}/{suffix}")

    # ------------------------------------------------------------ executors
    def _build_steps(self, names, ext_value):
        conf = self.net.conf
        pos = {n: j for j, n in enumerate(names)}
        steps = []
        for n in names:
            v = conf.vertices[n]
            refs = tuple(("ext", None) if i == ext_value else ("local", pos[i])
                         for i in conf.vertex_inputs[n])
            steps.append((n, v, refs))
        return steps

    def _apply_steps(self, steps, params, x, *, train, rng):
        """Run a region's vertices on one activation; returns the final
        activation. params: {template_layer_name: subtree}."""
        net = self.net
        cdtype = net.compute_dtype
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            x = jnp.asarray(x, cdtype)
        acts = {}
        keys = (jax.random.split(rng, max(len(steps), 1))
                if rng is not None else [None] * len(steps))
        out = x
        for (n, v, refs), k in zip(steps, keys):
            ins = [x if r[0] == "ext" else acts[steps[r[1]][0]] for r in refs]
            if isinstance(v, LayerVertexConf):
                xi = ins[0]
                if v.preprocessor is not None:
                    xi = v.preprocessor.pre_process(xi)
                p = params.get(n, {})
                if cdtype != net.param_dtype:
                    p = jax.tree.map(
                        lambda a: a.astype(cdtype)
                        if jnp.issubdtype(a.dtype, jnp.floating) else a, p)
                y, _s = net.impls[n].apply(
                    v.layer, p, {}, xi, train=train, rng=k, mask=None)
            else:
                y = net._vertex_forward(n, v, ins, params, {}, train, k,
                                        {}, acts)
            acts[n] = y
            out = y
        return out

    def pre_apply(self, pre_params, x, *, train, rng):
        if not self._steps_pre:
            return jnp.asarray(x, self.net.compute_dtype) \
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x
        return self._apply_steps(self._steps_pre, pre_params, x,
                                 train=train, rng=rng)

    def stage_apply(self, stage_params, x, *, train, rng):
        return self._apply_steps(self._steps_stage, stage_params, x,
                                 train=train, rng=rng)

    def post_loss(self, post_params, h, labels, *, train, rng, mask=None):
        """POST region + output-layer loss for a batch of finished
        activations."""
        net = self.net
        if self._steps_post:
            h = self._apply_steps(self._steps_post, post_params, h,
                                  train=train, rng=rng)
        v = self.out_vconf
        if v.preprocessor is not None:
            h = v.preprocessor.pre_process(h)
        # same compute-dtype policy as the non-PP loss path: the head
        # weight must not stream through the loss kernels in f32 for a
        # bf16 model
        p_out = post_params[self.out_name]
        if net.compute_dtype != net.param_dtype:
            from deeplearning4j_tpu.nn.training import tree_cast

            p_out = tree_cast(p_out, net.compute_dtype)
        return net.impls[self.out_name].loss(
            v.layer, p_out, h, labels, train=train, rng=rng, mask=mask)

    # ----------------------------------------------------- tree restructure
    def stage_local(self, stacked, g=None):
        """Rebuild {template_name: subtree} from a tuple of stacked leaves.
        g=None: leaves already have the stage axis stripped (inside
        shard_map); integer g: take stage g's slice (tracing-safe)."""
        params = {}
        i = 0
        for name, treedef, n in self.stage_template:
            leaves = [stacked[i + j] if g is None else stacked[i + j][g]
                      for j in range(n)]
            params[name] = jax.tree.unflatten(treedef, leaves)
            i += n
        return params

    def to_pipelined(self, params):
        pre = {n: params[n] for n in self.pre_layers}
        post = {n: params[n] for n in self.post_layers}
        per_group = []
        for g in self.group_layers:
            per_group.append([leaf for name in g
                              for leaf in jax.tree.leaves(params[name])])
        stages = tuple(jnp.stack([per_group[g][i]
                                  for g in range(self.S)])
                       for i in range(len(per_group[0])))
        return {"pre": pre, "stages": stages, "post": post}

    def to_canonical(self, pp):
        params = {}
        params.update(pp["pre"])
        params.update(pp["post"])
        for g, names in enumerate(self.group_layers):
            local = self.stage_local(pp["stages"], g=g)
            for tmpl_name, name in zip(self.group_layers[0], names):
                params[name] = local[tmpl_name]
        return params

    # --------------------------------------------------------- param place
    def placements(self, mesh: Mesh, axes: dict, rules):
        """Pipelined-tree pytree of NamedShardings: stacked stage leaves
        shard their leading [S] dim over the pipe axis composed with the
        TP/EP dim rules; pre/post follow the rules, replicated over pipe."""
        from deeplearning4j_tpu.parallel.tensor_parallel import sharding_for

        pipe = axes["pipe"]

        def leaf_spec(name):
            base = sharding_for(name, mesh, rules).spec
            return NamedSharding(mesh, P(pipe, *base))

        stage_sh = tuple(leaf_spec(n) for n in self.stage_leaf_names)

        def place_named(subtree, prefix):
            flat, treedef = jax.tree_util.tree_flatten_with_path(subtree)
            shs = []
            for path, _leaf in flat:
                suffix = "/".join(str(getattr(k, "key", k)) for k in path)
                shs.append(sharding_for(f"{prefix}{suffix}", mesh, rules))
            return jax.tree.unflatten(treedef, shs)

        src = self.net.params
        if isinstance(src, dict) and "stages" in src:
            src = self.to_canonical(src)
        pre_sh = {n: place_named(src[n], f"{n}/") for n in self.pre_layers}
        post_sh = {n: place_named(src[n], f"{n}/") for n in self.post_layers}
        return {"pre": pre_sh, "stages": stage_sh, "post": post_sh}


def check_pp_supported(net):
    """Configuration modes the PP step cannot honor raise up front."""
    from deeplearning4j_tpu.nn.conf.enums import (
        BackpropType,
        GradientNormalization,
        OptimizationAlgorithm,
    )

    g = net.conf.conf
    if str(g.optimization_algo) != str(
            OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT):
        raise ValueError("pipeline parallelism supports SGD-family "
                         "training only (no second-order solvers)")
    if str(net.conf.backprop_type) in (str(BackpropType.TRUNCATED_BPTT),
                                       "truncated_bptt"):
        raise ValueError("pipeline parallelism does not support TBPTT")
    for name, v in net.layer_vertices.items():
        lc = v.layer
        gn = getattr(lc, "gradient_normalization", None)
        if gn not in (None, GradientNormalization.NONE, "none"):
            raise ValueError(
                f"per-layer gradient normalization on '{name}' is not "
                "supported under pipeline parallelism")
        if (getattr(lc, "updater", None) not in (None, g.updater)
                or getattr(lc, "learning_rate", None) is not None):
            raise ValueError(
                f"per-layer updater/learning-rate override on '{name}' is "
                "not supported under pipeline parallelism (the optimizer "
                "runs on the stacked stage tree)")


def make_pp_train_step(net, plan: PipelinePlan, mesh: Mesh, axes: dict,
                       n_microbatches: int, rules):
    """Jitted train step over the pipelined param tree, standard container
    contract: step(pp_params, opt_state, state, rng, batch) ->
    (pp_params, opt_state, state, loss, {}).

    batch: {"features": (tokens [B, ...],), "labels": (labels [B, ...],)}
    with B divisible into n_microbatches x (data-axis multiple).
    """
    import optax

    pipe = axes["pipe"]
    data = axes.get("data")
    S, M = plan.S, n_microbatches
    if M % S:
        raise ValueError(f"{M} microbatches do not divide over {S} stages")
    k_slots = M // S
    T_total = M + 2 * S - 2

    def program(pre_p, stages_p, post_p, toks, labs, key):
        # local stage slice: shard_map strips the leading [S] axis to 1
        stage_p = plan.stage_local(tuple(a[0] for a in stages_p))
        idx = lax.axis_index(pipe)
        u = (idx + 1) % S  # done-lane hops from the last stage to here

        probe = plan.pre_apply(pre_p, toks[0], train=True,
                               rng=jax.random.fold_in(key, 0))
        zero = jnp.zeros_like(probe)

        def tick(carry, t):
            inflight, done_lane, store = carry
            kt = jax.random.fold_in(key, t)
            # stage 0 injects microbatch t while t < M (the PRE segment is
            # an embedding-scale gather — computing it replicated over
            # pipe is far cheaper than ringing the token stream)
            inject = jnp.where(t < M, t, 0)
            x0 = plan.pre_apply(
                pre_p, lax.dynamic_index_in_dim(toks, inject, 0, False),
                train=True, rng=jax.random.fold_in(kt, S))
            x_in = jnp.where(idx == 0,
                             jnp.where(t < M, x0, zero), inflight)
            y = plan.stage_apply(stage_p, x_in, train=True,
                                 rng=jax.random.fold_in(kt, idx))
            # done lane: last stage injects its finished microbatch; each
            # device captures the ones assigned to it (j % S == idx)
            done_in = jnp.where(idx == S - 1, y, done_lane)
            j = t - (S - 1) - u
            cap = (j % S == idx) & (j >= 0) & (j < M)
            slot = jnp.clip(j // S, 0, k_slots - 1)
            store = jnp.where(cap, store.at[slot].set(done_in), store)
            done_lane = lax.ppermute(done_in, pipe,
                                     [(i, (i + 1) % S) for i in range(S)])
            inflight = lax.ppermute(y, pipe,
                                    [(i, (i + 1) % S) for i in range(S)])
            return (inflight, done_lane, store), None

        store0 = jnp.zeros((k_slots,) + probe.shape, probe.dtype)
        carry0 = tuple(
            lax.pcast(a, (pipe,), to="varying")
            for a in (zero, zero, store0))
        (_, _, store), _ = lax.scan(tick, carry0, jnp.arange(T_total))

        # POST + loss once per microbatch, balanced over pipe devices:
        # device d holds microbatches j = s*S + d in slots s
        mb = toks.shape[1]
        h = store.reshape((k_slots * mb,) + store.shape[2:])
        labs_r = labs.reshape((k_slots, S) + labs.shape[1:])
        labs_local = lax.dynamic_index_in_dim(
            jnp.moveaxis(labs_r, 1, 0), idx, 0, False)
        labs_local = labs_local.reshape((k_slots * mb,) + labs.shape[2:])
        local = plan.post_loss(post_p, h, labs_local, train=True,
                               rng=jax.random.fold_in(key, T_total))
        # equal shard sizes: global mean = pmean of local means
        return lax.pmean(local, pipe)

    sm = jax.shard_map(
        program, mesh=mesh,
        in_specs=(P(), P(pipe), P(), P(), P(), P()),
        out_specs=P(), axis_names={pipe}, check_vma=False)

    def loss_fn(pp, rng, toks_m, labs_m):
        loss = sm(pp["pre"], pp["stages"], pp["post"], toks_m, labs_m, rng)
        # L1/L2 penalties (stacked leaves sum over stages exactly like the
        # canonical per-block sum — all blocks share one conf)
        for name in plan.pre_layers + plan.post_layers:
            src = pp["pre"] if name in pp["pre"] else pp["post"]
            loss = loss + l1_l2_penalty(
                net.layer_vertices[name].layer, src[name])
        i = 0
        stage_tree = {}
        for tname, treedef, n in plan.stage_template:
            stage_tree[tname] = jax.tree.unflatten(
                treedef, list(pp["stages"][i:i + n]))
            i += n
        for tname in stage_tree:
            loss = loss + l1_l2_penalty(
                net.layer_vertices[tname].layer, stage_tree[tname])
        return loss

    def step(pp_params, opt_state, state, rng, batch):
        toks = batch["features"][0]
        labs = batch["labels"][0]
        if batch.get("features_masks") or batch.get("labels_masks"):
            raise ValueError("masks are not supported under pipeline "
                             "parallelism — pad to full length")
        B = toks.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible into {M} microbatches")
        mb = B // M
        toks_m = toks.reshape((M, mb) + toks.shape[1:])
        labs_m = labs.reshape((M, mb) + labs.shape[1:])
        if data is not None:
            dsh = NamedSharding(mesh, P(None, data))
            toks_m = lax.with_sharding_constraint(toks_m, dsh)
            labs_m = lax.with_sharding_constraint(labs_m, dsh)
        loss, grads = jax.value_and_grad(loss_fn)(pp_params, rng,
                                                  toks_m, labs_m)
        updates, opt_state = net.tx.update(grads, opt_state, pp_params)
        pp_params = optax.apply_updates(pp_params, updates)
        return pp_params, opt_state, state, loss, {}

    return jax.jit(step, donate_argnums=(0, 1))
