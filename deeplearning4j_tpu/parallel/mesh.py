"""Device mesh utilities.

The mesh replaces the reference's cluster topology (Spark executors / Akka
workers). Axis conventions used throughout the framework:

- "data"  : data parallelism (gradient allreduce over ICI — replaces
            SparkDl4jMultiLayer parameter averaging)
- "model" : tensor parallelism (attention heads / FF hidden sharded)
- "seq"   : sequence/context parallelism (ring attention)

Multi-host: initialize the rendezvous first via
`distributed.bootstrap.initialize()` (the control plane the reference
delegated to Spark/ZooKeeper); jax.devices() then spans hosts and the same
mesh code scales from 1 chip to a multi-slice pod.
`distributed.global_mesh.make_global_mesh` builds the process-spanning
mesh; `spans_processes` below is how the train-step plumbing detects that
host batches need per-process globalization.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axes: dict[str, int] | None = None, *, devices=None) -> Mesh:
    """Build a Mesh from {axis: size}; -1 means 'all remaining devices'.

    make_mesh({"data": -1})                 # pure DP over every chip
    make_mesh({"data": 2, "model": 4})      # 2-way DP x 4-way TP
    """
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"data": -1})
    sizes = list(axes.values())
    n_fixed = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if -1 in sizes:
        rem = len(devices) // max(n_fixed, 1)
        sizes = [rem if s == -1 else s for s in sizes]
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh {dict(zip(axes, sizes))} needs {total} devices, "
                         f"have {len(devices)}")
    # host-side Device OBJECTS at mesh-build time, not a device sync
    arr = np.asarray(devices[:total]).reshape(sizes)  # graftlint: disable=G002
    return Mesh(arr, tuple(axes.keys()))


def spans_processes(mesh: Mesh) -> bool:
    """True when the mesh's devices live in more than one OS process —
    the switch that turns set_mesh's DP path multi-process (host batches
    then globalize via distributed.global_mesh.globalize_batch)."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def shard_batch(batch, mesh: Mesh, axis: str = "data"):
    """Place a host batch pytree with its leading dim sharded over `axis`."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree over the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
