"""Data-parallel training over the ICI mesh.

Two modes, mirroring the reference's two Spark training masters
(SURVEY.md §2.4 DP-1/DP-2, spark/impl/multilayer/SparkDl4jMultiLayer.java):

1. **Allreduce (the TPU-native mode)** — DataParallelTrainer: the batch is
   sharded over the mesh 'data' axis, params replicated; XLA inserts the
   gradient allreduce (psum over ICI) inside the single jitted step. This is
   BASELINE.json's "param-avg → ICI allreduce" replacement: no driver
   round-trip, no O(model) host traffic per round
   (vs SparkDl4jMultiLayer.runIteration:365-452 broadcast + accumulator).

2. **Parameter averaging (semantic parity mode)** — ParameterAveragingTrainer:
   each mesh slot holds its own replica params and updater state, runs k
   local steps (shard_map, no cross-replica collective), then averages
   params AND updater state with pmean every k steps — exactly the
   reference's AVERAGE_EACH_ITERATION/averagingFrequency semantics including
   UpdaterAggregator state merging (:421-427), for the allreduce-vs-param-avg
   benchmark.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.api import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator, ListDataSetIterator


class DataParallelTrainer:
    """Allreduce DP wrapper around a network (MultiLayerNetwork or
    ComputationGraph): `trainer.fit(iterator)` == network.fit with the step
    compiled over the mesh. `overlap` (True / bucket bytes / a
    parallel/overlap.BucketPlan) routes the gradient reduction through
    the bucketed shard_map step — per-bucket collectives in reverse
    layer order overlapping backward/update compute — instead of GSPMD's
    monolithic end-of-backward allreduce (the bench's `overlap` arm)."""

    def __init__(self, net, mesh: Mesh, overlap=None):
        if "data" not in mesh.axis_names:
            raise ValueError("mesh needs a 'data' axis")
        self.net = net
        self.mesh = mesh
        net.set_mesh(mesh, overlap=overlap)

    def fit(self, data, epochs: int = 1):
        return self.net.fit(data, epochs=epochs)


class ParameterAveragingTrainer:
    """Reference-parity parameter averaging (k local steps then average).

    Params/opt-state live stacked with a leading replica axis sharded over
    the mesh 'data' axis; shard_map keeps local steps collective-free and a
    pmean implements the averaging round. This reproduces what the Spark
    master did each `averagingFrequency` iterations — broadcast is implicit
    (the averaged value IS the new replica value).
    """

    def __init__(self, net, mesh: Mesh, averaging_frequency: int = 1,
                 average_updater_state: bool = True):
        self.net = net
        self.mesh = mesh
        self.k = max(1, averaging_frequency)
        self.average_updater = average_updater_state
        self.n_replicas = mesh.shape["data"]
        if net.params is None:
            net.init()
        self._stacked_params = self._stack(net.params)
        self._stacked_opt = self._stack(net.opt_state)
        self._stacked_state = self._stack(net.state)
        self._local_steps = 0
        self._warned_truncation = False
        self._build_steps()

    def _stack(self, tree):
        n = self.n_replicas
        stacked = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (n,) + x.shape),
                               tree)
        sh = NamedSharding(self.mesh, P("data"))
        return jax.tree.map(lambda x: jax.device_put(x, sh), stacked)

    def _build_steps(self):
        net, mesh = self.net, self.mesh
        tx = net.tx
        from deeplearning4j_tpu.util.compat import shard_map

        @partial(shard_map, mesh=mesh,
                 in_specs=(P("data"), P("data"), P("data"), P("data"), P()),
                 out_specs=(P("data"), P("data"), P("data"), P("data")))
        def local_step(params, opt_state, state, batch, rng):
            # leading replica axis has size 1 inside the shard — strip it
            params = jax.tree.map(lambda x: x[0], params)
            opt_state = jax.tree.map(lambda x: x[0], opt_state)
            state = jax.tree.map(lambda x: x[0], state)
            (loss, aux), grads = jax.value_and_grad(
                lambda p: net._loss(p, state, rng, batch), has_aux=True)(params)
            new_state = aux[0] if isinstance(aux, tuple) else aux
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            add = jax.tree.map(lambda x: x[None], (params, opt_state, new_state))
            return add[0], add[1], add[2], loss[None]

        self._local_step = jax.jit(local_step)

        def average(params, opt_state, state):
            def avg_float(x):
                if jnp.issubdtype(x.dtype, jnp.floating):
                    return jnp.mean(x, axis=0, keepdims=True) * jnp.ones_like(x)
                return x

            avg_p = jax.tree.map(avg_float, params)
            # per-layer state (BatchNorm running stats) averages like params
            avg_s = jax.tree.map(avg_float, state)
            if self.average_updater:
                # average float updater state (moments); keep int counters
                avg_o = jax.tree.map(avg_float, opt_state)
            else:
                avg_o = opt_state
            return avg_p, avg_o, avg_s

        self._average = jax.jit(average)

    def _convert(self, ds):
        """Prefetch-thread batch prep (data/pipeline.py): replica
        truncation, device conversion, and the data-axis placement all
        overlap the local step running on the step thread."""
        n = self.n_replicas
        b = ds.num_examples()
        per = b // n
        if per == 0:
            raise ValueError(
                f"batch of {b} examples cannot be split over {n} "
                f"replicas — use batches of at least {n} examples")
        if per * n != b and not self._warned_truncation:
            import warnings

            warnings.warn(
                f"batch size {b} is not divisible by {n} replicas; "
                f"the last {b - per * n} examples of each such batch "
                f"are dropped", stacklevel=2)
            self._warned_truncation = True
        m = per * n

        def trunc(arrs):
            return None if arrs is None else [
                None if a is None else a[:m] for a in arrs]

        if isinstance(ds, MultiDataSet):
            tds = MultiDataSet(trunc(ds.features), trunc(ds.labels),
                               trunc(ds.features_masks),
                               trunc(ds.labels_masks))
            batch = self.net._batch_dict(tds)
        else:
            tds = DataSet(
                ds.features[:m], ds.labels[:m],
                None if ds.features_mask is None else ds.features_mask[:m],
                None if ds.labels_mask is None else ds.labels_mask[:m])
            if hasattr(self.net, "_to_mds"):
                # ComputationGraph: multi-input batch format (tuples)
                batch = self.net._batch_dict(self.net._to_mds(tds))
            else:
                batch = self.net._batch_dict(tds)
        return jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(self.mesh, P("data"))), batch)

    def fit(self, data, epochs: int = 1):
        """Each incoming minibatch is split across replicas (the RDD
        partition analogue); every k local steps the replicas are averaged."""
        from deeplearning4j_tpu.data.pipeline import iter_prefetched

        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        it: DataSetIterator = data
        for _ in range(epochs):
            it.reset()
            for _ds, batch in iter_prefetched(it, self._convert):
                rng = self.net._next_rng()
                (self._stacked_params, self._stacked_opt, self._stacked_state,
                 losses) = self._local_step(
                    self._stacked_params, self._stacked_opt, self._stacked_state,
                    batch, rng)
                self.net.score_value = jnp.mean(losses)  # lazy host sync
                self.net.iteration_count += 1
                self._local_steps += 1
                if self._local_steps % self.k == 0:
                    (self._stacked_params, self._stacked_opt,
                     self._stacked_state) = self._average(
                        self._stacked_params, self._stacked_opt,
                        self._stacked_state)
                for lst in self.net.listeners:
                    lst.iteration_done(self.net, self.net.iteration_count)
        self.sync_to_network()
        return self.net

    def sync_to_network(self):
        """Write replica-0 (post-averaging) params/state back to the net."""
        self.net.params = jax.tree.map(lambda x: x[0], self._stacked_params)
        self.net.state = jax.tree.map(lambda x: x[0], self._stacked_state)
        return self.net
