"""Ring attention — sequence/context parallelism over the ICI mesh.

New capability (SURVEY.md §5: the reference's only long-sequence mechanisms
are TBPTT and masking; ring attention is the TPU-era answer for sequences
that don't fit one chip). Design per the blockwise-attention family:
sequence sharded over the mesh "seq" axis, K/V blocks rotated around the
ring with `lax.ppermute` while each shard accumulates its queries' output
with the online-softmax (log-sum-exp) recurrence, so the full [T, T] score
matrix never materializes and each hop overlaps compute with ICI transfer.

`ring_attention` is the per-shard function (call inside shard_map);
`ring_self_attention` wraps it in shard_map over a mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG = -1e30


def _keep_scale_jnp(seed, B, H, q0, k0, Tl, hash_t, rate):
    """[B, H, Tl, Tl] dropout keep*1/(1-rate) — the plain-jnp twin of the
    flash kernels' `_keep_mask` (ops/flash_attention.py), bit-for-bit:
    same murmur key per (b*H + h) row, same global-coordinate element
    mix. Used by the einsum fallback so odd-length local blocks drop the
    SAME elements the kernel path (and the single-chip monolithic
    kernel) would. q0/k0 may be traced (hop origins)."""
    from deeplearning4j_tpu.ops.flash_attention import _fmix32

    u = jnp.uint32
    bh = jnp.arange(B * H, dtype=jnp.uint32).reshape(B, H, 1, 1)
    key = _fmix32(jnp.reshape(seed, ()).astype(u) + bh * u(0x9E3779B9))
    gq = jnp.asarray(q0, jnp.int32).astype(u) + jnp.arange(Tl, dtype=u)
    gk = jnp.asarray(k0, jnp.int32).astype(u) + jnp.arange(Tl, dtype=u)
    h = key + (gq[:, None] * u(hash_t) + gk[None, :])
    h = h * u(0xCC9E2D51)
    h = h ^ (h >> u(15))
    h = h * u(0x1B873593)
    h = h ^ (h >> u(13))
    thr = u(min(int((1.0 - rate) * 4294967296.0), 4294967295))
    return (h < thr).astype(jnp.float32) * (1.0 / (1.0 - rate))


def _ring_flash(q, k, v, *, axis_name: str, causal: bool, hop_chunk=None,
                dropout=0.0, seed=None):
    """Per-hop Pallas flash kernel + two-way lse merge (VERDICT r3 #4: the
    ring previously ran f32 einsum blockwise softmax — the dense math the
    kernel exists to replace). Each hop runs the fused kernel on local Q
    against the visiting K/V block at the single-chip flash rate; the
    (o, lse) results merge across hops with the standard logsumexp
    combine (lse_combine — shared with the serial chunk loop in
    ops/flash_attention.py), whose weights differentiate through the
    kernel's lse output (flash_attention_lse). ppermute overlap is
    unchanged. Local blocks past MAX_FLASH_T (the monolithic kernels'
    VMEM envelope) run each hop through chunked_flash_attention_lse, so
    the ring scales to n_shards x 128k-token sequences; `hop_chunk`
    forces that tile length (tests use it at small Tl).

    dropout/seed: in-kernel attention dropout (r6). Every hop hashes its
    GLOBAL window origin (idx*Tl, src*Tl) with the GLOBAL length n*Tl,
    so the keep mask for logical element (bh, i, j) equals the
    single-chip monolithic kernel's — identical regardless of shard
    count or hop order. `seed` is the replicated [1, 1] int32 step key
    (same on every shard — the mask depends only on global coordinates)."""
    from deeplearning4j_tpu.ops.flash_attention import (
        MAX_FLASH_T,
        MONOLITHIC_COMPILE_MAX,
        _drop_ctx,
        _tiles_str,
        chunked_flash_attention_lse,
        flash_attention_lse,
        flash_attention_lse_drop,
        lse_combine,
        max_chunks,
        pick_chunk,
    )

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Tl, D = q.shape
    scale = 1.0 / float(np.sqrt(D))
    qf = q.reshape(B * H, Tl, D)
    perm = [(j, (j + 1) % n) for j in range(n)]
    T_global = n * Tl
    ones_km = (jnp.ones((B * H, 1, Tl), jnp.float32) if dropout else None)
    # hop tiling is non-causal for every below-diagonal hop: since r8
    # those tile loops SCAN their kv tiles (one traced kernel per q
    # chunk — no n_tiles^2 unroll, ADVICE r5 #1) and the tile length is
    # D-aware (head dims past 128 use shorter proven tiles)
    if hop_chunk or (Tl > MAX_FLASH_T and pick_chunk(Tl, False,
                                                     head_dim=D) > 0):
        def hop_lse(qf, kf, vf, scale, causal_hop, k0):
            if dropout:
                return chunked_flash_attention_lse(
                    qf, kf, vf, scale, causal_hop, chunk=hop_chunk,
                    dropout=dropout, seed=seed, q_origin=idx * Tl,
                    k_origin=k0, hash_t=T_global)
            return chunked_flash_attention_lse(qf, kf, vf, scale,
                                               causal_hop, chunk=hop_chunk)
    elif Tl <= MAX_FLASH_T or (Tl <= MONOLITHIC_COMPILE_MAX and D <= 128):
        # non-tileable local blocks up to the measured compile ceiling
        # keep the monolithic per-hop kernel (pre-r5 behavior). The
        # extended tier past MAX_FLASH_T is gated at D <= 128 like
        # supports_monolithic_fallback (ADVICE r5 #3: the backward's VMEM
        # working set scales with D; the ceiling was measured at D=128) —
        # blocks inside the proven envelope take any D, as on one chip
        def hop_lse(qf, kf, vf, scale, causal_hop, k0):
            if dropout:
                return flash_attention_lse_drop(
                    qf, kf, vf, ones_km, _drop_ctx(seed, idx * Tl, k0),
                    scale, causal_hop, float(dropout), T_global)
            return flash_attention_lse(qf, kf, vf, scale, causal_hop)
    else:
        raise ValueError(
            f"ring attention local block Tl={Tl} (head_dim {D}) is "
            f"neither tileable (2-{max_chunks(False)} tiles of "
            f"{_tiles_str(D)}, D-aware scanned kv loop) nor within the "
            f"monolithic kernels' envelope (Tl <= "
            f"{MONOLITHIC_COMPILE_MAX} at head_dim <= 128) — use more "
            "'seq' shards or pad T so the per-shard block is tileable")

    def hop(k_cur, v_cur, src):
        kf = k_cur.reshape(B * H, Tl, D)
        vf = v_cur.reshape(B * H, Tl, D)
        k0 = src * Tl

        def full(_):
            return hop_lse(qf, kf, vf, scale, False, k0)

        def diag(_):
            return hop_lse(qf, kf, vf, scale, True, k0)

        def skip(_):
            return (jnp.zeros_like(qf),
                    jnp.full((B * H, Tl), _NEG, jnp.float32))

        if not causal:
            return full(None)
        # visiting block entirely in the past -> full; same block ->
        # causal diagonal; entirely in the future -> no contribution
        case = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
        return lax.switch(case, [full, diag, skip], None)

    o0 = jnp.zeros((B * H, Tl, D), jnp.float32)
    lse0 = jnp.full((B * H, Tl), _NEG, jnp.float32)

    def step(carry, i):
        o, lse, k_cur, v_cur = carry
        src = (idx - i) % n
        o_hop, lse_hop = hop(k_cur, v_cur, src)
        o, lse = lse_combine(o, lse, o_hop, lse_hop)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, lse, k_nxt, v_nxt), None

    (o, _, _, _), _ = lax.scan(step, (o0, lse0, k, v), jnp.arange(n))
    return o.reshape(B, H, Tl, D).astype(q.dtype)


def ring_attention(q, k, v, *, axis_name: str = "seq", causal: bool = True,
                   hop_chunk=None, dropout=0.0, dropout_rng=None):
    """Per-shard blockwise attention. q,k,v: [B, H, Tl, D] local blocks of a
    sequence sharded over `axis_name`. Returns [B, H, Tl, D].

    Runs n_shards steps; at each step attends local q against the visiting
    k/v block, then rotates k/v one hop around the ring. When the local
    block length is kernel-legal (Tl % 128 == 0) each hop runs the Pallas
    flash kernel (chunk-tiled when Tl exceeds the monolithic VMEM
    envelope); otherwise the f32 einsum blockwise softmax (tiny-shape
    tests, odd lengths).

    dropout: in-kernel attention-weight dropout (r6) — the counter-hash
    keep mask keys on GLOBAL sequence coordinates, so the ring drops
    exactly what a single-chip kernel at T = n_shards*Tl would.
    `dropout_rng` must be REPLICATED across the seq shards (the layer
    passes its step rng unsplit); the einsum fallback regenerates the
    identical mask via the jnp twin of the kernels' hash."""
    B, H, Tl, D = q.shape
    seed = None
    if dropout:
        if dropout_rng is None:
            raise ValueError("dropout > 0 requires dropout_rng")
        from deeplearning4j_tpu.ops.flash_attention import _step_seed

        seed = _step_seed(dropout_rng)
    if Tl % 128 == 0:
        return _ring_flash(q, k, v, axis_name=axis_name, causal=causal,
                           hop_chunk=hop_chunk, dropout=dropout, seed=seed)
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    q32 = q.astype(jnp.float32)

    q_pos = idx * Tl + jnp.arange(Tl)

    # derive the accumulators from q so they carry the 'seq' varying-axis
    # tag that shard_map's type system expects of per-shard state
    o0 = jnp.zeros_like(q32)
    m0 = jnp.full_like(q32[..., 0], _NEG)
    l0 = jnp.zeros_like(q32[..., 0])
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        src = (idx - i) % n  # which block the visiting k/v belongs to
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, k_cur.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * Tl + jnp.arange(Tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        # l accumulates the UNDROPPED p (dense semantics: dropout applies
        # to the softmax output), matching the kernels' _attn_single_block
        l_new = l * corr + p.sum(axis=-1)
        pd = p
        if dropout:
            pd = p * _keep_scale_jnp(seed, B, H, idx * Tl, src * Tl, Tl,
                                     n * Tl, dropout)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", pd, v_cur.astype(jnp.float32))
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Mesh, *, causal: bool = True,
                        seq_axis: str = "seq"):
    """Whole-sequence entry point: q,k,v [B, H, T, D] (T divisible by the
    seq-axis size). shard_maps the ring over the mesh."""
    from deeplearning4j_tpu.util.compat import shard_map

    spec = P(None, None, seq_axis, None)
    fn = shard_map(
        partial(ring_attention, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        # the per-hop pallas_call can't annotate vma on its out_shape
        check_vma=False,
    )
    return fn(q, k, v)


def sequence_sharded_attention_reference(q, k, v, *, causal: bool = True):
    """Unsharded reference for tests: plain softmax attention in f32."""
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / jnp.sqrt(float(D))
    if causal:
        T = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None, None], s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32)).astype(q.dtype)
