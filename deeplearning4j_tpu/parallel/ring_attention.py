"""Ring attention — sequence/context parallelism over the ICI mesh.

New capability (SURVEY.md §5: the reference's only long-sequence mechanisms
are TBPTT and masking; ring attention is the TPU-era answer for sequences
that don't fit one chip). Design per the blockwise-attention family:
sequence sharded over the mesh "seq" axis, K/V blocks rotated around the
ring with `lax.ppermute` while each shard accumulates its queries' output
with the online-softmax (log-sum-exp) recurrence, so the full [T, T] score
matrix never materializes and each hop overlaps compute with ICI transfer.

`ring_attention` is the per-shard function (call inside shard_map);
`ring_self_attention` wraps it in shard_map over a mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG = -1e30


def ring_attention(q, k, v, *, axis_name: str = "seq", causal: bool = True):
    """Per-shard blockwise attention. q,k,v: [B, H, Tl, D] local blocks of a
    sequence sharded over `axis_name`. Returns [B, H, Tl, D].

    Runs n_shards steps; at each step attends local q against the visiting
    k/v block, then rotates k/v one hop around the ring.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, Tl, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    q32 = q.astype(jnp.float32)

    q_pos = idx * Tl + jnp.arange(Tl)

    # derive the accumulators from q so they carry the 'seq' varying-axis
    # tag that shard_map's type system expects of per-shard state
    o0 = jnp.zeros_like(q32)
    m0 = jnp.full_like(q32[..., 0], _NEG)
    l0 = jnp.zeros_like(q32[..., 0])
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        src = (idx - i) % n  # which block the visiting k/v belongs to
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32, k_cur.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * Tl + jnp.arange(Tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Mesh, *, causal: bool = True,
                        seq_axis: str = "seq"):
    """Whole-sequence entry point: q,k,v [B, H, T, D] (T divisible by the
    seq-axis size). shard_maps the ring over the mesh."""
    from jax import shard_map

    spec = P(None, None, seq_axis, None)
    fn = shard_map(
        partial(ring_attention, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)


def sequence_sharded_attention_reference(q, k, v, *, causal: bool = True):
    """Unsharded reference for tests: plain softmax attention in f32."""
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / jnp.sqrt(float(D))
    if causal:
        T = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None, None], s, _NEG)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32)).astype(q.dtype)
