"""Pipeline parallelism (PP): a GPipe-style microbatch schedule over a
mesh 'pipe' axis.

No reference analogue — the reference scales out only via data-parallel
Spark/Akka masters; PP is part of this framework's TPU-first distributed
design (SURVEY.md §5 long-context/distributed goals, scaling-book recipe):
a homogeneous stack of S blocks (e.g. transformer layers) is partitioned
one-stage-per-device; microbatches flow through the stages with
`jax.lax.ppermute` moving activations over ICI, and the whole schedule —
fill, steady state, drain — is one `lax.scan` inside `shard_map`, fully
differentiable (ppermute has a transpose rule, so jax.grad gives the
reverse schedule automatically).

Layout contract:
- stage parameters are stacked on a leading axis of size S and sharded
  over 'pipe' (each device holds ONE stage's params);
- the input batch is split into M microbatches (M >= S keeps bubbles at
  the GPipe fraction (S-1)/(M+S-1));
- `stage_fn(params, x) -> y` is the per-stage computation with identical
  activation shapes in and out (homogeneous stack).

`pipeline_apply` returns outputs identical (up to float assoc) to
sequentially applying the S stages to each microbatch.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from deeplearning4j_tpu.util.compat import pcast_varying, shard_map
from jax.sharding import PartitionSpec as P


def pipeline_apply(stage_fn, stacked_params, x, *, mesh, n_microbatches,
                   axis: str = "pipe"):
    """Run x through S pipelined stages.

    stage_fn(params_one_stage, x_mb) -> y_mb (same shape as x_mb)
    stacked_params: pytree with leading stage axis S (sharded over `axis`)
    x: [batch, ...]; batch must divide into n_microbatches
    Returns y [batch, ...].
    """
    S = mesh.shape[axis]
    M = n_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    mb = B // M
    xs = x.reshape(M, mb, *x.shape[1:])

    def stage_program(params, xs_local):
        # params: this device's stage (leading axis stripped to size 1)
        p = jax.tree.map(lambda a: a[0], params)
        idx = jax.lax.axis_index(axis)
        T = M + S - 1  # total ticks: fill + steady + drain
        fwd = [(i, (i + 1) % S) for i in range(S)]  # stage i -> i+1

        zero = jnp.zeros_like(xs_local[0])

        def tick(carry, t):
            inflight, outputs = carry
            # stage 0 injects microbatch t while t < M; other stages use
            # what arrived from the previous stage on the last rotation
            inject = jnp.where(t < M, t, 0)
            x_in = jnp.where(idx == 0,
                             jnp.where(t < M, xs_local[inject], zero),
                             inflight)
            y = stage_fn(p, x_in)
            # last stage stores its result: it finishes microbatch t-(S-1)
            out_slot = jnp.clip(t - (S - 1), 0, M - 1)
            store = (idx == S - 1) & (t >= S - 1)
            # masked write (a lax.cond would need matching varying-axis
            # types under shard_map; where keeps it simple)
            outputs = jnp.where(store, outputs.at[out_slot].set(y), outputs)
            # rotate activations one stage forward
            inflight = jax.lax.ppermute(y, axis, fwd)
            return (inflight, outputs), None

        outputs0 = jnp.zeros_like(xs_local)
        # the body's carries are device-varying (they depend on axis_index
        # and ppermute); mark the initial values accordingly for scan's
        # type agreement under shard_map
        zero_v = pcast_varying(zero, (axis,))
        outputs0_v = pcast_varying(outputs0, (axis,))
        (_, outputs), _ = jax.lax.scan(
            tick, (zero_v, outputs0_v), jnp.arange(T))
        return outputs

    # xs is replicated across the pipe axis; each device sees the full
    # microbatch stream (only stage 0 injects, only stage S-1 emits; the
    # psum below collapses the zero buffers of the other stages)
    def program(params, xs_repl):
        out = stage_program(params, xs_repl)
        # only the last stage wrote real outputs; make them replicated
        is_last = jax.lax.axis_index(axis) == S - 1
        out = jnp.where(is_last, out, jnp.zeros_like(out))
        return jax.lax.psum(out, axis)

    out = shard_map(
        program, mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
    )(stacked_params, xs)
    return out.reshape(B, *x.shape[1:])


def stack_stage_params(params_list):
    """Stack per-stage param pytrees along a new leading axis (the 'pipe'
    sharding axis). All stages must be homogeneous."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def shard_stacked_params(stacked, mesh, axis: str = "pipe"):
    """Place the stacked stage params with one stage per 'pipe' device."""
    from jax.sharding import NamedSharding

    sh = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda a: jax.device_put(a, sh), stacked)


def pipeline_loss(stage_fn, loss_fn, stacked_params, x, y, *, mesh,
                  n_microbatches, axis: str = "pipe"):
    """loss over a pipelined forward — differentiable end to end (the
    reverse microbatch schedule falls out of ppermute's transpose)."""
    out = pipeline_apply(stage_fn, stacked_params, x, mesh=mesh,
                         n_microbatches=n_microbatches, axis=axis)
    return loss_fn(out, y)
