"""Bucketed gradient reduction with compute/communication overlap.

BENCH_r05 measured the monolithic DP formulation *losing* to the
reference's own coarse-sync parameter averaging
(`resnet20_dp_allreduce_vs_paramavg_speedup` = 0.9597): GSPMD emits the
gradient allreduce as one barrier at the end of backward, so every step
pays full latency for every gradient leaf before the update can start.
This module implements the classic overlap design characterized for
TF/CUDA-aware-MPI clusters in arXiv:1810.11112 — break the gradient
pytree into size-targeted **buckets**, ordered by *reverse layer order*
(the gradients backward produces first reduce first), and issue one
collective per bucket:

- each bucket's collective depends only on that bucket's grad leaves, so
  XLA's async-collective scheduler can launch it while backward compute
  for earlier layers is still in flight, and the optimizer update for a
  reduced bucket can start while later buckets are still reducing — the
  per-leaf dataflow of the update gives the scheduler that freedom;
- on chatty interconnects (the 8-virtual-device CPU mesh the DP bench
  runs on; DCN fleets) bucketing also amortizes per-collective dispatch
  latency: ~65 per-leaf allreduces become a handful of flat ones.

`BucketPlan` is pure metadata derived from the param pytree structure —
identical on every process by construction (no host nondeterminism; the
collective-consistency stage re-traces it under simulated ranks), and
`bucketed_reduce` below is the repo's ONE blessed site for collectives
on gradient pytrees (graftlint G015; `nn/training.py` consumes it).

The train-step integration (`nn/training.make_train_step(...,
overlap=BucketPlan)`) computes per-shard gradients under `shard_map` and
reduces them here; the optimizer update runs in the enclosing jit, so
the formulation composes with `zero1_opt_shardings` (the reduce-scatter
weight-update placement) unchanged.

jax imports stay inside functions: the module must remain importable
under graftlint's no-jax package stubs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

# TPU-oriented default (a few fused allreduces per step for O(100M)-param
# models). The DP bench sweeps much smaller sizes: on the virtual-CPU
# mesh the per-collective dispatch cost is low enough that finer buckets
# win (r7 sweep: 64-256KB beat a single fused vector by ~8%).
DEFAULT_BUCKET_BYTES = 4 << 20

_REDUCE_MODES = ("psum", "psum_scatter")


@dataclass(frozen=True)
class Bucket:
    """One reduction unit: a contiguous run of grad leaves (in reverse
    layer order) reduced as a single flat vector."""

    index: int
    paths: Tuple[str, ...]        # jax.tree_util.keystr leaf paths
    leaf_ids: Tuple[int, ...]     # positions in the canonical flatten order
    n_elements: int
    n_bytes: int                  # at the reduction dtype


@dataclass(frozen=True)
class BucketPlan:
    """Deterministic partition of a grads pytree into reduction buckets.

    Derived purely from the pytree structure + static sizes, so every
    process computes the identical plan (and therefore issues the
    identical per-bucket collective sequence — the property the
    stage-3 `distributed/overlap_step_2x4` entry freezes)."""

    buckets: Tuple[Bucket, ...]
    bucket_bytes: int
    reduce_dtype: str = "float32"
    mode: str = "psum"            # or "psum_scatter"

    @property
    def n_leaves(self) -> int:
        return sum(len(b.paths) for b in self.buckets)

    @property
    def n_elements(self) -> int:
        return sum(b.n_elements for b in self.buckets)

    def leaf_paths(self) -> Tuple[str, ...]:
        return tuple(p for b in self.buckets for p in b.paths)

    def summary(self) -> dict:
        """Telemetry-ready description (the `bucket_plan` event)."""
        return {
            "n_buckets": len(self.buckets),
            "bucket_bytes": self.bucket_bytes,
            "mode": self.mode,
            "reduce_dtype": self.reduce_dtype,
            "n_leaves": self.n_leaves,
            "n_elements": self.n_elements,
            "buckets": [{"index": b.index, "n_leaves": len(b.paths),
                         "bytes": b.n_bytes} for b in self.buckets],
        }


def _keystr(path) -> str:
    import jax

    return jax.tree_util.keystr(path)


def plan_buckets(tree, bucket_bytes: int = DEFAULT_BUCKET_BYTES, *,
                 layer_order: Optional[Sequence[str]] = None,
                 reduce_dtype: str = "float32",
                 mode: str = "psum") -> BucketPlan:
    """Partition `tree` (params or grads — same structure) into
    size-targeted buckets by REVERSE layer order.

    Greedy pack over the reversed leaf sequence: a bucket closes when
    adding the next leaf would exceed `bucket_bytes` (a single oversized
    leaf still gets its own bucket). `layer_order` — the network's
    top-level layer names in forward order — pins "layer order" to the
    model's actual topology; without it the pytree flatten order (sorted
    dict keys) stands in. Deterministic: equal trees -> equal plans on
    every process.
    """
    import numpy as np

    import jax

    if mode not in _REDUCE_MODES:
        raise ValueError(f"mode must be one of {_REDUCE_MODES}, got {mode!r}")
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    flat, _treedef = jax.tree_util.tree_flatten_with_path(tree)
    if not flat:
        raise ValueError("cannot plan buckets over an empty pytree")
    itemsize = np.dtype(reduce_dtype).itemsize
    order = list(range(len(flat)))
    if layer_order is not None:
        pos = {name: i for i, name in enumerate(layer_order)}

        def layer_pos(i):
            path = flat[i][0]
            key = getattr(path[0], "key", getattr(path[0], "name", None)) \
                if path else None
            return pos.get(key, len(pos))

        order.sort(key=lambda i: (layer_pos(i), i))
    order.reverse()  # last-computed gradients reduce first

    buckets = []
    cur_ids, cur_elems = [], 0
    for i in order:
        size = int(flat[i][1].size)
        if cur_ids and (cur_elems + size) * itemsize > bucket_bytes:
            buckets.append((tuple(cur_ids), cur_elems))
            cur_ids, cur_elems = [], 0
        cur_ids.append(i)
        cur_elems += size
    if cur_ids:
        buckets.append((tuple(cur_ids), cur_elems))
    return BucketPlan(
        buckets=tuple(
            Bucket(index=bi, paths=tuple(_keystr(flat[i][0]) for i in ids),
                   leaf_ids=ids, n_elements=elems,
                   n_bytes=elems * itemsize)
            for bi, (ids, elems) in enumerate(buckets)),
        bucket_bytes=int(bucket_bytes), reduce_dtype=reduce_dtype,
        mode=mode)


def bucketed_reduce(grads, plan: BucketPlan, axis_name: str, *,
                    mean: bool = True):
    """Cross-replica reduction of a grads pytree, one collective per
    bucket in plan order (reverse layer order). Call inside `shard_map`
    with `axis_name` bound.

    THE blessed site for collectives on gradient pytrees (G015): every
    bucket is flattened into one `reduce_dtype` vector and reduced with
    `psum` (or `psum_scatter` + `all_gather` in reduce-scatter mode —
    same math, the decomposed collective), then sliced back to the leaf
    shapes/dtypes. Exact cover is asserted against the plan at trace
    time, so a plan built for a different tree fails loudly.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
    paths = tuple(_keystr(p) for p, _ in flat)
    if sorted(paths) != sorted(plan.leaf_paths()):
        raise ValueError(
            f"bucket plan does not cover this grads pytree: plan has "
            f"{plan.n_leaves} leaves, grads have {len(paths)} "
            f"(first mismatch: "
            f"{sorted(set(paths) ^ set(plan.leaf_paths()))[:3]})")
    leaves = [l for _, l in flat]
    n = lax.psum(1, axis_name)
    dtype = jnp.dtype(plan.reduce_dtype)
    out = [None] * len(leaves)
    for bucket in plan.buckets:
        segs = [jnp.ravel(leaves[i]).astype(dtype) for i in bucket.leaf_ids]
        vec = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
        if plan.mode == "psum_scatter":
            pad = (-vec.size) % n
            if pad:
                vec = jnp.concatenate([vec, jnp.zeros((pad,), dtype)])
            shard = lax.psum_scatter(vec, axis_name, scatter_dimension=0,
                                     tiled=True)
            vec = lax.all_gather(shard, axis_name, tiled=True)
            if pad:
                vec = vec[:bucket.n_elements]
        else:
            vec = lax.psum(vec, axis_name)
        if mean:
            vec = vec / n
        off = 0
        for i in bucket.leaf_ids:
            leaf = leaves[i]
            out[i] = (vec[off:off + leaf.size].reshape(leaf.shape)
                      .astype(leaf.dtype))
            off += leaf.size
    return jax.tree_util.tree_unflatten(treedef, out)


@dataclass(frozen=True)
class SparseBucket:
    """One sparse reduction unit: a gradient that travels as a fixed-shape
    COO pair — `indices [n_rows]` naming embedding-table rows and
    `values [n_rows, dim]` carrying their gradients — and is NEVER
    materialized at the table's shape on the wire (the dense-softmax-
    over-vocab anti-pattern G030 flags). Pure metadata like `Bucket`:
    derived from static batch shapes, identical on every process."""

    name: str
    n_rows: int                   # rows per participant (fixed shape)
    dim: int
    n_participants: int = 1
    index_dtype: str = "int32"
    value_dtype: str = "float32"

    @property
    def n_bytes(self) -> int:
        """Per-participant wire bytes: indices + values."""
        import numpy as np

        return self.n_rows * (np.dtype(self.index_dtype).itemsize
                              + self.dim * np.dtype(self.value_dtype).itemsize)

    @property
    def gathered_bytes(self) -> int:
        """Bytes each participant holds after the all-gather."""
        return self.n_bytes * self.n_participants

    def summary(self) -> dict:
        """Telemetry-ready description (rides the `bucket_plan` event
        next to the dense BucketPlan summaries)."""
        return {
            "kind": "sparse", "name": self.name, "n_rows": self.n_rows,
            "dim": self.dim, "n_participants": self.n_participants,
            "bytes": self.n_bytes, "gathered_bytes": self.gathered_bytes,
        }


def plan_sparse_bucket(name: str, n_rows: int, dim: int, *,
                       n_participants: int = 1,
                       index_dtype: str = "int32",
                       value_dtype: str = "float32") -> SparseBucket:
    """Plan one sparse (indices, values) bucket. Like `plan_buckets`,
    this is pure static metadata — every process derives the identical
    plan from the identical batch shapes."""
    if n_rows <= 0 or dim <= 0:
        raise ValueError(f"sparse bucket needs positive n_rows/dim, got "
                         f"({n_rows}, {dim})")
    if n_participants <= 0:
        raise ValueError(f"n_participants must be positive, "
                         f"got {n_participants}")
    return SparseBucket(name=name, n_rows=int(n_rows), dim=int(dim),
                        n_participants=int(n_participants),
                        index_dtype=index_dtype, value_dtype=value_dtype)


def sparse_bucket_reduce(indices, values, axis_name: str, *,
                         bucket: Optional[SparseBucket] = None):
    """Cross-replica exchange of a sparse gradient bucket: all-gather the
    (indices, values) COO pair over `axis_name` so every participant can
    scatter-add the rows it owns. Call inside `shard_map` with
    `axis_name` bound.

    THE blessed site for collectives on sparse embedding gradients (the
    sparse counterpart of `bucketed_reduce`): the pair stays COO on the
    wire — `(n * n_rows)` indices and `(n * n_rows, dim)` values — and
    is never expanded to the table's shape (G030's densification
    anti-pattern). Duplicate indices across participants are fine: the
    owner's scatter-add sums them, which is exactly the dense formulation's
    semantics. When a `bucket` plan is passed, the traced shapes are
    checked against it so a plan built for different batch shapes fails
    loudly at trace time."""
    from jax import lax

    if values.ndim != 2 or indices.ndim != 1 \
            or values.shape[0] != indices.shape[0]:
        raise ValueError(
            f"sparse bucket expects indices [R] + values [R, D], got "
            f"{indices.shape} / {values.shape}")
    if bucket is not None:
        if (indices.shape[0] != bucket.n_rows
                or values.shape[1] != bucket.dim):
            raise ValueError(
                f"sparse bucket plan {bucket.name!r} is for "
                f"({bucket.n_rows}, {bucket.dim}) rows, traced shapes are "
                f"{indices.shape} / {values.shape}")
    gathered_idx = lax.all_gather(indices, axis_name, tiled=True)
    gathered_vals = lax.all_gather(values, axis_name, tiled=True)
    return gathered_idx, gathered_vals


def reduce_gradients(grads, axis_names, *, mean: bool = True):
    """Unbucketed cross-replica gradient mean over one or more bound
    axes — the blessed routing for manual-collective train steps that do
    not bucket (sequence parallelism). Per-axis tree-level pmean, same
    primitive sequence the SP step always issued (frozen stage-3
    signature unchanged)."""
    from jax import lax

    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    for ax in axis_names:
        # whole-tree pmean: ONE multi-operand psum eqn per axis — the
        # exact eqn sequence the callers always issued
        grads = lax.pmean(grads, ax) if mean else lax.psum(grads, ax)
    return grads


def pmean_float_leaves(tree, axis_name: str):
    """Average float leaves over `axis_name`, pass integer leaves (step
    counters) through — the replicated-output contract for per-shard
    mutable layer state (BatchNorm running stats computed on local batch
    shards leave the step as the cross-replica average; the same
    averaging the SP step and the param-averaging trainer apply)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def avg(a):
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
            return lax.pmean(a, axis_name)
        return a

    return jax.tree.map(avg, tree)
