"""Multi-process / multi-host control plane.

Replaces the reference's scaleout stack — the Akka master/worker actors
with heartbeat-based dead-worker removal
(`deeplearning4j-scaleout/deeplearning4j-scaleout-akka/.../MasterActor.java:61-158`),
the ZooKeeper configuration registry
(`deeplearning4j-scaleout-zookeeper/.../ZooKeeperConfigurationRegister.java`),
and the Hazelcast distributed state tracker (`HazelCastStateTracker.java`) —
with a single small TCP coordinator plus worker clients:

- **ClusterCoordinator**: registry (worker ranks), heartbeat monitor with
  dead-worker removal, a JSON config registry, synchronization barriers,
  and synchronous parameter-averaging rounds (the Spark master's
  aggregate-and-broadcast, elastic: a round completes with whoever is
  still alive when a contributor dies mid-round). With `snapshot_path`
  the registry/claim state persists to JSON on every mutation and a
  restarted coordinator reloads it (HazelCastStateTracker semantics) —
  paired with the client's reconnect-and-re-register, the control plane
  itself is no longer a single in-memory point of failure.
- **ClusterClient**: register/heartbeat/config/barrier/average calls.
- **run_elastic_worker**: the worker training loop — local steps on the
  worker's data shard, parameter averaging every `sync_every` steps,
  checkpoint via ModelSerializer after each sync, resume-from-checkpoint
  on restart (elastic recovery: kill a worker, restart it, it rejoins
  from the last checkpoint).
- **initialize_multihost**: thin wrapper over `jax.distributed.initialize`
  for REAL multi-host TPU pods — there the ICI/DCN collectives inside a
  jitted step replace host-side averaging entirely; this module's
  coordinator still provides registration/heartbeat/elastic restart
  around it.

The wire protocol is a newline-delimited JSON control line, optionally
followed by a length-prefixed raw float32 frame (the JSON line carries
`payload_bytes`): control messages stay human-debuggable JSON while
parameter vectors travel as binary — no base64 bloat (~33%) and no full
string copy per round, so 100MB+ models move at socket speed. Latency is
amortized: one round-trip per averaging round, not per step.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
from typing import Dict, Optional

import numpy as np


def _to_bytes(arr: np.ndarray) -> bytes:
    return np.ascontiguousarray(arr, np.float32).tobytes()


def _from_bytes(payload: bytes) -> np.ndarray:
    return np.frombuffer(payload, np.float32).copy()


def _send_msg(sock: socket.socket, obj, payload: Optional[bytes] = None) -> None:
    """One message = JSON header line (+ optional raw binary frame whose
    length the header announces in `payload_bytes`)."""
    if payload is not None:
        obj = dict(obj, payload_bytes=len(payload))
    sock.sendall((json.dumps(obj) + "\n").encode())
    if payload:  # separate send: no header+payload concatenation copy
        sock.sendall(payload)


# Upper bound on a single binary frame (1 GiB ~= a 268M-param float32
# flat view — far above any model this coordinator averages). A corrupt
# or hostile header cannot make the peer allocate arbitrary memory in one
# read (ADVICE r3). Module-level and read at CALL time, so genuinely
# larger models raise it process-wide (cluster.MAX_FRAME_BYTES = ...),
# or per-endpoint via the max_frame_bytes constructor args.
MAX_FRAME_BYTES = 1 << 30


def _recv_msg(fileobj, max_frame_bytes: Optional[int] = None):
    """Read (msg, payload) from a BINARY buffered stream; payload is None
    for pure-control messages (header without `payload_bytes`) and b"" for
    an announced zero-length frame."""
    cap = MAX_FRAME_BYTES if max_frame_bytes is None else max_frame_bytes
    line = fileobj.readline()
    if not line:
        raise ConnectionError("peer closed")
    msg = json.loads(line)
    n = msg.pop("payload_bytes", None)
    payload = None
    if n is not None:
        n = int(n)
        if n < 0 or n > cap:
            raise ConnectionError(
                f"frame of {n} bytes exceeds the {cap}-byte "
                "limit (corrupt header? raise cluster.MAX_FRAME_BYTES "
                "for larger models)")
        payload = fileobj.read(n)
        if payload is None or len(payload) < n:
            raise ConnectionError("peer closed mid-payload")
    return msg, payload


class _Round:
    """One synchronous averaging/barrier round."""

    def __init__(self):
        self.contributions: Dict[str, np.ndarray] = {}
        self.done = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.result_bytes: Optional[bytes] = None


class ClusterCoordinator:
    """Master actor + config registry + state tracker in one process.

    Start with `coord = ClusterCoordinator().start()`; workers connect to
    `coord.address`. `heartbeat_timeout` controls dead-worker removal
    (reference MasterActor clears disconnected workers on heartbeat,
    MasterActor.java:111-158).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 heartbeat_timeout: float = 10.0,
                 round_timeout: Optional[float] = None,
                 snapshot_path: Optional[str] = None):
        self.heartbeat_timeout = heartbeat_timeout
        # max wall time an averaging round waits for alive-but-silent
        # workers before finishing without them (progress guarantee; a
        # worker whose local step takes longer than this is misconfigured)
        self.round_timeout = (round_timeout if round_timeout is not None
                              else 6.0 * heartbeat_timeout)
        self._lock = threading.RLock()
        self._workers: Dict[str, dict] = {}
        self._ranks: Dict[str, int] = {}  # stable across re-registration
        self._configs: Dict[str, dict] = {}
        self._next_rank = 0
        self._avg_rounds: Dict[int, _Round] = {}
        self._barriers: Dict[str, _Round] = {}
        # durable registry/claim state (HazelCastStateTracker semantics):
        # every mutation snapshots {ranks, configs, workers} to JSON, and a
        # restarted coordinator reloads it — shard claims (config keys
        # "shard_owner/<s>") and ranks survive a coordinator crash, so the
        # fleet resumes instead of re-sharding from scratch. In-flight
        # averaging rounds are NOT persisted: contributors' reconnect
        # logic simply re-submits and a fresh round forms.
        self.snapshot_path = snapshot_path
        if snapshot_path and os.path.exists(snapshot_path):
            with open(snapshot_path) as fh:
                snap = json.load(fh)
            self._ranks = {w: int(r) for w, r in snap.get("ranks", {}).items()}
            self._next_rank = int(snap.get("next_rank", len(self._ranks)))
            self._configs = dict(snap.get("configs", {}))
            # restored workers start provisionally alive: their clients'
            # heartbeats re-confirm within one interval, and treating them
            # dead instead would let a fast re-claimer steal their shard
            # slots during the restart gap
            now = time.monotonic()
            self._workers = {w: {"rank": self._ranks[w], "last_seen": now}
                             for w in snap.get("workers", [])
                             if w in self._ranks}

        coord = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    while True:
                        msg, payload = _recv_msg(self.rfile)
                        reply, reply_payload = coord._dispatch(msg, payload)
                        _send_msg(self.request, reply, reply_payload)
                except (ConnectionError, OSError, json.JSONDecodeError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = "%s:%d" % self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ClusterCoordinator":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @property
    def port(self) -> int:
        """Bound TCP port (rebind a restarted coordinator to the same one
        so reconnecting clients find it)."""
        return self._server.server_address[1]

    def _save_snapshot(self) -> None:
        """Persist registry/claim state; call under self._lock after every
        mutation. Atomic tmp+replace so a crash mid-write leaves the
        previous snapshot intact."""
        if not self.snapshot_path:
            return
        snap = {"version": 1, "ranks": self._ranks,
                "next_rank": self._next_rank, "configs": self._configs,
                "workers": sorted(self._workers)}
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(snap, fh)
        os.replace(tmp, self.snapshot_path)

    # ------------------------------------------------------------- queries
    def record_config(self, key: str, value) -> None:
        """In-process config write (no socket round-trip) — the elastic
        supervisor journals fleet generations through this, making every
        re-form durable in the same snapshot the ranks live in."""
        with self._lock:
            self._configs[key] = value
            self._save_snapshot()

    def read_config(self, key: str, default=None):
        with self._lock:
            return self._configs.get(key, default)

    def alive_workers(self):
        now = time.monotonic()
        with self._lock:
            dead = [w for w, info in self._workers.items()
                    if now - info["last_seen"] > self.heartbeat_timeout]
            for w in dead:  # dead-worker removal (MasterActor semantics)
                del self._workers[w]
            if dead:
                self._save_snapshot()
            return dict(self._workers)

    # ------------------------------------------------------------ dispatch
    def _dispatch(self, msg: dict, payload: Optional[bytes] = None):
        """Returns (reply_dict, reply_payload_bytes_or_None)."""
        op = msg.get("op")
        if op == "register":
            with self._lock:
                wid = msg["worker"]
                reassigned_from = None
                if wid not in self._ranks and msg.get("replace_dead"):
                    # elastic replacement: a NEW worker adopts the
                    # lowest rank whose owner left the alive set (died,
                    # deregistered, or heartbeat-expired), so a re-formed
                    # fleet keeps a dense [0, N') rank space instead of
                    # growing fresh ranks past dead ones. A known wid
                    # never reassigns — rejoining workers keep their own
                    # rank (the snapshot-restore invariant).
                    alive = self.alive_workers()
                    for old, rank in sorted(self._ranks.items(),
                                            key=lambda kv: kv[1]):
                        if old not in alive:
                            del self._ranks[old]
                            self._ranks[wid] = rank
                            reassigned_from = old
                            break
                if wid not in self._ranks:
                    self._ranks[wid] = self._next_rank
                    self._next_rank += 1
                self._workers[wid] = {"rank": self._ranks[wid],
                                      "last_seen": time.monotonic()}
                self._save_snapshot()
                return {"ok": True, "rank": self._ranks[wid],
                        "reassigned_from": reassigned_from,
                        "n_workers": len(self._workers),
                        "heartbeat_timeout": self.heartbeat_timeout,
                        "round_timeout": self.round_timeout}, None
        if op == "heartbeat":
            with self._lock:
                if msg["worker"] in self._workers:
                    self._workers[msg["worker"]]["last_seen"] = time.monotonic()
                    return {"ok": True}, None
            return {"ok": False, "error": "unknown worker (re-register)"}, None
        if op == "deregister":
            with self._lock:
                self._workers.pop(msg["worker"], None)
                self._save_snapshot()
            return {"ok": True}, None
        if op == "workers":
            return {"ok": True, "workers": sorted(self.alive_workers())}, None
        if op == "set_config":
            with self._lock:
                self._configs[msg["key"]] = msg["value"]
                self._save_snapshot()
            return {"ok": True}, None
        if op == "get_config":
            with self._lock:
                if msg["key"] not in self._configs:
                    return {"ok": False, "error": "no such config"}, None
                return {"ok": True, "value": self._configs[msg["key"]]}, None
        if op == "claim_slot":
            # atomic data-shard claim (the read-modify-write happens under
            # the coordinator lock — a set_config/get_config read-back is
            # racy): assign the caller the lowest slot in [0, n_slots)
            # that is unclaimed, already its own, or whose owner left the
            # alive set. Claims live in the config registry under
            # "shard_owner/<s>" so operators can inspect them.
            with self._lock:
                alive = set(self.alive_workers())
                wid = msg["worker"]
                n = int(msg["n_slots"])
                # the caller's EXISTING claim wins over reassignable
                # slots: otherwise a re-claiming worker could be handed a
                # lower dead-owner slot while still registered as its old
                # slot's (alive) owner, orphaning that shard forever
                for s in range(n):
                    if self._configs.get(f"shard_owner/{s}") == wid:
                        return {"ok": True, "slot": s}, None
                for s in range(n):
                    key = f"shard_owner/{s}"
                    owner = self._configs.get(key)
                    if owner is None or owner not in alive:
                        self._configs[key] = wid
                        self._save_snapshot()
                        return {"ok": True, "slot": s}, None
                return {"ok": True, "slot": None}, None
        if op == "average":
            return self._average(msg, payload)
        if op == "barrier":
            return self._barrier(msg), None
        return {"ok": False, "error": f"unknown op {op!r}"}, None

    # ----------------------------------------------------- averaging round
    def _average(self, msg: dict, payload: bytes):
        step = int(msg["step"])
        worker = msg["worker"]
        arr = _from_bytes(payload)
        with self._lock:
            if worker in self._workers:
                self._workers[worker]["last_seen"] = time.monotonic()
            rnd = self._avg_rounds.setdefault(step, _Round())
            if not rnd.done.is_set():
                rnd.contributions[worker] = arr
                if set(rnd.contributions) >= set(self.alive_workers()):
                    self._finish_round(rnd)
        # elastic completion: the liveness re-check finishes the round as
        # soon as every still-alive worker has contributed (dead workers
        # drop out via heartbeat expiry); round_timeout is the last-resort
        # progress guarantee against alive-but-stuck contributors
        deadline = time.monotonic() + self.round_timeout
        while not rnd.done.wait(timeout=0.05):
            with self._lock:
                if not rnd.done.is_set() and (
                        set(rnd.contributions) >= set(self.alive_workers())
                        or time.monotonic() > deadline):
                    self._finish_round(rnd)
        with self._lock:
            # completed rounds stay cached so a straggler contributing to an
            # already-finished step gets the same result instead of opening
            # (and hanging on) a fresh round; prune well-past steps
            for old in [k for k in self._avg_rounds if k < step - 16]:
                del self._avg_rounds[old]
        return ({"ok": True, "n": len(rnd.contributions)},
                rnd.result_bytes)

    def _finish_round(self, rnd: _Round) -> None:
        if rnd.done.is_set():
            return
        rnd.result = np.mean(list(rnd.contributions.values()), axis=0)
        # serialize ONCE per round, not once per contributor's reply
        rnd.result_bytes = _to_bytes(rnd.result)
        rnd.done.set()

    # -------------------------------------------------------------- barrier
    def _barrier(self, msg: dict) -> dict:
        name = msg["name"]
        worker = msg["worker"]
        with self._lock:
            rnd = self._barriers.setdefault(name, _Round())
            rnd.contributions[worker] = np.zeros(0)
            if set(rnd.contributions) >= set(self.alive_workers()):
                rnd.done.set()
        deadline = time.monotonic() + self.round_timeout
        while not rnd.done.wait(timeout=0.05):
            with self._lock:
                if (set(rnd.contributions) >= set(self.alive_workers())
                        or time.monotonic() > deadline):
                    rnd.done.set()
        with self._lock:
            self._barriers.pop(name, None)
        return {"ok": True}


class ClusterClient:
    """Worker-side connection to the coordinator (one socket, heartbeats on
    a daemon thread — the worker actor's heartbeat loop).

    Survives a coordinator restart: calls and heartbeats that hit a dead
    socket reconnect with backoff for up to ``reconnect_timeout`` seconds
    and re-register (ranks and shard claims are stable — the restarted
    coordinator reloads them from its snapshot), so a fleet rides through
    a kill-and-restart of the control plane without losing claims."""

    def __init__(self, address: str, worker_id: str,
                 heartbeat_interval: float = 1.0,
                 reconnect_timeout: float = 30.0,
                 replace_dead: bool = False):
        host, port = address.rsplit(":", 1)
        self.address = (host, int(port))
        self.worker_id = worker_id
        self.reconnect_timeout = reconnect_timeout
        # replacement worker (elastic re-form): adopt the lowest rank
        # whose owner is no longer alive instead of minting a new one
        self.replace_dead = replace_dead
        self.reassigned_from = None
        self._lock = threading.Lock()
        self._sock = None
        self._file = None
        with self._lock:
            self._reconnect()  # initial connect retries like any other
        self._hb_stop = threading.Event()
        self._hb = threading.Thread(
            target=self._heartbeat_loop, args=(heartbeat_interval,),
            daemon=True)
        self._hb.start()

    def _connect_once(self) -> None:
        """One connection + registration attempt (caller holds _lock)."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = socket.create_connection(self.address, timeout=120)
        self._file = self._sock.makefile("rb")
        reg = {"op": "register", "worker": self.worker_id}
        if self.replace_dead:
            reg["replace_dead"] = True
        _send_msg(self._sock, reg)
        reply, _ = _recv_msg(self._file)
        self.rank = reply["rank"]
        self.reassigned_from = reply.get("reassigned_from")
        # a blocked average() waits up to the server's round_timeout; give
        # the socket comfortable headroom beyond it
        self._sock.settimeout(2.0 * reply.get("round_timeout", 60.0) + 60.0)

    def _reconnect(self) -> None:
        """Connect/re-register with FULL-JITTER exponential backoff until
        reconnect_timeout (caller holds _lock) — the window a restarting
        coordinator has to come back up. Jitter matters here even more
        than in the rendezvous bootstrap: after an elastic re-form every
        surviving worker reconnects at once, and synchronized retry
        waves are exactly the thundering herd a tiny single-threaded
        accept queue cannot absorb."""
        from deeplearning4j_tpu.distributed.bootstrap import Backoff

        backoff = Backoff(base=0.1, cap=2.0,
                          max_elapsed=self.reconnect_timeout)
        while True:
            try:
                self._connect_once()
                return
            except (ConnectionError, OSError):
                if not backoff.pause():
                    raise

    def _call(self, msg: dict, payload: Optional[bytes] = None):
        msg = dict(msg, worker=self.worker_id)
        with self._lock:
            deadline = time.monotonic() + self.reconnect_timeout
            while True:
                try:
                    _send_msg(self._sock, msg, payload)
                    reply, reply_payload = _recv_msg(self._file)
                    break
                except (ConnectionError, OSError):
                    # dead socket (coordinator restart?): the ops are safe
                    # to re-send — registration/config/claims are
                    # idempotent and an average contribution is keyed by
                    # (step, worker). deregister is NOT retried: a dead
                    # coordinator forgets us anyway.
                    if (msg.get("op") == "deregister"
                            or time.monotonic() > deadline):
                        raise
                    self._reconnect()
        if not reply.get("ok"):
            raise RuntimeError(f"coordinator error: {reply.get('error')}")
        return reply, reply_payload

    def _heartbeat_loop(self, interval: float) -> None:
        # injected `drop-heartbeat` fault: this worker goes silent (the
        # coordinator reaps it after heartbeat_timeout and its shard slot
        # becomes claimable) while the process itself stays alive — the
        # partial-failure mode a kill can't simulate
        from deeplearning4j_tpu.distributed.faults import active_faults
        from deeplearning4j_tpu.telemetry.recorder import get_default

        faults = active_faults()
        if faults.drop_heartbeat:
            get_default().fault("drop-heartbeat", worker=self.worker_id,
                                fired=True)
            return
        # separate connection so heartbeats never queue behind a long
        # averaging round; a broken socket is dropped and re-dialed on the
        # next beat (coordinator-restart tolerance)
        sock = None
        f = None
        while not self._hb_stop.wait(interval):
            try:
                if sock is None:
                    sock = socket.create_connection(self.address, timeout=30)
                    f = sock.makefile("rb")
                _send_msg(sock, {"op": "heartbeat", "worker": self.worker_id})
                reply, _ = _recv_msg(f)
                if not reply.get("ok") and not self._hb_stop.is_set():
                    # demoted after a transient stall: re-register (the
                    # coordinator keeps ranks stable across re-registration).
                    # The _hb_stop guard avoids re-registering a worker whose
                    # close() already deregistered it (in-flight heartbeat).
                    _send_msg(sock, {"op": "register",
                                     "worker": self.worker_id})
                    _recv_msg(f)
            except (OSError, ConnectionError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                sock = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # ---------------------------------------------------------------- API
    def workers(self):
        return self._call({"op": "workers"})[0]["workers"]

    def set_config(self, key: str, value) -> None:
        self._call({"op": "set_config", "key": key, "value": value})

    def get_config(self, key: str, default=None):
        """Config value, or `default` for a key nobody has set."""
        try:
            return self._call({"op": "get_config", "key": key})[0]["value"]
        except RuntimeError as e:
            if "no such config" in str(e):
                return default
            raise

    def barrier(self, name: str) -> None:
        self._call({"op": "barrier", "name": name})

    def average(self, step: int, flat_params: np.ndarray) -> np.ndarray:
        _, payload = self._call({"op": "average", "step": step},
                                _to_bytes(flat_params))
        return _from_bytes(payload)

    def close(self, deregister: bool = True) -> None:
        """deregister=False drops the connection but keeps the worker in
        the coordinator's alive set until heartbeat expiry — a probe
        handing off to a training client under the SAME worker_id uses it
        so a claimed shard slot cannot be stolen during the handoff."""
        self._hb_stop.set()
        if deregister:
            try:
                self._call({"op": "deregister"})
            except Exception:
                pass
        self._sock.close()

    def claim_slot(self, n_slots: int):
        """Atomically claim a data-shard slot in [0, n_slots); None when
        every slot is held by an alive worker (retry after a beat)."""
        return self._call({"op": "claim_slot",
                           "n_slots": int(n_slots)})[0]["slot"]


# ---------------------------------------------------------------- training

def run_elastic_worker(address: str, worker_id: str, net, batches, *,
                       sync_every: int = 1, checkpoint_path: Optional[str] = None,
                       epochs: int = 1, client: Optional["ClusterClient"] = None):
    """Elastic data-parallel worker loop (multi-PROCESS param averaging).

    net: an initialized MultiLayerNetwork/ComputationGraph; batches: this
    worker's shard as a list of DataSets (the RDD partition analogue).
    Every `sync_every` local steps the flat parameter vector is averaged
    across alive workers through the coordinator and written back; after
    each sync the model is checkpointed, and a restarted worker resumes
    from the checkpoint's step counter (reference: the Spark master's
    fault tolerance came from RDD lineage; here it is
    checkpoint-and-rejoin).

    Returns the trained net.
    """
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    start_step = 0
    if checkpoint_path and os.path.exists(checkpoint_path):
        # copy the checkpoint's arrays into the CALLER's net so runtime
        # configuration (mesh, listeners, custom optimizer) survives the
        # restart — replacing the object would silently drop them
        restored = ModelSerializer.restore(checkpoint_path)
        if net.params is None:
            net.init()
        import jax

        if (jax.tree.structure(restored.params) != jax.tree.structure(net.params)
                or [l.shape for l in jax.tree.leaves(restored.params)]
                != [l.shape for l in jax.tree.leaves(net.params)]):
            raise ValueError(
                f"checkpoint {checkpoint_path} holds a different architecture "
                "than the worker's net — delete the stale checkpoint or pass "
                "the matching configuration")
        net.params = restored.params
        net.opt_state = restored.opt_state
        net.state = restored.state
        net.iteration_count = restored.iteration_count
        start_step = restored.iteration_count
    # accepting a live client keeps a claimed shard slot heartbeating
    # through the caller's setup gap — a fresh registration here would
    # leave the slot sweepable for one heartbeat_timeout (ADVICE r4)
    client = client or ClusterClient(address, worker_id)
    try:
        if net.params is None:
            net.init()
        step = 0
        for _ in range(epochs):
            for ds in batches:
                step += 1
                if step <= start_step:
                    continue  # fast-forward a resumed worker
                net.fit(ds)
                if step % sync_every == 0:
                    avg = client.average(step, net.params_flat())
                    net.set_params_flat(avg)
                    if checkpoint_path:
                        tmp = checkpoint_path + ".tmp"
                        ModelSerializer.write_model(net, tmp)
                        os.replace(tmp, checkpoint_path)
    finally:
        client.close()
    return net


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         local_device_ids=None) -> None:
    """Initialize jax's multi-host runtime for REAL TPU pod slices.

    On Cloud TPU all arguments may be omitted: jax auto-detects the
    coordinator, process count, and process id from the TPU metadata
    server (this is how TpuPodLauncher's broadcast launch works).

    After this, `jax.devices()` spans all hosts and a Mesh over them makes
    jitted steps communicate over ICI/DCN via XLA collectives — the
    TPU-native replacement for the reference's Spark/Akka data plane. The
    ClusterCoordinator above remains useful purely as control plane
    (registration, elastic restart, config registry).

    Compatibility alias: the hardened implementation (env contract,
    retry/backoff, CPU-fleet collectives, per-process telemetry) lives in
    `distributed/bootstrap.py` — new code should call
    `distributed.bootstrap.initialize` directly.
    """
    from deeplearning4j_tpu.distributed import bootstrap

    bootstrap.initialize(coordinator_address=coordinator_address,
                         num_processes=num_processes,
                         process_id=process_id,
                         local_device_ids=local_device_ids)
