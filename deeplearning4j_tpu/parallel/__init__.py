"""Distributed training (replaces reference deeplearning4j-scaleout:
dl4j-spark parameter averaging, scaleout-akka actors, Hazelcast state,
ZooKeeper config — SURVEY.md §2.4).

On TPU the whole communication backend is XLA collectives compiled over the
ICI mesh (DCN across slices); the host control plane is jax.distributed,
brought up through `deeplearning4j_tpu.distributed.bootstrap` (rendezvous
env contract, retry/backoff, per-process telemetry).
"""

from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    replicate,
    shard_batch,
    spans_processes,
)
from deeplearning4j_tpu.parallel.data_parallel import (  # noqa: F401
    DataParallelTrainer,
    ParameterAveragingTrainer,
)
from deeplearning4j_tpu.parallel.tensor_parallel import (  # noqa: F401
    TRANSFORMER_TP_RULES,
    shard_params,
    sharding_for,
)
from deeplearning4j_tpu.parallel.ring_attention import ring_attention  # noqa: F401
