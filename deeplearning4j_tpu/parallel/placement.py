"""Unified mesh/axes entry point for the network containers.

`net.set_mesh(mesh, axes={...})` is the single switch that turns a
builder-API network distributed (the capability the reference reached
only through the Spark/Akka masters, SparkDl4jMultiLayer.java:335 — and
only for data parallelism). Roles map to mesh axis names:

    net.set_mesh(mesh, axes={"data": "data"})                  # DP
    net.set_mesh(mesh, axes={"data": "data", "model": "model"})# DP x TP
    net.set_mesh(mesh, axes={"data": "data", "model": "model",
                             "pipe": "pipe"}, n_microbatches=8)# DP x TP x PP
    net.set_mesh(mesh, axes={"data": "data", "expert": "expert"})  # DP x EP
    net.set_mesh(mesh, axes={"data": "data", "seq": "seq"})    # DP x SP

- "data": batch leaves shard over the axis; XLA inserts the gradient
  allreduce (replaces the Spark broadcast/accumulator round-trip).
- "model": Megatron-style TP placement rules
  (tensor_parallel.TRANSFORMER_TP_RULES or custom via `tp_rules`);
  GSPMD propagates and inserts the per-block psums.
- "expert": MoE expert tensors shard their expert dim
  (tensor_parallel.MOE_EP_RULES); the gate-combine psum is inserted by
  GSPMD — a differentiable, composable EP train path.
- "pipe": the network conf is partitioned into pipeline stages
  (parallel/pipeline.py); params restructure into the pipelined layout
  (stages stacked on a [S] axis sharded over the pipe axis) and the train
  step becomes the microbatched GPipe schedule. Composes with data/model/
  expert axes, which stay AUTO inside the schedule's shard_map.
- "seq": TIME shards over the axis — ring attention + offset positional
  encodings inside shard_map (parallel/sequence_parallel.py). Requires a
  conf built with seq_parallel_axis; composes with "data".

`set_mesh(mesh)` with no axes keeps the round-1 behavior (pure DP over a
'data' axis, optional ZeRO-1).
"""

from __future__ import annotations

import jax

ROLES = ("data", "model", "pipe", "expert", "seq")


def _iter_layer_confs(net):
    if hasattr(net, "layer_vertices"):
        return [v.layer for v in net.layer_vertices.values()]
    return list(net.layer_confs)


def _map_param_shaped(tree, ref_params, convert):
    """Apply `convert` to every subtree of `tree` whose pytree structure
    equals ref_params' (optimizer moments mirror the param tree; counts
    and scalars pass through). Used to carry optimizer state across the
    canonical <-> pipelined restructure without resetting moments."""
    ref = jax.tree.structure(ref_params)

    def is_param_shaped(x):
        try:
            return jax.tree.structure(x) == ref
        except Exception:
            return False

    def maybe(x):
        return convert(x) if is_param_shaped(x) else x

    return jax.tree.map(maybe, tree, is_leaf=is_param_shaped)


def exit_pipeline(net):
    """Restore canonical per-layer params/opt_state from the pipelined
    layout (called when the mesh is cleared or re-configured)."""
    plan = net._pp_plan
    if plan is None:
        return
    pipelined = net.params
    net.params = plan.to_canonical(pipelined)
    if net.opt_state is not None:
        net.opt_state = _map_param_shaped(
            net.opt_state, pipelined, plan.to_canonical)
    net._pp_plan = None
    net._pp_microbatches = None


def _ensure_tree_optimizer(net, axes, zero1):
    """The flat-view fused optimizer (updater.FlatViewTransform) cannot
    carry per-leaf shardings; param-placement roles (model/expert/pipe)
    and ZeRO-1 need tree-shaped moments — rebuild the optimizer and
    UNFLATTEN the accumulated moments into the per-leaf layout (a
    mid-training re-shard must not warm-restart Adam)."""
    from deeplearning4j_tpu.nn.updater import (
        FlatViewTransform,
        build_optimizer,
        named_layer_confs,
        unflatten_state_like,
    )

    needs_tree = zero1 or bool(set(axes or {}) & {"model", "expert", "pipe"})
    if not needs_tree or not isinstance(net.tx, FlatViewTransform):
        return
    old_state = net.opt_state
    net.tx = build_optimizer(net.conf.conf, named_layer_confs(net),
                             flat=False)
    if net.params is None:
        return
    if old_state is not None and net.iteration_count > 0:
        net.opt_state = unflatten_state_like(old_state, net.params)
    else:
        net.opt_state = net.tx.init(net.params)


def _configure_overlap(net, mesh, axes, overlap):
    """Validate + build the bucketed-reduction plan for set_mesh(
    overlap=...): pure-DP only, no TBPTT (the overlap step does not
    thread carries), plan derived from the params pytree in the net's
    actual layer topology. Emits a `bucket_plan` telemetry event so the
    bucket layout every rank will issue is on the record."""
    from deeplearning4j_tpu.parallel.overlap import BucketPlan, plan_buckets

    if mesh is None:
        raise ValueError("overlap=... requires a mesh")
    roles = set(axes) if axes else {"data"}
    if roles - {"data"}:
        raise ValueError(
            f"overlap composes with the 'data' role only (got "
            f"{sorted(roles)}); model/expert/pipe/seq placement keeps "
            "the GSPMD/manual steps — see ARCHITECTURE.md "
            "§Data-parallel overlap")
    data_ax = (axes or {}).get("data", "data")
    if data_ax not in mesh.axis_names:
        raise ValueError(
            f"overlap needs the data axis {data_ax!r} on the mesh "
            f"(mesh has {mesh.axis_names})")
    from deeplearning4j_tpu.nn.conf.enums import BackpropType

    if str(getattr(net.conf, "backprop_type", "")) in (
            str(BackpropType.TRUNCATED_BPTT), "truncated_bptt"):
        raise ValueError(
            "overlap does not support TRUNCATED_BPTT (the bucketed step "
            "does not thread carries) — drop overlap or the TBPTT config")
    if net.params is None:
        net.init()
    if isinstance(overlap, BucketPlan):
        plan = overlap
    else:
        from deeplearning4j_tpu.parallel.overlap import DEFAULT_BUCKET_BYTES

        bucket_bytes = (DEFAULT_BUCKET_BYTES if overlap is True
                        else int(overlap))
        layer_order = (list(net.layer_vertices)
                       if hasattr(net, "layer_vertices")
                       else list(net.layer_names))
        plan = plan_buckets(net.params, bucket_bytes,
                            layer_order=layer_order)
    from deeplearning4j_tpu.telemetry import get_default as _telemetry

    _telemetry().event("bucket_plan", axis=data_ax, **plan.summary())
    return plan


def configure_mesh(net, mesh, *, zero1=False, axes=None, n_microbatches=None,
                   tp_rules=None, overlap=None):
    """Shared body of MultiLayerNetwork/ComputationGraph.set_mesh.

    overlap: True / bucket-size-bytes / a prebuilt
    `parallel/overlap.BucketPlan` — route the DP gradient reduction
    through the bucketed shard_map step (compute/communication overlap)
    instead of GSPMD's monolithic allreduce. Data role only; composes
    with zero1."""
    from deeplearning4j_tpu.parallel.tensor_parallel import (
        param_shardings,
        resolve_rules,
        shard_params,
    )
    from deeplearning4j_tpu.reshard.planner import Placement

    if isinstance(mesh, Placement):
        # the automatic-placement-search contract (reshard/search.py):
        # `search_placement(...).winner` feeds set_mesh unmodified — the
        # Placement carries the mesh shape (axes named by role), the
        # role map, and the zero1 choice, so the devices-side Mesh and
        # the axes dict are derived here, never hand-constructed by the
        # caller (graftlint G022 guards the call sites)
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        placement = mesh
        mesh = make_mesh(dict(placement.mesh_axes), devices=jax.devices())
        if axes is None:
            axes = {r: a for r, a in placement.roles}
        zero1 = bool(zero1 or placement.zero1)
    if getattr(net, "_pp_plan", None) is not None:
        exit_pipeline(net)
    # re-placement detection: a net whose params were already PLACED by
    # an earlier set_mesh routes the new placement through the portable
    # resharding engine (reshard/) instead of raw host-side device_puts
    # — same plans, same telemetry, as checkpoint/elastic resharding
    prev_mesh = getattr(net, "_mesh", None)
    prev_axes = getattr(net, "_mesh_axes", None)
    prev_placed = getattr(net, "_param_sh", None) is not None
    net._mesh = mesh
    net._zero1 = zero1
    # process-spanning mesh (distributed/bootstrap + global_mesh): host
    # batches must globalize per process — _batch_dict keys off this flag
    net._multiprocess = False
    if mesh is not None:
        from deeplearning4j_tpu.parallel.mesh import spans_processes

        net._multiprocess = spans_processes(mesh)
    net._mesh_axes = dict(axes) if axes else None
    net._param_sh = None
    net._resolved_rules = None
    net._pp_plan = None
    net._pp_microbatches = None
    net._train_step = None
    net._scan_fit = None
    net._output_jit = None
    net._score_examples_jit = {}
    net._overlap_plan = (None if overlap is None
                         else _configure_overlap(net, mesh, axes, overlap))
    if mesh is not None:
        _ensure_tree_optimizer(net, axes, zero1)
    if mesh is None or axes is None:
        return net

    bad = set(axes) - set(ROLES)
    if bad:
        raise ValueError(f"unknown mesh roles {sorted(bad)}; valid: {ROLES}")
    if net._multiprocess and set(axes) - {"data"}:
        # model/expert/pipe placement device_puts param shards host-side,
        # which cannot target another process's devices; cross-process
        # TP/PP needs jit-driven placement (ARCHITECTURE.md §Distributed
        # runtime names the lifting plan)
        raise ValueError(
            "a process-spanning mesh currently supports the 'data' role "
            "only (got {}); model/expert/pipe/seq placement does host-side "
            "device_puts that cannot reach non-addressable devices — see "
            "ARCHITECTURE.md §Distributed runtime".format(sorted(axes)))
    for role, ax in axes.items():
        if ax not in mesh.axis_names:
            raise ValueError(
                f"axes[{role!r}]={ax!r} is not a mesh axis "
                f"(mesh has {mesh.axis_names})")
    if zero1 and set(axes) - {"data"}:
        raise ValueError("zero1 currently composes with the 'data' axis "
                         "only — drop it or the model/pipe/expert/seq axes "
                         "(ARCHITECTURE.md §Placement design notes has the "
                         "lifting plan)")
    if "seq" in axes:
        # sequence parallelism shards TIME inside shard_map: the layer
        # impls must know the ring axis (ring attention, offset posenc) —
        # the conf carries it (transformer_lm(seq_parallel_axis=...)).
        # 'data' and 'model' compose: the shard_map is manual over
        # seq/data only, so Megatron TP placements on a 'model' axis
        # propagate GSPMD-auto through the per-shard compute (r3 #4
        # lifted the seq-with-data-only restriction).
        if set(axes) - {"seq", "data", "model", "pipe"}:
            raise ValueError(
                "the 'seq' axis composes with 'data', 'model' and 'pipe' "
                "(time-sharded ring attention runs manual inside the SP "
                "or PP shard_map; 'expert' needs a different schedule — "
                "ARCHITECTURE.md §Placement design notes carries the "
                "seq x expert impossibility argument)")
        if not hasattr(net, "layer_vertices"):
            raise ValueError(
                "the 'seq' axis requires the ComputationGraph container "
                "(only its train step routes through the sequence-parallel "
                "shard_map); build the model via .graph_builder()")
        if (len(net.conf.network_inputs) != 1
                or len(net.conf.network_outputs) != 1):
            raise ValueError(
                "the 'seq' axis supports single-input single-output "
                "graphs (the SP step shards one token/label pair over "
                "time)")
        sp_layers = [
            lc for lc in _iter_layer_confs(net)
            if getattr(lc, "seq_parallel_axis", "")]
        if not sp_layers:
            raise ValueError(
                "axes['seq'] needs a sequence-parallel-ready conf: build "
                "the model with seq_parallel_axis set to the mesh axis "
                "name (e.g. transformer_lm(seq_parallel_axis="
                f"{axes['seq']!r}))")
        for lc in sp_layers:
            if lc.seq_parallel_axis != axes["seq"]:
                raise ValueError(
                    f"conf layer '{getattr(lc, 'name', '?')}' is built for "
                    f"seq axis {lc.seq_parallel_axis!r} but axes['seq'] is "
                    f"{axes['seq']!r}")
        if "pipe" in axes:
            # seq x pipe: fall through to the pipeline block below — the
            # PP schedule runs manual over {pipe, data, seq} and the
            # SP-configured layers' ring collectives resolve against the
            # bound seq axis inside the stage bodies (r5, VERDICT r4 #9)
            pass
        elif "model" in axes:
            from deeplearning4j_tpu.parallel.tensor_parallel import (
                param_shardings,
                resolve_rules as _resolve,
                shard_params,
            )

            rules = _resolve(axes, tp_rules)
            net._resolved_rules = rules
            if net.params is None:
                net.init()
            net.params = shard_params(net.params, mesh, rules)
            net._param_sh = param_shardings(net.params, mesh, rules)
            if net.opt_state is not None:
                net.opt_state = _map_param_shaped(
                    net.opt_state, net.params,
                    lambda t: jax.tree.map(jax.device_put, t, net._param_sh))
        if "pipe" not in axes:
            return net

    rules = resolve_rules(axes, tp_rules)
    net._resolved_rules = rules

    if "pipe" in axes:
        from deeplearning4j_tpu.parallel.pipeline import (
            PipelinePlan,
            check_pp_supported,
        )

        if not hasattr(net, "layer_vertices"):
            raise ValueError(
                "the 'pipe' axis requires the ComputationGraph container "
                "(stage partitioning runs on the DAG conf); wrap the "
                "layer stack in a graph via .graph_builder()")
        if net.params is None:
            net.init()
        check_pp_supported(net)
        plan = PipelinePlan(net, mesh.shape[axes["pipe"]])
        if n_microbatches is None:
            n_microbatches = 2 * plan.S
        canonical = net.params
        pp = plan.to_pipelined(canonical)
        sh = plan.placements(mesh, axes, rules)
        net.params = jax.tree.map(jax.device_put, pp, sh)
        net._pp_plan = plan
        net._pp_microbatches = n_microbatches
        if net.opt_state is not None:
            if net.iteration_count == 0:
                # fresh net: re-init in pipelined space; jit propagates the
                # input shardings onto the zero moments (one-shot placement
                # work, not a per-step path)
                net.opt_state = jax.jit(net.tx.init)(net.params)  # graftlint: disable=G005
            else:
                converted = _map_param_shaped(
                    net.opt_state, canonical, plan.to_pipelined)
                net.opt_state = _map_param_shaped(
                    converted, net.params,
                    lambda t: jax.tree.map(jax.device_put, t, sh))
    elif "model" in axes or "expert" in axes:
        if net.params is None:
            net.init()  # placement needs materialized params — same as pipe
        if prev_mesh is not None and prev_placed:
            # an already-placed net: mesh-to-mesh move through the
            # resharding planner (reshard_plan event + reshard span on
            # the record; collective identity on the same device set,
            # device_put transfer otherwise)
            from deeplearning4j_tpu.reshard.executor import (
                mesh_placement,
                reshard_net_live,
            )

            reshard_net_live(net, mesh, axes,
                             src=mesh_placement(prev_mesh, prev_axes),
                             tp_rules=tp_rules)
            net._param_sh = param_shardings(net.params, mesh, rules)
        else:
            net.params = shard_params(net.params, mesh, rules)
            net._param_sh = param_shardings(net.params, mesh, rules)
            if net.opt_state is not None:
                net.opt_state = _map_param_shaped(
                    net.opt_state, net.params,
                    lambda t: jax.tree.map(jax.device_put, t,
                                           net._param_sh))
    return net
