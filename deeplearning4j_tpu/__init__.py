"""deeplearning4j_tpu — a TPU-native deep-learning framework.

Brand-new framework with the capability surface of Deeplearning4j 0.4-rc3
(reference: /root/reference — builder-style declarative configs, sequential and
DAG network containers, SGD-family updaters and second-order solvers, data
pipelines, evaluation/early-stopping, checkpointing, gradient checks,
data-parallel distributed training, Word2Vec-family NLP, DeepWalk, clustering,
t-SNE, UI, CLI) — designed idiomatically for TPUs: JAX jit/grad/vmap/scan for
compute, pjit/shard_map collectives over ICI/DCN device meshes for scale-out,
Pallas kernels for hot paths, and host-side Python for data/control planes.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.conf import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
    ComputationGraphConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401
from deeplearning4j_tpu.nn.graph import ComputationGraph  # noqa: F401
