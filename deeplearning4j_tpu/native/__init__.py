"""ctypes bindings for the native IO core (io_core.cpp).

Build model: the C++ source ships inside the package and is compiled ONCE
per source-hash into `~/.cache/deeplearning4j_tpu/` with the system g++
(`-O3 -shared -fPIC`) at first use — no pybind11/pip dependency, no build
step at install time, and a missing toolchain simply means the Python
fallbacks run (every caller treats `None` from these helpers as "use the
Python path"). This mirrors the reference's split: Java front-end, native
(libnd4j/canova) hot path — except our compute native layer is XLA and
only host-side record parsing/corpus encoding lives here.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "io_core.cpp")
_CACHE_DIR = os.path.expanduser("~/.cache/deeplearning4j_tpu")

_lock = threading.Lock()
_lib = None
_lib_failed = False


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_CACHE_DIR, f"io_core-{digest}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_CACHE_DIR, exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except (OSError, subprocess.SubprocessError):
        if os.path.exists(tmp):
            os.unlink(tmp)
        return None


def get_lib():
    """The loaded CDLL, or None when no toolchain is available."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        path = _build()
        if path is None:
            _lib_failed = True
            return None
        lib = ctypes.CDLL(path)
        c_long_p = ctypes.POINTER(ctypes.c_long)
        f32_p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        i32_p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.dl4j_csv_dims.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char, c_long_p, c_long_p]
        lib.dl4j_csv_dims.restype = ctypes.c_long
        lib.dl4j_parse_csv.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char, f32_p,
            ctypes.c_long, ctypes.c_long]
        lib.dl4j_parse_csv.restype = ctypes.c_long
        lib.dl4j_svmlight_rows.argtypes = [ctypes.c_char_p]
        lib.dl4j_svmlight_rows.restype = ctypes.c_long
        lib.dl4j_parse_svmlight.argtypes = [
            ctypes.c_char_p, ctypes.c_long, f32_p, f32_p, ctypes.c_long]
        lib.dl4j_parse_svmlight.restype = ctypes.c_long
        lib.dl4j_encode_tokens.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p, ctypes.c_long,
            ctypes.c_long, i32_p, ctypes.c_long]
        lib.dl4j_encode_tokens.restype = ctypes.c_long
        lib.dl4j_encode_corpus.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p, ctypes.c_long,
            ctypes.c_long, i32_p, i32_p, ctypes.c_long]
        lib.dl4j_encode_corpus.restype = ctypes.c_long
        _lib = lib
        return _lib


# ------------------------------------------------------------- public API

def load_csv(path: str, skip_lines: int = 0,
             delimiter: str = ",") -> Optional[np.ndarray]:
    """Numeric CSV → float32 [rows, cols], or None (unavailable/non-numeric)."""
    lib = get_lib()
    if lib is None or len(delimiter) != 1:
        return None
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    if lib.dl4j_csv_dims(path.encode(), skip_lines, delimiter.encode(),
                         ctypes.byref(rows), ctypes.byref(cols)) != 0:
        return None
    if rows.value <= 0 or cols.value <= 0:
        return None
    out = np.empty((rows.value, cols.value), np.float32)
    got = lib.dl4j_parse_csv(path.encode(), skip_lines, delimiter.encode(),
                             out, rows.value, cols.value)
    if got < 0:
        return None  # non-numeric cell: caller falls back to Python parsing
    return out[:got]


def load_svmlight(path: str, num_features: int
                  ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """SVMLight file → (labels [N], dense features [N, F]), or None."""
    lib = get_lib()
    if lib is None:
        return None
    n = lib.dl4j_svmlight_rows(path.encode())
    if n < 0:
        return None
    labels = np.empty(n, np.float32)
    feats = np.zeros((n, num_features), np.float32)
    got = lib.dl4j_parse_svmlight(path.encode(), num_features, labels,
                                  feats, n)
    if got < 0:
        return None
    return labels[:got], feats[:got]


def encode_tokens(text: str, vocab: List[str]) -> Optional[np.ndarray]:
    """Whitespace-tokenize `text` and map tokens to vocab indices (-1 for
    OOV) in one native pass — the corpus-indexing step of the word2vec
    device pipeline. Returns int32 [n_tokens] or None."""
    lib = get_lib()
    if lib is None:
        return None
    data = text.encode()
    blob = "\n".join(vocab).encode()
    # upper bound on token count: every other byte a separator
    out = np.empty(len(data) // 2 + 1, np.int32)
    got = lib.dl4j_encode_tokens(data, len(data), blob, len(blob),
                                 len(vocab), out, len(out))
    if got < 0:
        return None
    return out[:got]


def encode_corpus(lines: List[str], vocab: List[str]
                  ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Encode a WHOLE corpus (one sentence per list entry) in one native
    pass: builds the vocab hash table once and returns (token_ids,
    sentence_ids), OOV as -1 — per-line encode_tokens calls would rebuild
    the table per sentence."""
    lib = get_lib()
    if lib is None:
        return None
    # normalize: embedded/trailing newlines in a line would desync the
    # native sentence counter from the list indices
    data = "\n".join(
        l.replace("\n", " ").strip() for l in lines).encode()
    blob = "\n".join(vocab).encode()
    cap = len(data) // 2 + 1
    ids = np.empty(cap, np.int32)
    sent = np.empty(cap, np.int32)
    got = lib.dl4j_encode_corpus(data, len(data), blob, len(blob),
                                 len(vocab), ids, sent, cap)
    if got < 0:
        return None
    return ids[:got], sent[:got]


def available() -> bool:
    return get_lib() is not None
