// Native IO core — the data-loading hot path in C++.
//
// The reference keeps its performance-critical runtime in native code
// (libnd4j via JNI; canova's readers feed it). In this framework the
// COMPUTE native layer is XLA itself; what remains host-side and hot is
// record parsing and corpus encoding, implemented here and bound via
// ctypes (deeplearning4j_tpu/native/__init__.py) with pure-Python
// fallbacks when no toolchain is available.
//
// Exposed C ABI:
//   dl4j_csv_dims      — scan a numeric CSV for (rows, cols)
//   dl4j_parse_csv     — parse into a caller-allocated float32 matrix
//   dl4j_svmlight_rows — count records in an SVMLight file
//   dl4j_parse_svmlight— labels + dense float32 features
//   dl4j_encode_tokens — whitespace-tokenize a text buffer and map each
//                        token to its vocab index (open-addressing hash),
//                        -1 for OOV — the corpus-indexing step that feeds
//                        the on-device word2vec pipeline.
//
// All functions return -1 on hard errors (unreadable file, malformed
// numeric cell), which the Python side turns into a fallback.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

bool read_file(const char* path, std::string& out) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    std::fseek(f, 0, SEEK_END);
    long n = std::ftell(f);
    if (n < 0) {  // non-seekable (FIFO/device): not supported here
        std::fclose(f);
        return false;
    }
    std::fseek(f, 0, SEEK_SET);
    out.resize(static_cast<size_t>(n));
    size_t got = n ? std::fread(&out[0], 1, static_cast<size_t>(n), f) : 0;
    std::fclose(f);
    return got == static_cast<size_t>(n);
}

// FNV-1a — stable, fast, good enough for vocab-sized tables.
uint64_t fnv1a(const char* s, size_t n) {
    uint64_t h = 1469598103934665603ull;
    for (size_t i = 0; i < n; ++i) {
        h ^= static_cast<unsigned char>(s[i]);
        h *= 1099511628211ull;
    }
    return h;
}

struct TokenHash {
    // open addressing, power-of-two capacity
    std::vector<int64_t> idx;     // vocab index or -1
    std::vector<const char*> key;
    std::vector<size_t> klen;
    size_t mask = 0;

    void build(const char* blob, int64_t blob_len, int64_t n_words) {
        size_t cap = 16;
        while (cap < static_cast<size_t>(n_words) * 2) cap <<= 1;
        idx.assign(cap, -1);
        key.assign(cap, nullptr);
        klen.assign(cap, 0);
        mask = cap - 1;
        const char* p = blob;
        const char* end = blob + blob_len;
        int64_t wi = 0;
        while (p < end && wi < n_words) {
            const char* nl = static_cast<const char*>(
                memchr(p, '\n', static_cast<size_t>(end - p)));
            size_t len = nl ? static_cast<size_t>(nl - p)
                            : static_cast<size_t>(end - p);
            size_t h = fnv1a(p, len) & mask;
            while (idx[h] != -1) h = (h + 1) & mask;
            idx[h] = wi;
            key[h] = p;
            klen[h] = len;
            ++wi;
            p = nl ? nl + 1 : end;
        }
    }

    int64_t lookup(const char* s, size_t n) const {
        size_t h = fnv1a(s, n) & mask;
        while (idx[h] != -1) {
            if (klen[h] == n && std::memcmp(key[h], s, n) == 0) return idx[h];
            h = (h + 1) & mask;
        }
        return -1;
    }
};

inline bool is_ws(char c) {
    return c == ' ' || c == '\t' || c == '\r' || c == '\n' || c == '\v'
        || c == '\f';
}

}  // namespace

namespace {

// Scan dims of a numeric CSV. Returns 0 on success, -1 on IO error.
long csv_dims_impl(const char* path, long skip_lines, char delim,
                   long* n_rows, long* n_cols) {
    std::string buf;
    if (!read_file(path, buf)) return -1;
    long rows = 0, cols = 0, line = 0;
    const char* p = buf.data();
    const char* end = p + buf.size();
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* eol = nl ? nl : end;
        if (line++ >= skip_lines && eol > p) {
            long c = 1;
            for (const char* q = p; q < eol; ++q)
                if (*q == delim) ++c;
            if (cols == 0) cols = c;
            if (c == cols) ++rows;  // ragged lines skipped like csv.reader+guard
        }
        p = nl ? nl + 1 : end;
    }
    *n_rows = rows;
    *n_cols = cols;
    return 0;
}

// Parse into out[rows*cols]. Returns rows parsed, or -1 on malformed cell.
long parse_csv_impl(const char* path, long skip_lines, char delim,
                    float* out, long max_rows, long n_cols) {
    std::string buf;
    if (!read_file(path, buf)) return -1;
    long rows = 0, line = 0;
    const char* p = buf.data();
    const char* end = p + buf.size();
    while (p < end && rows < max_rows) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* eol = nl ? nl : end;
        if (line++ >= skip_lines && eol > p) {
            const char* q = p;
            long col = 0;
            bool malformed = false;   // bad numeric cell -> abort fast path
            bool ragged = false;      // wrong cell count -> skip (like dims)
            while (col < n_cols) {
                if (q >= eol) {       // missing cells (incl. empty last cell)
                    malformed = true;
                    break;
                }
                char* cell_end = nullptr;
                float v = std::strtof(q, &cell_end);
                // reject empty/non-numeric cells and values whose text ran
                // past the end of the line (strtof ignores newlines)
                if (cell_end == q || cell_end > eol) {
                    malformed = true;
                    break;
                }
                out[rows * n_cols + col] = v;
                q = cell_end;
                while (q < eol && (*q == ' ' || *q == '\r')) ++q;
                ++col;
                if (col < n_cols) {
                    if (q >= eol || *q != delim) {
                        ragged = true;  // fewer cells than the first line
                        break;
                    }
                    ++q;
                }
            }
            if (!malformed && !ragged && q < eol) {
                ragged = true;          // extra cells beyond n_cols
            }
            if (malformed) return -1;
            if (!ragged) ++rows;
        }
        p = nl ? nl + 1 : end;
    }
    return rows;
}

long svmlight_rows_impl(const char* path) {
    std::string buf;
    if (!read_file(path, buf)) return -1;
    long rows = 0;
    const char* p = buf.data();
    const char* end = p + buf.size();
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* eol = nl ? nl : end;
        const char* q = p;
        while (q < eol && is_ws(*q)) ++q;
        if (q < eol && *q != '#') ++rows;
        p = nl ? nl + 1 : end;
    }
    return rows;
}

// labels[max_rows], feats[max_rows*num_features] (feats must be zeroed by
// the caller). Returns rows parsed or -1.
long parse_svmlight_impl(const char* path, long num_features, float* labels,
                         float* feats, long max_rows) {
    std::string buf;
    if (!read_file(path, buf)) return -1;
    long rows = 0;
    const char* p = buf.data();
    const char* end = p + buf.size();
    while (p < end && rows < max_rows) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* eol = nl ? nl : end;
        const char* q = p;
        while (q < eol && is_ws(*q)) ++q;
        if (q < eol && *q != '#') {
            char* cell_end = nullptr;
            float label = std::strtof(q, &cell_end);
            if (cell_end == q || cell_end > eol) return -1;
            labels[rows] = label;
            q = cell_end;
            while (q < eol) {
                while (q < eol && is_ws(*q)) ++q;
                if (q >= eol || *q == '#') break;
                char* ie = nullptr;
                long idx = std::strtol(q, &ie, 10);
                if (ie == q || ie >= eol || *ie != ':') return -1;
                q = ie + 1;
                float v = std::strtof(q, &cell_end);
                // empty value: strtof would cross the newline and consume
                // the next line's label — same guard as the CSV parser
                if (cell_end == q || cell_end > eol) return -1;
                q = cell_end;
                if (idx >= 1 && idx <= num_features)
                    feats[rows * num_features + (idx - 1)] = v;
            }
            ++rows;
        }
        p = nl ? nl + 1 : end;
    }
    return rows;
}

// Tokenize text[0..text_len) on whitespace; for each token write its vocab
// index (or -1 for OOV) into out. vocab_blob: '\n'-joined words. Returns
// the number of tokens written (<= max_tokens).
long encode_tokens_impl(const char* text, long text_len,
                        const char* vocab_blob, long blob_len, long n_words,
                        int32_t* out, long max_tokens) {
    TokenHash table;
    table.build(vocab_blob, blob_len, n_words);
    long count = 0;
    const char* p = text;
    const char* end = text + text_len;
    while (p < end && count < max_tokens) {
        while (p < end && is_ws(*p)) ++p;
        if (p >= end) break;
        const char* start = p;
        while (p < end && !is_ws(*p)) ++p;
        out[count++] = static_cast<int32_t>(
            table.lookup(start, static_cast<size_t>(p - start)));
    }
    return count;
}

// one-pass corpus encoding: token ids + sentence ids (newline-separated
// sentences), built on a SINGLE vocab hash table for the whole corpus.
long encode_corpus_impl(const char* text, long text_len,
                        const char* vocab_blob, long blob_len, long n_words,
                        int32_t* out_ids, int32_t* out_sent,
                        long max_tokens) {
    TokenHash table;
    table.build(vocab_blob, blob_len, n_words);
    long count = 0;
    int32_t sent = 0;
    const char* p = text;
    const char* end = text + text_len;
    while (p < end && count < max_tokens) {
        while (p < end && is_ws(*p)) {
            if (*p == '\n') ++sent;
            ++p;
        }
        if (p >= end) break;
        const char* start = p;
        while (p < end && !is_ws(*p)) ++p;
        out_ids[count] = static_cast<int32_t>(
            table.lookup(start, static_cast<size_t>(p - start)));
        out_sent[count] = sent;
        ++count;
    }
    return count;
}

}  // namespace

// Every extern "C" entry is an exception barrier: the module contract is
// "hard errors return -1 and Python falls back", and a C++ exception
// escaping extern "C" would std::terminate the host interpreter.
extern "C" {

long dl4j_csv_dims(const char* path, long skip_lines, char delim,
                   long* n_rows, long* n_cols) {
    try { return csv_dims_impl(path, skip_lines, delim, n_rows, n_cols); }
    catch (...) { return -1; }
}

long dl4j_parse_csv(const char* path, long skip_lines, char delim,
                    float* out, long max_rows, long n_cols) {
    try { return parse_csv_impl(path, skip_lines, delim, out, max_rows,
                                n_cols); }
    catch (...) { return -1; }
}

long dl4j_svmlight_rows(const char* path) {
    try { return svmlight_rows_impl(path); }
    catch (...) { return -1; }
}

long dl4j_parse_svmlight(const char* path, long num_features, float* labels,
                         float* feats, long max_rows) {
    try { return parse_svmlight_impl(path, num_features, labels, feats,
                                     max_rows); }
    catch (...) { return -1; }
}

long dl4j_encode_tokens(const char* text, long text_len,
                        const char* vocab_blob, long blob_len, long n_words,
                        int32_t* out, long max_tokens) {
    try { return encode_tokens_impl(text, text_len, vocab_blob, blob_len,
                                    n_words, out, max_tokens); }
    catch (...) { return -1; }
}

long dl4j_encode_corpus(const char* text, long text_len,
                        const char* vocab_blob, long blob_len, long n_words,
                        int32_t* out_ids, int32_t* out_sent,
                        long max_tokens) {
    try { return encode_corpus_impl(text, text_len, vocab_blob, blob_len,
                                    n_words, out_ids, out_sent, max_tokens); }
    catch (...) { return -1; }
}

}  // extern "C"
