"""Sharded embedding engine + device-resident ANN vector search — the
`expert` (ep) axis's first real tenant.

Layering (ARCHITECTURE.md "Embeddings & vector search"):

* `engine.py`  — ep-row-sharded embedding tables under shard_map; SGNS +
  hierarchical-softmax train steps with sparse-gather forward and
  (indices, values) scatter-add backward; the legacy-API lookup view.
* `ann.py`     — fixed-shape partition-then-refine ANN index (the L6
  vptree/kdtree contract, batched): coarse centroid routing + exact
  top-k inside the probed partitions.
* `walks.py`   — ragged DeepWalk walks bucketed into fixed shapes
  (serving/buckets.py-style padding) + device-side pair extraction.
* `corpus.py`  — skip-gram pair batches fed through the data/ async
  prefetch pipeline.
* `serving.py` — the `/embed` + `/search` serving engine riding the
  existing server/fleet plumbing.
"""

from deeplearning4j_tpu.embedding.engine import (  # noqa: F401
    EngineLookupView,
    ShardedEmbeddingEngine,
)
from deeplearning4j_tpu.embedding.ann import DeviceANNIndex  # noqa: F401
from deeplearning4j_tpu.embedding.serving import (  # noqa: F401
    EmbeddingServingEngine,
)
