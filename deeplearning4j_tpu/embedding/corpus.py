"""Skip-gram pair feeds for the embedding engine, riding the data/
async pipeline.

Two front doors, matching the tentpole's two corpora:

* `walk_pair_batches` — DeepWalk random walks (ragged) through the
  WalkBucketer/WalkPairExtractor fixed-shape path, compacted host-side
  into fixed [batch] (center, context) training batches.
* `sequence_pair_batches` — tokenized word2vec sequences (already
  index-mapped) through the same compaction.

Both produce FIXED-SHAPE batches (tail resampled like SequenceVectors'
flush, so the engine step compiles once), and `prefetched` wraps any of
them in the data/prefetcher.Prefetcher channel — pair generation and
negative sampling run on the prefetch thread, overlapping the device
step exactly like the data/ pipeline's fit loops.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.data.prefetcher import EOS, Prefetcher
from deeplearning4j_tpu.embedding.walks import WalkBucketer, WalkPairExtractor


def _compact(buf_c, buf_x, batch_size, rng):
    """Yield fixed-size (center, context) batches from growing buffers;
    returns the remainders."""
    out = []
    while buf_c.size >= batch_size:
        out.append((buf_c[:batch_size], buf_x[:batch_size]))
        buf_c, buf_x = buf_c[batch_size:], buf_x[batch_size:]
    return out, buf_c, buf_x


def _flush_tail(buf_c, buf_x, batch_size, rng):
    """Pad the tail by resampling existing pairs — the SequenceVectors
    tail-flush convention, keeping the step shape fixed."""
    if buf_c.size == 0:
        return None
    pad = rng.integers(0, buf_c.size, batch_size - buf_c.size)
    return (np.concatenate([buf_c, buf_c[pad]]),
            np.concatenate([buf_x, buf_x[pad]]))


def walk_pair_batches(walks, *, batch_size: int = 1024, window: int = 5,
                      length_buckets=None, walk_batch: int = 64,
                      seed: int = 0, bucketer: WalkBucketer = None,
                      extractor: WalkPairExtractor = None):
    """Ragged walks -> fixed [batch_size] (center, context) batches.
    The device-side extraction stays fixed-shape per length bucket; the
    host compacts the masked pairs."""
    if bucketer is None:
        kw = {} if length_buckets is None else \
            {"length_buckets": length_buckets}
        bucketer = WalkBucketer(batch=walk_batch, **kw)
    if extractor is None:
        extractor = WalkPairExtractor(window=window)
    rng = np.random.default_rng(seed)
    buf_c = np.empty(0, np.int32)
    buf_x = np.empty(0, np.int32)
    for block, mask in bucketer.batches(walks):
        centers, contexts, valid = extractor.extract(block, mask)
        keep = np.asarray(valid)
        buf_c = np.concatenate([buf_c, np.asarray(centers)[keep]])
        buf_x = np.concatenate([buf_x, np.asarray(contexts)[keep]])
        ready, buf_c, buf_x = _compact(buf_c, buf_x, batch_size, rng)
        yield from ready
    tail = _flush_tail(buf_c, buf_x, batch_size, rng)
    if tail is not None:
        yield tail


def sequence_pair_batches(sequences, *, batch_size: int = 1024,
                          window: int = 5, seed: int = 0):
    """Index sequences (word2vec corpus, already vocab-mapped) ->
    fixed [batch_size] (center, context) batches with the full fixed
    window (the engine-corpus counterpart of SequenceVectors'
    random-shrunk host windows)."""
    rng = np.random.default_rng(seed)
    buf_c = np.empty(0, np.int32)
    buf_x = np.empty(0, np.int32)
    for seq in sequences:
        idx = np.asarray(seq, np.int32).reshape(-1)
        n = idx.size
        if n < 2:
            continue
        centers, contexts = [], []
        for i in range(n):
            lo, hi = max(0, i - window), min(n, i + window + 1)
            for j in range(lo, hi):
                if j != i:
                    centers.append(idx[i])
                    contexts.append(idx[j])
        buf_c = np.concatenate([buf_c, np.asarray(centers, np.int32)])
        buf_x = np.concatenate([buf_x, np.asarray(contexts, np.int32)])
        ready, buf_c, buf_x = _compact(buf_c, buf_x, batch_size, rng)
        yield from ready
    tail = _flush_tail(buf_c, buf_x, batch_size, rng)
    if tail is not None:
        yield tail


def with_negatives(pair_batches, cum_table, k: int, seed: int = 0):
    """Attach [batch, k] negative samples to each (center, context)
    batch — unigram-table sampling on the PRODUCER thread, so the whole
    feed (pairs + negatives) overlaps the device step when prefetched."""
    from deeplearning4j_tpu.nlp.vocab import sample_negatives

    rng = np.random.default_rng(seed)
    for centers, contexts in pair_batches:
        negs = sample_negatives(cum_table, (centers.size, k), rng)
        yield centers, contexts, negs


def prefetched(batches, *, depth: int = 4, name: str = "embed-pairs"):
    """Wrap a pair-batch generator in the data/ async prefetch channel.
    Returns an iterator; generation runs on the prefetch thread."""
    pf = Prefetcher(lambda: batches, depth=depth, name=name)
    try:
        while True:
            item = pf.get()
            if item is EOS:
                return
            yield item
    finally:
        pf.stop()
