"""Ragged-walk batching: uneven DeepWalk walks -> fixed device shapes.

`graph/walkers.py` walks are ragged — CUTOFF_ON_DISCONNECTED truncates
at dead ends, so a seeded corpus mixes lengths freely. Feeding those
shapes straight to a jitted pair extractor would retrace per length;
this module applies the serving/buckets.py discipline to the training
input: a fixed LENGTH GRID, each walk padded up to the smallest bucket
that holds it (mask marks real tokens), walks of one bucket batched
together into fixed [B, L] blocks. The device-side skip-gram pair
extraction then compiles ONCE per (B, L) bucket shape — the
zero-retrace contract tests/test_embedding.py pins across a seeded
ragged corpus.

Pair extraction mirrors the fixed-window half of the SequenceVectors
skip-gram (every (center, context) pair within `window`, both real
tokens): the [B, L, 2*window] candidate block is built with static
offsets on device, masked, and returned flat with a validity mask. The
host compacts valid pairs into training batches (embedding/corpus.py)
— the DEVICE shapes are what must stay fixed.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_LENGTH_BUCKETS = (8, 16, 32, 64)


class WalkBucketer:
    """Buckets ragged walks into fixed [batch, length] blocks with
    masks. Walks longer than the top bucket are split; shorter ones pad
    up to the smallest bucket that holds them (id 0, mask False)."""

    def __init__(self, length_buckets=DEFAULT_LENGTH_BUCKETS,
                 batch: int = 64):
        self.length_buckets = tuple(sorted(int(b) for b in length_buckets))
        if not self.length_buckets:
            raise ValueError("need at least one length bucket")
        self.batch = int(batch)

    def length_bucket(self, n: int) -> int:
        for b in self.length_buckets:
            if n <= b:
                return b
        return self.length_buckets[-1]

    def batches(self, walks):
        """Yield (walk_block [batch, L] int32, mask [batch, L] bool)
        fixed-shape batches from an iterable of ragged walks. Partial
        batches flush with all-False mask rows."""
        pending = {b: [] for b in self.length_buckets}
        top = self.length_buckets[-1]
        for walk in walks:
            arr = np.asarray(walk, np.int32).reshape(-1)
            # split over-long walks into top-bucket chunks
            chunks = [arr[i:i + top] for i in range(0, max(arr.size, 1), top)]
            for chunk in chunks:
                if chunk.size < 2:
                    continue
                bucket = self.length_bucket(chunk.size)
                pending[bucket].append(chunk)
                if len(pending[bucket]) >= self.batch:
                    yield self._pack(pending[bucket], bucket)
                    pending[bucket] = []
        for bucket, rows in pending.items():
            if rows:
                yield self._pack(rows, bucket)

    def _pack(self, rows, bucket: int):
        block = np.zeros((self.batch, bucket), np.int32)
        mask = np.zeros((self.batch, bucket), bool)
        for i, row in enumerate(rows):
            block[i, :row.size] = row
            mask[i, :row.size] = True
        return block, mask


class WalkPairExtractor:
    """Device-side skip-gram pair extraction over a fixed [B, L] walk
    block: returns (centers [B*L*2w], contexts [B*L*2w], valid
    [B*L*2w]) — flat, fixed-shape, compiled once per (B, L)."""

    def __init__(self, window: int = 5):
        self.window = int(window)
        self._fns = {}
        self._trace_count = 0
        self._mu = threading.Lock()

    @property
    def trace_count(self) -> int:
        return self._trace_count

    def _get_fn(self, b: int, length: int):
        key = (b, length)
        with self._mu:
            fn = self._fns.get(key)
        if fn is None:
            window = self.window

            def body(block, mask):
                self._trace_count += 1  # trace time only
                offsets = [o for o in range(-window, window + 1) if o != 0]
                centers, contexts, valid = [], [], []
                for off in offsets:
                    shifted = jnp.roll(block, -off, axis=1)
                    shifted_mask = jnp.roll(mask, -off, axis=1)
                    pos = jnp.arange(length) + off
                    in_range = (pos >= 0) & (pos < length)
                    ok = mask & shifted_mask & in_range[None, :]
                    centers.append(block.reshape(-1))
                    contexts.append(jnp.where(ok, shifted, 0).reshape(-1))
                    valid.append(ok.reshape(-1))
                return (jnp.concatenate(centers),
                        jnp.concatenate(contexts),
                        jnp.concatenate(valid))

            fn = jax.jit(body)
            with self._mu:
                fn = self._fns.setdefault(key, fn)
        return fn

    def extract(self, block: np.ndarray, mask: np.ndarray):
        """Fixed-shape pair extraction; see class docstring."""
        b, length = block.shape
        fn = self._get_fn(int(b), int(length))
        return fn(jnp.asarray(block, jnp.int32), jnp.asarray(mask, bool))
