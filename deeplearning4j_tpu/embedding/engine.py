"""The ep-sharded embedding engine: row-partitioned tables under
shard_map, sparse-gather forward, (indices, values) scatter-add backward.

Reference (SURVEY §2.3 / §L7): InMemoryLookupTable + SkipGram HS/NS are
the training core of the reference's ~31k-LoC embeddings library; the
legacy port (nlp/lookup.py) runs them as dense single-device steps.
This engine is the mesh-native redesign, the cross-replica-sharding
shape of arXiv:2004.13336 applied to the embedding table itself:

* `syn0`/`syn1`/`syn1neg` rows are partitioned across the `expert` (ep)
  mesh axis — tables deliberately sized past one process's memory are
  the point. Per-device bytes are attributed through the memstat ledger
  (`ledger`, subsystem "params"), which is how the bench verifies that
  ep=2 really halves the per-device footprint.
* Forward is a SPARSE GATHER: each rank gathers the rows it owns
  (masked take), then one psum over `expert` assembles the full [B, D]
  strips. Scoring runs through the fused negative-sampling
  sampled-softmax kernel (ops/fused_neg_softmax.py — pure-jnp reference
  outside its envelope, bit-identical to the legacy math).
* Backward travels as (indices, values) COO pairs — the overlap layer's
  sparse bucket kind (parallel/overlap.sparse_bucket_reduce) when a
  `data` axis is present — and each rank scatter-adds ONLY its owned
  rows. The gradient is never materialized at the table's shape
  (graftlint G030 polices exactly that outside this package).
* At ep=1 every masking/psum op is value-preserving, so the engine is
  BIT-IDENTICAL to nlp/lookup.sgns_step / sg_hs_step — the parity
  contract tests/test_embedding.py pins after N seeded steps.

Host-side `self._trace_count += 1` inside the traced bodies runs at
TRACE time only — the zero-retrace warmup gate counts these, exactly
like serving/engine.py's counter.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nlp.lookup import MAX_ROW_STEP
from deeplearning4j_tpu.ops.fused_neg_softmax import neg_softmax_scores
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.overlap import (
    plan_sparse_bucket,
    sparse_bucket_reduce,
)
from deeplearning4j_tpu.telemetry import get_default
from deeplearning4j_tpu.telemetry.memstat import MemoryLedger
from deeplearning4j_tpu.util.compat import shard_map

# mesh axis names; the step bodies below run under the shard_map in
# `_wrap`, which binds both (G012's axis-name contract)
EP_AXIS = "expert"
DP_AXIS = "data"


def _ep_gather(local, idx, lo, v_local, axis_name=EP_AXIS):
    """Sparse gather across the `expert` axis: each rank takes the rows
    of its [V/ep, D] shard that `idx` names (masked take — out-of-shard
    indices contribute zero rows) and one psum assembles the full
    strips. idx [...], returns [..., D]. At ep=1 every op is
    value-preserving, so the result is bit-identical to `table[idx]`."""
    rel = idx - lo
    owned = (rel >= 0) & (rel < v_local)
    rows = local[jnp.where(owned, rel, 0)]
    rows = jnp.where(owned[..., None], rows, jnp.zeros((), rows.dtype))
    return lax.psum(rows, axis_name)


def _ep_scatter_update(local, idx, grads, lr, lo, v_local):
    """Owned-rows scatter-add + SGD with the legacy per-row trust-region
    cap (nlp/lookup._scatter_update, applied to the local shard — each
    global row lives on exactly one rank, so the row sums and norms
    match the dense formulation's). idx [N], grads [N, D]."""
    rel = idx - lo
    owned = (rel >= 0) & (rel < v_local)
    safe = jnp.where(owned, rel, 0)
    grads = jnp.where(owned[:, None], grads, jnp.zeros((), grads.dtype))
    sums = jnp.zeros_like(local).at[safe].add(grads.astype(local.dtype))
    step = lr * sums
    n = jnp.linalg.norm(step, axis=1, keepdims=True)
    step = step * jnp.minimum(1.0, MAX_ROW_STEP / jnp.maximum(n, 1e-12))
    return local - step


class ShardedEmbeddingEngine:
    """Row-sharded embedding tables + jitted SGNS / hierarchical-softmax
    train steps. Construction mirrors InMemoryLookupTable (same seed ->
    same init bits at ep=1); `EngineLookupView` adapts the query API."""

    def __init__(self, vocab_size: int, vector_length: int, *,
                 ep: int = 1, dp: int = 1, negative: int = 5,
                 use_hs: bool = False, seed: int = 123,
                 dtype=jnp.float32, recorder=None):
        if vocab_size <= 0 or vector_length <= 0:
            raise ValueError("vocab_size and vector_length must be positive")
        self.vocab_size = int(vocab_size)
        self.vector_length = int(vector_length)
        self.ep = int(ep)
        self.dp = int(dp)
        self.negative = int(negative)
        self.use_hs = bool(use_hs)
        self.seed = int(seed)
        self.dtype = dtype
        # rows pad to an ep multiple so every rank owns an equal shard;
        # padding rows are init'd but never indexed by real ids
        self.padded_vocab = -(-self.vocab_size // self.ep) * self.ep
        axes = {"expert": self.ep} if self.dp == 1 else \
            {"data": self.dp, "expert": self.ep}
        self.mesh = make_mesh(axes)
        self._table_spec = P("expert", None)
        self._batch_spec = P("data") if self.dp > 1 else P()
        self._recorder = recorder if recorder is not None else get_default()
        self._trace_count = 0
        self._steps = {}            # (kind, *shape) -> jitted step
        self._lookups = {}          # n -> jitted gather
        self._mu = threading.Lock()
        self.loss_history = []
        self.reset_weights()
        self.ledger = MemoryLedger()
        self.ledger.register("params", self._device0_shards)

    # ------------------------------------------------------------- state
    def reset_weights(self):
        key = jax.random.PRNGKey(self.seed)
        # reference init: (rand - 0.5) / dim (InMemoryLookupTable.java:133)
        # — identical bits to nlp/lookup.InMemoryLookupTable at ep=1
        syn0 = ((jax.random.uniform(
            key, (self.padded_vocab, self.vector_length)) - 0.5)
            / self.vector_length).astype(self.dtype)
        sharding = NamedSharding(self.mesh, self._table_spec)
        shape = (self.padded_vocab, self.vector_length)
        self.syn0 = jax.device_put(syn0, sharding)
        # separate buffers: a shared zeros array would make a later
        # donation of one table delete the other
        self.syn1 = jax.device_put(np.zeros(shape, np.float32)
                                   .astype(self.dtype), sharding)
        self.syn1neg = jax.device_put(np.zeros(shape, np.float32)
                                      .astype(self.dtype), sharding)

    def _device0_shards(self):
        """Memstat ledger source: the table shards resident on mesh
        device 0 — per-device table bytes, the number the ep-scaling
        acceptance row halves."""
        dev = self.mesh.devices.flat[0]
        out = []
        for table in (self.syn0, self.syn1, self.syn1neg):
            for shard in table.addressable_shards:
                if shard.device == dev:
                    out.append(shard.data)
        return out

    def table_bytes_per_device(self) -> int:
        """Per-device table bytes, read through the memstat ledger (the
        blessed G029 producer)."""
        return int(self.ledger.attributed().get("params", 0))

    @property
    def trace_count(self) -> int:
        """Times any engine computation was (re)traced — the
        zero-retrace warmup gate's counter."""
        return self._trace_count

    # ------------------------------------------------------- train steps
    def _v_local(self) -> int:
        return self.padded_vocab // self.ep

    def _wrap(self, body, n_tables, n_batch):
        """shard_map + jit a step body: tables row-sharded over
        `expert`, batch over `data` (replicated when dp == 1), lr
        replicated; tables donated."""
        in_specs = ((self._table_spec,) * n_tables
                    + (self._batch_spec,) * n_batch + (P(),))
        out_specs = (self._table_spec,) * n_tables + (P(),)
        fn = shard_map(body, mesh=self.mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=False)
        return jax.jit(fn, donate_argnums=tuple(range(n_tables)))

    def _build_sgns(self, batch: int, k: int):
        v_local = self._v_local()
        dp = self.dp
        b_local = batch // dp if dp > 1 else batch
        sb_center = plan_sparse_bucket(
            "sgns_syn0", b_local, self.vector_length, n_participants=dp)
        sb_out = plan_sparse_bucket(
            "sgns_syn1neg", b_local * (1 + k), self.vector_length,
            n_participants=dp)
        self._emit_bucket_plan("sgns", (sb_center, sb_out))

        def body(syn0, syn1neg, center, context, negatives, lr):
            self._trace_count += 1      # trace time only
            lo = lax.axis_index(EP_AXIS) * v_local
            c = _ep_gather(syn0, center, lo, v_local)
            pos = _ep_gather(syn1neg, context, lo, v_local)
            neg = _ep_gather(syn1neg, negatives, lo, v_local)

            pos_score, neg_score = neg_softmax_scores(c, pos, neg)

            g_pos = (pos_score - 1.0)[:, None]
            g_neg = neg_score[:, :, None]
            grad_c = g_pos * pos + jnp.einsum(
                "bko,bkd->bd", g_neg, neg,
                preferred_element_type=jnp.float32)
            grad_pos = g_pos * c
            grad_neg = g_neg * c[:, None, :]

            b, kk = negatives.shape
            cen_idx, cen_vals = center, grad_c
            out_idx = jnp.concatenate([context, negatives.reshape(b * kk)])
            out_vals = jnp.concatenate(
                [grad_pos, grad_neg.reshape(b * kk, -1)])
            if dp > 1:
                cen_idx, cen_vals = sparse_bucket_reduce(
                    cen_idx, cen_vals, DP_AXIS, bucket=sb_center)
                out_idx, out_vals = sparse_bucket_reduce(
                    out_idx, out_vals, DP_AXIS, bucket=sb_out)
            syn0 = _ep_scatter_update(syn0, cen_idx, cen_vals, lr,
                                      lo, v_local)
            syn1neg = _ep_scatter_update(syn1neg, out_idx, out_vals, lr,
                                         lo, v_local)

            loss = -(jnp.sum(jnp.log(pos_score + 1e-10))
                     + jnp.sum(jnp.log(1.0 - neg_score + 1e-10)))
            if dp > 1:
                loss = lax.psum(loss, DP_AXIS)
            return syn0, syn1neg, loss / batch

        return self._wrap(body, n_tables=2, n_batch=3)

    def _build_hs(self, batch: int, depth: int):
        v_local = self._v_local()
        dp = self.dp
        b_local = batch // dp if dp > 1 else batch
        sb_center = plan_sparse_bucket(
            "hs_syn0", b_local, self.vector_length, n_participants=dp)
        sb_nodes = plan_sparse_bucket(
            "hs_syn1", b_local * depth, self.vector_length,
            n_participants=dp)
        self._emit_bucket_plan("hs", (sb_center, sb_nodes))

        def body(syn0, syn1, center, codes, points, mask, lr):
            self._trace_count += 1      # trace time only
            lo = lax.axis_index(EP_AXIS) * v_local
            c = _ep_gather(syn0, center, lo, v_local)
            nodes = _ep_gather(syn1, points, lo, v_local)
            sign = 1.0 - 2.0 * codes.astype(c.dtype)
            logit = jnp.einsum("bd,bld->bl", c, nodes,
                               preferred_element_type=jnp.float32)
            p = jax.nn.sigmoid(sign * logit)
            m = mask.astype(c.dtype)

            g = -sign * (1.0 - p) * m
            grad_c = jnp.einsum("bl,bld->bd", g, nodes,
                                preferred_element_type=jnp.float32)
            grad_nodes = g[:, :, None] * c[:, None, :]

            b, length = codes.shape
            cen_idx, cen_vals = center, grad_c
            flat_pts = jnp.where(mask, points, 0).reshape(b * length)
            flat_vals = (grad_nodes * m[:, :, None]).reshape(b * length, -1)
            if dp > 1:
                cen_idx, cen_vals = sparse_bucket_reduce(
                    cen_idx, cen_vals, DP_AXIS, bucket=sb_center)
                flat_pts, flat_vals = sparse_bucket_reduce(
                    flat_pts, flat_vals, DP_AXIS, bucket=sb_nodes)
            syn0 = _ep_scatter_update(syn0, cen_idx, cen_vals, lr,
                                      lo, v_local)
            syn1 = _ep_scatter_update(syn1, flat_pts, flat_vals, lr,
                                      lo, v_local)

            loss = -jnp.sum(jnp.log(p + 1e-10) * m)
            if dp > 1:
                loss = lax.psum(loss, DP_AXIS)
            return syn0, syn1, loss / batch

        return self._wrap(body, n_tables=2, n_batch=4)

    def _emit_bucket_plan(self, step_kind, buckets):
        self._recorder.event(
            "bucket_plan", sparse=True, step=step_kind, ep=self.ep,
            dp=self.dp, buckets=[b.summary() for b in buckets])

    def _get_step(self, kind, *shape):
        key = (kind, *shape)
        with self._mu:
            fn = self._steps.get(key)
        if fn is None:
            fn = (self._build_sgns(*shape) if kind == "sgns"
                  else self._build_hs(*shape))
            with self._mu:
                fn = self._steps.setdefault(key, fn)
        return fn

    def _pair_bytes(self, n_rows: int) -> int:
        """Wire bytes of an (indices, values) gradient pair."""
        return n_rows * (4 + self.vector_length
                         * jnp.dtype(self.dtype).itemsize)

    def sgns_step(self, center, context, negatives, lr):
        """One SGNS step over a fixed-shape pair batch: center [B],
        context [B], negatives [B, K], scalar lr. Returns the device
        loss scalar (no host sync)."""
        center = jnp.asarray(center, jnp.int32)
        context = jnp.asarray(context, jnp.int32)
        negatives = jnp.asarray(negatives, jnp.int32)
        batch, k = negatives.shape
        fn = self._get_step("sgns", batch, k)
        sparse_rows = batch * (2 + k)
        with self._recorder.span(
                "scatter_add", step="sgns", rows=sparse_rows,
                bytes=self._pair_bytes(sparse_rows), ep=self.ep,
                ep_gather_bytes=self._gather_bytes(sparse_rows)):
            self.syn0, self.syn1neg, loss = fn(
                self.syn0, self.syn1neg, center, context, negatives, lr)
        self.loss_history.append(loss)
        return loss

    def hs_step(self, center, codes, points, mask, lr):
        """One hierarchical-softmax step: center [B], codes/points/mask
        [B, L] (Huffman rows gathered host-side, like the legacy path)."""
        center = jnp.asarray(center, jnp.int32)
        codes = jnp.asarray(codes, jnp.int32)
        points = jnp.asarray(points, jnp.int32)
        mask = jnp.asarray(mask, bool)
        batch, depth = codes.shape
        fn = self._get_step("hs", batch, depth)
        sparse_rows = batch * (1 + depth)
        with self._recorder.span(
                "scatter_add", step="hs", rows=sparse_rows,
                bytes=self._pair_bytes(sparse_rows), ep=self.ep,
                ep_gather_bytes=self._gather_bytes(sparse_rows)):
            self.syn0, self.syn1, loss = fn(
                self.syn0, self.syn1, center, codes, points, mask, lr)
        self.loss_history.append(loss)
        return loss

    def _gather_bytes(self, n_rows: int) -> int:
        """Bytes the forward sparse gather moves across the ep axis:
        each psum'd [rows, D] strip carries (ep-1)/ep remote rows."""
        row_bytes = self.vector_length * jnp.dtype(self.dtype).itemsize
        return n_rows * row_bytes * (self.ep - 1) // self.ep

    # ----------------------------------------------------------- lookup
    def _get_lookup(self, n: int):
        with self._mu:
            fn = self._lookups.get(n)
        if fn is None:
            v_local = self._v_local()

            def body(syn0, idx):
                self._trace_count += 1  # trace time only
                lo = lax.axis_index(EP_AXIS) * v_local
                return _ep_gather(syn0, idx, lo, v_local)

            wrapped = shard_map(
                body, mesh=self.mesh, in_specs=(self._table_spec, P()),
                out_specs=P(), check_rep=False)
            fn = jax.jit(wrapped)
            with self._mu:
                fn = self._lookups.setdefault(n, fn)
        return fn

    def embed(self, ids) -> jax.Array:
        """Sparse-gather `syn0` rows for `ids` [n] (fixed shape per n —
        serving pads to a bucket grid). Returns the device [n, D]."""
        ids = jnp.asarray(ids, jnp.int32)
        n = int(ids.shape[0])
        fn = self._get_lookup(n)
        row_bytes = self.vector_length * jnp.dtype(self.dtype).itemsize
        with self._recorder.span("gather", rows=n, ep=self.ep,
                                 bytes=n * (row_bytes + 4)):
            return fn(self.syn0, ids)


class EngineLookupView:
    """InMemoryLookupTable's query API over the engine — what
    SequenceVectors/serializers see when the engine is installed.
    Reads slice padding rows off; `nearest` keeps the legacy exact
    brute-force contract (the ANN index is the serving-path variant)."""

    def __init__(self, engine: ShardedEmbeddingEngine):
        self._engine = engine
        self.use_hs = engine.use_hs
        self.negative = engine.negative
        self.dtype = engine.dtype

    @property
    def engine(self) -> ShardedEmbeddingEngine:
        return self._engine

    @property
    def vocab_size(self) -> int:
        return self._engine.vocab_size

    @property
    def vector_length(self) -> int:
        return self._engine.vector_length

    @property
    def syn0(self):
        return self._engine.syn0[:self._engine.vocab_size]

    @property
    def syn1(self):
        return self._engine.syn1[:self._engine.vocab_size]

    @property
    def syn1neg(self):
        return self._engine.syn1neg[:self._engine.vocab_size]

    def reset_weights(self):
        self._engine.reset_weights()

    # vectors -------------------------------------------------------------
    def vector(self, index: int) -> np.ndarray:
        return np.asarray(self._engine.embed(jnp.asarray([index]))[0])

    def vectors(self) -> np.ndarray:
        return np.asarray(self.syn0)

    def set_vectors(self, arr: np.ndarray):
        e = self._engine
        arr = jnp.asarray(arr, e.dtype)
        v, d = arr.shape
        if (v, d) != (e.vocab_size, e.vector_length):
            raise ValueError(
                f"set_vectors shape {(v, d)} != engine table "
                f"{(e.vocab_size, e.vector_length)}")
        if e.padded_vocab != v:
            arr = jnp.concatenate(
                [arr, jnp.zeros((e.padded_vocab - v, d), e.dtype)])
        e.syn0 = jax.device_put(
            arr, NamedSharding(e.mesh, e._table_spec))

    # similarity ----------------------------------------------------------
    def _normed(self):
        syn0 = self.syn0
        n = jnp.linalg.norm(syn0, axis=1, keepdims=True)
        return syn0 / jnp.maximum(n, 1e-12)

    def nearest(self, query_vec: np.ndarray, top_n: int = 10,
                exclude=()) -> list:
        normed = self._normed()
        q = jnp.asarray(query_vec, self.dtype)
        q = q / jnp.maximum(jnp.linalg.norm(q), 1e-12)
        sims = jnp.einsum("vd,d->v", normed, q,
                          preferred_element_type=jnp.float32)
        if exclude:
            sims = sims.at[jnp.asarray(list(exclude))].set(-jnp.inf)
        vals, idx = jax.lax.top_k(sims, min(top_n, self.vocab_size))
        return list(zip(np.asarray(idx).tolist(), np.asarray(vals).tolist()))

    def similarity(self, i: int, j: int) -> float:
        rows = self._engine.embed(jnp.asarray([i, j]))
        a, b = rows[0], rows[1]
        denom = jnp.linalg.norm(a) * jnp.linalg.norm(b)
        return float(jnp.vdot(a, b) / jnp.maximum(denom, 1e-12))
