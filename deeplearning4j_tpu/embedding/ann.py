"""Device-resident ANN index: batched partition-then-refine lookup.

Reproduces the reference's L6 nearest-neighbor contract (clustering/
vptree.py's `search(target, k) -> [(distance, index)]`, kdtree's exact
top-k) in the shape a TPU wants: instead of a pointer-chasing tree
descent per query, a BATCH of queries runs one fixed-shape jitted
program — coarse centroid routing (score P centroids, keep the top
`nprobe`) followed by exact top-k scoring inside the probed partitions.
Everything is fixed-shape — [P, cap] partitions padded with -1 ids,
[Q, k] results — so the serving zero-retrace warmup contract holds: one
compile per (Q, k, nprobe) triple at warmup, zero retraces after.

Build is k-means (a few Lloyd iterations, on device) over the corpus,
then capacity-capped assignment with spill: rows that overflow their
nearest partition fall to the next-nearest with room — recall insurance
for skewed clusters. `calibrate_nprobe` walks the nprobe ladder until a
held-out sample reaches the recall floor, BEFORE warmup, so calibration
compiles never count against the serving path.

Metric is cosine via normalized dot product — the same normalized-
matmul + top_k contract as nlp/lookup.InMemoryLookupTable.nearest and
clustering/vptree's "cosinesimilarity" metric (monotonic in its
sqrt(2(1-cos)) true-metric form, so top-k order matches exactly).
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.telemetry import get_default

_NEG_INF = -1e30


def _normalize(x, axis=-1):
    n = jnp.linalg.norm(x, axis=axis, keepdims=True)
    return x / jnp.maximum(n, 1e-12)


@jax.jit
def _kmeans_iter(centroids, vecs):
    """One Lloyd iteration over normalized vectors (spherical k-means:
    assign by max dot, recenter, renormalize)."""
    scores = jnp.einsum("nd,pd->np", vecs, centroids,
                        preferred_element_type=jnp.float32)     # [N, P]
    assign = jnp.argmax(scores, axis=1)               # [N]
    p = centroids.shape[0]
    one_hot = jax.nn.one_hot(assign, p, dtype=vecs.dtype)   # [N, P]
    sums = jnp.einsum("np,nd->pd", one_hot, vecs,
                      preferred_element_type=jnp.float32)       # [P, D]
    counts = one_hot.sum(axis=0)[:, None]             # [P, 1]
    # empty partitions keep their old centroid
    new = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), centroids)
    return _normalize(new), assign


def brute_force_topk(vectors, queries, k: int):
    """Exact cosine top-k — the recall baseline and the legacy `nearest`
    contract, batched: one normalized matmul over the FULL table plus
    top_k. Returns (ids [Q, k], scores [Q, k])."""
    normed = _normalize(jnp.asarray(vectors))
    q = _normalize(jnp.asarray(queries))
    sims = jnp.einsum("qd,vd->qv", q, normed,
                      preferred_element_type=jnp.float32)       # [Q, V]
    scores, idx = jax.lax.top_k(sims, k)
    return idx.astype(jnp.int32), scores


class DeviceANNIndex:
    """Fixed-shape IVF (partition-then-refine) index over an [V, D]
    corpus. `search` is jitted per (Q, k, nprobe); `trace_count` counts
    traces for the zero-retrace gate."""

    def __init__(self, centroids, part_vecs, part_ids, *, recorder=None):
        self.centroids = centroids          # [P, D] normalized
        self.part_vecs = part_vecs          # [P, cap, D] normalized, 0-pad
        self.part_ids = part_ids            # [P, cap] int32, -1 pad
        self.n_partitions, self.capacity, self.dim = part_vecs.shape
        self._recorder = recorder if recorder is not None else get_default()
        self._trace_count = 0
        self._search_fns = {}
        self._mu = threading.Lock()

    # ------------------------------------------------------------- build
    @classmethod
    def build(cls, vectors, n_partitions: int = 64, *,
              iters: int = 5, slack: float = 1.5, seed: int = 0,
              recorder=None) -> "DeviceANNIndex":
        """K-means + capacity-capped assignment with next-nearest spill.
        `slack` scales partition capacity over the perfectly-balanced
        V / P rows so skewed clusters keep their members."""
        vecs = _normalize(jnp.asarray(vectors, jnp.float32))
        v, d = vecs.shape
        p = min(int(n_partitions), v)
        rng = np.random.default_rng(seed)
        init = vecs[jnp.asarray(rng.choice(v, size=p, replace=False))]
        centroids = _normalize(init)
        for _ in range(max(1, iters)):
            centroids, _ = _kmeans_iter(centroids, vecs)

        cap = min(v, int(np.ceil(v / p * slack)))
        # host-side assignment (build time, not the query path): order
        # candidates by centroid affinity, spill to the next-nearest
        # partition with room
        scores = np.asarray(jnp.einsum(
            "vd,pd->vp", vecs, centroids,
            preferred_element_type=jnp.float32))       # [V, P]
        pref = np.argsort(-scores, axis=1)             # [V, P]
        part_rows = [[] for _ in range(p)]
        for row in range(v):
            for c in pref[row]:
                if len(part_rows[c]) < cap:
                    part_rows[c].append(row)
                    break
        part_ids = np.full((p, cap), -1, np.int32)
        host_vecs = np.asarray(vecs)
        part_vecs = np.zeros((p, cap, d), np.float32)
        for c, rows in enumerate(part_rows):
            if rows:
                part_ids[c, :len(rows)] = rows
                part_vecs[c, :len(rows)] = host_vecs[rows]
        return cls(centroids, jnp.asarray(part_vecs),
                   jnp.asarray(part_ids), recorder=recorder)

    # ------------------------------------------------------------- query
    def _get_search(self, q: int, k: int, nprobe: int):
        key = (q, k, nprobe)
        with self._mu:
            fn = self._search_fns.get(key)
        if fn is None:
            def body(centroids, part_vecs, part_ids, queries):
                self._trace_count += 1  # trace time only
                qn = _normalize(queries)
                coarse = jnp.einsum("qd,pd->qp", qn, centroids,
                                    preferred_element_type=jnp.float32)
                _, probe = jax.lax.top_k(coarse, nprobe)      # [Q, nprobe]
                cand_vecs = part_vecs[probe]        # [Q, nprobe, cap, D]
                cand_ids = part_ids[probe].reshape(q, -1)
                fine = jnp.einsum("qd,qncd->qnc", qn, cand_vecs,
                                  preferred_element_type=jnp.float32)
                fine = fine.reshape(q, -1)
                fine = jnp.where(cand_ids >= 0, fine, _NEG_INF)
                scores, pos = jax.lax.top_k(fine, k)
                ids = jnp.take_along_axis(cand_ids, pos, axis=1)
                return ids, scores

            fn = jax.jit(body, static_argnums=())
            with self._mu:
                fn = self._search_fns.setdefault(key, fn)
        return fn

    def search(self, queries, k: int = 10, *, nprobe: int = 8):
        """Batched ANN lookup: queries [Q, D] -> (ids [Q, k], cosine
        scores [Q, k]), nearest-first — the vptree `search` contract,
        batched and fixed-shape."""
        queries = jnp.asarray(queries, jnp.float32)
        q = int(queries.shape[0])
        nprobe = min(int(nprobe), self.n_partitions)
        fn = self._get_search(q, int(k), nprobe)
        probed_bytes = (q * nprobe * self.capacity
                        * (self.dim * 4 + 4))
        with self._recorder.span("ann_probe", queries=q, k=int(k),
                                 nprobe=nprobe, bytes=int(probed_bytes)):
            ids, scores = fn(self.centroids, self.part_vecs,
                             self.part_ids, queries)
        return ids, scores

    @property
    def trace_count(self) -> int:
        return self._trace_count

    # -------------------------------------------------------- calibration
    def calibrate_nprobe(self, vectors, sample_queries, k: int = 10,
                         floor: float = 0.95,
                         ladder=(4, 8, 16, 32, 64)) -> tuple:
        """Walk the nprobe ladder until recall@k on `sample_queries`
        reaches `floor` vs exact brute force. Runs BEFORE warmup so its
        compiles never count against the serving path. Returns
        (nprobe, recall)."""
        exact_ids, _ = brute_force_topk(vectors, sample_queries, k)
        exact = np.asarray(exact_ids)
        best = (int(ladder[-1]), 0.0)
        for nprobe in ladder:
            if nprobe > self.n_partitions:
                break
            ids, _ = self.search(sample_queries, k, nprobe=nprobe)
            r = recall_at_k(np.asarray(ids), exact)
            best = (int(nprobe), float(r))
            if r >= floor:
                break
        return best


def recall_at_k(ann_ids: np.ndarray, exact_ids: np.ndarray) -> float:
    """Mean |ANN ∩ exact| / k over the query batch."""
    q, k = exact_ids.shape
    hits = 0
    for row in range(q):
        hits += len(set(ann_ids[row].tolist())
                    & set(exact_ids[row].tolist()))
    return hits / float(q * k)
