"""Vector-search serving: the embedding engine's front door.

`EmbeddingServingEngine` is the ep axis's first serving tenant — it
plugs the sharded embedding table (embedding/engine.py) and the
device-resident ANN index (embedding/ann.py) into the existing serving
stack under the same operational contracts the transformer engines
honor:

* **Bucket lattice** (serving/buckets.py): request sizes are padded UP
  to the lattice's batch grid, every (bucket, k) shape is compiled at
  warmup under `compile` spans, and the trace counter is frozen after —
  the zero-retrace contract holds on the /search path exactly as it
  does on /predict.
* **Fleet protocol** (serving/fleet.py): the single lookup worker
  exposes the heartbeat/lifecycle surface (`fleet_workers`,
  `fleet_reap`, `fleet_respawn`, `fleet_snapshot`) so a FleetSupervisor
  can reap a wedged worker and respawn it onto the SAME jitted
  executables — zero compiles on respawn.
* **Telemetry**: every lookup rides the `gather`/`ann_probe` spans the
  engine and index already emit (bytes moved attached), and each
  completed request emits a `request` event — the same stream the
  Prometheus /metrics latency histograms are fed from.

The HTTP routes live in serving/server.py (`POST /embed`,
`POST /search`), gated on `submit_embed`/`submit_search` exactly like
/generate gates on `submit_generate`.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from deeplearning4j_tpu.embedding.ann import DeviceANNIndex
from deeplearning4j_tpu.serving.buckets import BucketLattice

# hard bound on one request's wait inside the worker queue; far above
# any sane lookup time — a hit means the worker died mid-request
_DEFAULT_NPROBE_LADDER = (4, 8, 16, 32, 64)


class EmbedRequest:
    """One admitted /embed or /search request: the caller waits on
    `done`; the worker fills `result` (or `error`) and stamps timing."""

    def __init__(self, kind: str, request_id=None):
        self.kind = kind
        self.request_id = request_id or f"{kind}-{id(self):x}"
        self.ids = None          # embed: [n] int ids
        self.queries = None      # search: [q, d] vectors
        self.k = None
        self.result = None
        self.error = None
        self.t_enqueue = 0.0
        self.t_done = 0.0
        self._done = threading.Event()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def finish(self, result=None, error=None, now=None) -> None:
        self.result = result
        self.error = error
        self.t_done = time.monotonic() if now is None else now
        self._done.set()


class _LookupWorker:
    """The single lookup thread, speaking the fleet heartbeat/lifecycle
    protocol so a FleetSupervisor can watch it. Respawn restarts the
    thread over the SAME engine (the jitted executables survive)."""

    def __init__(self, engine, index: int = 0):
        self.engine = engine
        self.index = index
        self.alive = False
        self.lifecycle = "warming"
        self.last_beat = time.monotonic()
        self.current_batch = None   # the in-flight request, for reap
        # served/failed are written by the worker thread and read from
        # describe()/stats() on the caller thread — one dedicated lock
        # guards every access (the PagePool counter idiom)
        self._lock = threading.Lock()
        self.served = 0
        self.failed = 0
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self.alive = True
        self.lifecycle = "serving"
        self.last_beat = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"embed-lookup-{self.index}")
        self._thread.start()

    def join(self, timeout=None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def describe(self, now: float) -> dict:
        with self._lock:
            served, failed = self.served, self.failed
        return {
            "index": self.index,
            "state": self.lifecycle,
            "alive": self.alive,
            "served": served,
            "failed": failed,
            "last_beat_age_s": round(now - self.last_beat, 4),
        }

    def _run(self) -> None:
        q = self.engine._queue
        while True:
            req = q.get()
            self.last_beat = time.monotonic()
            if req is None:           # drain sentinel
                self.lifecycle = "draining"
                self.alive = False
                return
            self.current_batch = req
            try:
                result = self.engine._process(req)
                with self._lock:
                    self.served += 1
                req.finish(result=result)
                ok, err = True, None
            except Exception as exc:  # noqa: BLE001 — fail loudly per req
                with self._lock:
                    self.failed += 1
                err = f"{type(exc).__name__}: {exc}"
                req.finish(error=err)
                ok = False
            finally:
                self.current_batch = None
                self.last_beat = time.monotonic()
            self.engine.recorder.event(
                "request", ok=ok, kind=req.kind, id=req.request_id,
                total_s=round(req.t_done - req.t_enqueue, 6),
                **({"error": err} if err else {}))


class EmbeddingServingEngine:
    """Serves `/embed` (id -> vector) and `/search` (vector -> ANN
    top-k) over a trained embedding table.

    `source` is an EngineLookupView (the trained ShardedEmbeddingEngine
    — lookups then run the ep-sharded `gather` path) or a plain [V, D]
    vector array (a published snapshot). The ANN index is built at
    construction unless one is passed in; `start()` calibrates nprobe
    against the recall floor (BEFORE warmup, so calibration compiles
    never count), then warms every (bucket, k) search shape and every
    embed bucket under `compile` spans. After warmup the trace counter
    is frozen — `stats()["trace_count"]` growing mid-traffic is a
    retrace, the same red flag the transformer engines pin."""

    def __init__(self, source, *, index: DeviceANNIndex | None = None,
                 lattice: BucketLattice | None = None,
                 n_partitions: int = 64, k_grid=(10,),
                 nprobe: int | None = None, recall_floor: float = 0.95,
                 calibration_queries: int = 64, seed: int = 0,
                 recorder=None):
        if recorder is None:
            from deeplearning4j_tpu.telemetry import (NullRecorder, Recorder,
                                                      get_default)

            recorder = get_default()
            if isinstance(recorder, NullRecorder):
                # the null default would starve the server's /metrics
                # sink (its event() never fires sinks) — an in-memory
                # recorder keeps the embedding series live out of the
                # box without forcing a telemetry file on the process
                recorder = Recorder(path=None)
        self.recorder = recorder
        self._view = source if hasattr(source, "vectors") else None
        vectors = np.asarray(
            source.vectors() if self._view is not None else source,
            np.float32)
        if vectors.ndim != 2:
            raise ValueError(f"need [V, D] vectors, got {vectors.shape}")
        self.vocab_size, self.dim = vectors.shape
        self._vectors = vectors
        self.index = index if index is not None else DeviceANNIndex.build(
            vectors, n_partitions=n_partitions, seed=seed,
            recorder=recorder)
        self.lattice = lattice or BucketLattice(batch_sizes=(1, 4, 16, 64))
        self.k_grid = tuple(sorted({int(k) for k in k_grid}))
        self.recall_floor = float(recall_floor)
        self.nprobe = int(nprobe) if nprobe is not None else None
        self._calibration_queries = int(calibration_queries)
        self._seed = seed
        self._queue: queue.Queue = queue.Queue()
        self._worker = _LookupWorker(self)
        self._draining = False
        self._started = False
        self._embed_table = None    # lazy device copy for snapshot mode
        self._embed_fns = {}
        self._embed_traces = 0
        self.warmup_s = 0.0
        self.calibrated_recall = None

    # ------------------------------------------------------------ lookup
    def _embed_rows(self, ids: np.ndarray):
        """Fixed-shape id -> vector gather. Engine-backed sources run
        the ep-sharded gather (psum + `gather` span inside the engine);
        snapshot sources gather from a device-resident copy under the
        same span."""
        if self._view is not None:
            return self._view.engine.embed(ids)
        import jax
        import jax.numpy as jnp

        if self._embed_table is None:
            self._embed_table = jnp.asarray(self._vectors)
        n = int(ids.shape[0])
        fn = self._embed_fns.get(n)
        if fn is None:
            def body(table, idx):
                self._embed_traces += 1  # trace time only
                return table[idx]

            fn = jax.jit(body)
            self._embed_fns[n] = fn
        row_bytes = self.dim * self._embed_table.dtype.itemsize
        with self.recorder.span("gather", rows=n, ep=1,
                                bytes=n * (row_bytes + 4)):
            return fn(self._embed_table, jnp.asarray(ids, jnp.int32))

    def _process(self, req: EmbedRequest):
        if req.kind == "embed":
            n = int(req.ids.shape[0])
            bucket = self.lattice.batch_bucket(n)
            padded = np.zeros(bucket, np.int32)
            padded[:n] = req.ids
            rows = np.asarray(self._embed_rows(padded))
            return {"vectors": rows[:n]}
        # search: pad the query batch up to its lattice bucket; padded
        # rows are zero vectors whose results are sliced away
        q = int(req.queries.shape[0])
        bucket = self.lattice.batch_bucket(q)
        padded = np.zeros((bucket, self.dim), np.float32)
        padded[:q] = req.queries
        ids, scores = self.index.search(padded, req.k, nprobe=self.nprobe)
        return {"ids": np.asarray(ids)[:q], "scores": np.asarray(scores)[:q]}

    # --------------------------------------------------------- lifecycle
    def start(self) -> "EmbeddingServingEngine":
        """Calibrate (if no nprobe was pinned), then warm every lattice
        shape. Compiles during calibration and warmup happen BEFORE the
        post-warmup trace count is snapshotted — the zero-retrace gate
        measures only traffic-time compiles."""
        if self._started:
            return self
        t0 = time.perf_counter()
        if self.nprobe is None:
            rng = np.random.default_rng(self._seed)
            sample = self._vectors[rng.choice(
                self.vocab_size,
                size=min(self._calibration_queries, self.vocab_size),
                replace=False)]
            k = max(self.k_grid)
            with self.recorder.span("compile", what="ann-calibrate"):
                self.nprobe, self.calibrated_recall = \
                    self.index.calibrate_nprobe(
                        self._vectors, sample, k,
                        floor=self.recall_floor,
                        ladder=_DEFAULT_NPROBE_LADDER)
        for b in self.lattice.batch_sizes:
            with self.recorder.span("compile", what="embed", bucket=b):
                self._embed_rows(np.zeros(b, np.int32))
            for k in self.k_grid:
                with self.recorder.span("compile", what="search",
                                        bucket=b, k=k):
                    self.index.search(np.zeros((b, self.dim), np.float32),
                                      k, nprobe=self.nprobe)
        self.warmup_s = round(time.perf_counter() - t0, 4)
        self._worker.start()
        self._started = True
        return self

    def drain(self, timeout: float = 30.0) -> None:
        """Refuse new requests, flush the queue, join the worker."""
        self._draining = True
        self._queue.put(None)
        self._worker.join(timeout)
        self.recorder.event("span", name="drain", ok=True, seconds=0.0,
                            served=self.served, failed=self.failed)

    # ------------------------------------------------------------ submit
    def _admit(self, req: EmbedRequest) -> EmbedRequest:
        if self._draining:
            raise RuntimeError("draining; not admitting requests")
        req.t_enqueue = time.monotonic()
        self._queue.put(req)
        return req

    def submit_embed(self, ids, request_id=None) -> EmbedRequest:
        """Admit an id-lookup request; returns an EmbedRequest the
        caller waits on. Rejects (ValueError — the client's 400) empty
        batches, out-of-range ids, and batches over the lattice max."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty id list")
        if ids.size > self.lattice.max_batch:
            raise ValueError(
                f"{ids.size} ids exceed the lattice max batch "
                f"{self.lattice.max_batch}")
        if ids.min() < 0 or ids.max() >= self.vocab_size:
            raise ValueError(
                f"ids must be in [0, {self.vocab_size}); got "
                f"[{ids.min()}, {ids.max()}]")
        req = EmbedRequest("embed", request_id)
        req.ids = ids.astype(np.int32)
        return self._admit(req)

    def submit_search(self, queries, k: int | None = None,
                      request_id=None) -> EmbedRequest:
        """Admit an ANN top-k request over one or more query vectors.
        `k` must be on the warmed k-grid (a foreign k would retrace)."""
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.ndim != 2 or queries.shape[1] != self.dim:
            raise ValueError(
                f"queries must be [q, {self.dim}], got {queries.shape}")
        if queries.shape[0] > self.lattice.max_batch:
            raise ValueError(
                f"{queries.shape[0]} queries exceed the lattice max "
                f"batch {self.lattice.max_batch}")
        k = self.k_grid[0] if k is None else int(k)
        if k not in self.k_grid:
            raise ValueError(
                f"k={k} is not on the warmed k grid {self.k_grid}")
        req = EmbedRequest("search", request_id)
        req.queries = queries
        req.k = k
        return self._admit(req)

    # ----------------------------------------------------- fleet surface
    def fleet_workers(self):
        return [self._worker]

    def fleet_reap(self, worker, reason: str = "died") -> int:
        """Fail the in-flight request loudly; queued requests stay in
        the FIFO for the respawned worker."""
        worker.alive = False
        worker.lifecycle = "dead"
        req = worker.current_batch
        if req is not None:
            with worker._lock:
                worker.failed += 1
            req.finish(error=f"worker reaped ({reason})")
            worker.current_batch = None
            return 1
        return 0

    def fleet_respawn(self, worker) -> None:
        """Restart the lookup thread over the same engine — the jitted
        executables survive, so respawn costs zero compiles."""
        worker.start()

    def fleet_snapshot(self) -> dict:
        return {
            "queue_depth": self._queue.qsize(),
            "n_replicas": 1,
            "n_serving": 1 if self._worker.lifecycle == "serving" else 0,
        }

    # -------------------------------------------------------------- stats
    @property
    def trace_count(self) -> int:
        count = self.index.trace_count + self._embed_traces
        if self._view is not None:
            count += self._view.engine.trace_count
        return count

    @property
    def served(self) -> int:
        with self._worker._lock:
            return self._worker.served

    @property
    def failed(self) -> int:
        with self._worker._lock:
            return self._worker.failed

    def stats(self) -> dict:
        now = time.monotonic()
        out = {
            "replicas": 1,
            "served": self.served,
            "failed": self.failed,
            "queue_depth": self._queue.qsize(),
            "trace_count": self.trace_count,
            "lattice": self.lattice.describe(),
            "fleet": [self._worker.describe(now)],
            "ann": {
                "vocab_size": self.vocab_size,
                "dim": self.dim,
                "n_partitions": self.index.n_partitions,
                "capacity": self.index.capacity,
                "nprobe": self.nprobe,
                "k_grid": list(self.k_grid),
                "recall_floor": self.recall_floor,
                "calibrated_recall": self.calibrated_recall,
            },
            "warmup_s": self.warmup_s,
        }
        if self._view is not None:
            out["memory"] = {
                "ledger": dict(self._view.engine.ledger.attributed()),
            }
        return out
