"""Word2Vec — skip-gram/CBOW word embeddings.

Reference: models/word2vec/Word2Vec.java:33 (extends
SequenceVectors<VocabWord>; Builder:76+ wires SentenceIterator +
TokenizerFactory → SentenceTransformer → sequence iterator).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.text import (
    CollectionSentenceIterator,
    SentenceIterator,
    SentenceTransformer,
    TokenizerFactory,
)


class Word2Vec(SequenceVectors):
    """Word embeddings over a sentence corpus. Use the Builder (API parity
    with the reference) or construct directly with keyword args."""

    class Builder:
        def __init__(self):
            self._kw = {}
            self._iterator: Optional[SentenceIterator] = None
            self._factory: Optional[TokenizerFactory] = None
            self._stop: Sequence[str] = ()

        def iterate(self, it):
            if isinstance(it, (list, tuple)):
                it = CollectionSentenceIterator(it)
            self._iterator = it
            return self

        def tokenizer_factory(self, f: TokenizerFactory):
            self._factory = f
            return self

        def stop_words(self, words: Sequence[str]):
            self._stop = words
            return self

        def layer_size(self, n):
            self._kw["layer_size"] = n
            return self

        def window_size(self, n):
            self._kw["window_size"] = n
            return self

        def min_word_frequency(self, n):
            self._kw["min_word_frequency"] = n
            return self

        def epochs(self, n):
            self._kw["epochs"] = n
            return self

        def iterations(self, n):  # reference alias
            return self.epochs(n)

        def learning_rate(self, a):
            self._kw["learning_rate"] = a
            return self

        def min_learning_rate(self, a):
            self._kw["min_learning_rate"] = a
            return self

        def negative_sample(self, k):
            self._kw["negative"] = int(k)
            return self

        def use_hierarchic_softmax(self, flag=True):
            self._kw["use_hs"] = flag
            return self

        def sampling(self, s):
            self._kw["sampling"] = s
            return self

        def batch_size(self, b):
            self._kw["batch_size"] = b
            return self

        def seed(self, s):
            self._kw["seed"] = int(s)
            return self

        def workers(self, n: int):
            """Host-parallel vocabulary counting processes (reference
            Builder.workers — its multi-threaded VocabConstructor /
            Spark TextPipeline analogue; see nlp/distributed_vocab.py)."""
            self._kw["n_workers"] = int(n)
            return self

        def use_device_pipeline(self, flag=True):
            """Whole-epoch on-device training (see nlp/device_pipeline.py)."""
            self._kw["use_device_pipeline"] = flag
            return self

        def use_engine(self, flag=True, ep: int = 1, dp: int = 1):
            """Route skip-gram training through the sharded embedding
            engine (embedding/engine.py; on by default). ep row-shards
            the tables over the expert mesh axis, dp data-parallelizes
            the pair batch with sparse (indices, values) gradient
            exchange. ep=1 is bit-identical to the legacy dense path."""
            self._kw["use_engine"] = flag
            self._kw["engine_ep"] = int(ep)
            self._kw["engine_dp"] = int(dp)
            return self

        def share_negatives(self, flag=True):
            """Per-center negative sharing in the device pipeline (default
            on; False = strict per-pair sampling)."""
            self._kw["pipeline_share_negatives"] = flag
            return self

        def device_mesh(self, mesh, chunk: int = 512, group=None):
            """Shard the chunk stream over mesh's 'data' axis (DP-5).
            Implies use_device_pipeline. group None = auto (smallest
            mesh multiple of the r5 default 2); pin an explicit
            mesh-multiple for device-count-invariant results."""
            self._kw["use_device_pipeline"] = True
            self._kw["device_mesh"] = mesh
            self._kw["pipeline_chunk"] = chunk
            self._kw["pipeline_group"] = group
            return self

        def negative_oversample(self, factor: float):
            """Shared-negative variance reduction: draw factor*K shared
            negatives each weighted K/M (expectation-identical to
            per-pair SGNS; default 2.0 — see nlp/device_pipeline.py)."""
            self._kw["pipeline_neg_oversample"] = float(factor)
            return self

        def elements_learning_algorithm(self, name: str):
            self._kw["elements_learning_algorithm"] = (
                "cbow" if "cbow" in name.lower() else "skipgram")
            return self

        def build(self) -> "Word2Vec":
            w2v = Word2Vec(**self._kw)
            w2v._iterator = self._iterator
            w2v._factory = self._factory
            w2v._stop = self._stop
            return w2v

    @staticmethod
    def builder() -> "Word2Vec.Builder":
        return Word2Vec.Builder()

    def __init__(self, **kw):
        # Word2Vec is a thin front-end over the sharded embedding
        # engine: skip-gram flushes run the engine's sparse-gather /
        # scatter-add step (bit-identical to the legacy dense path at
        # ep=1). CBOW and the device pipeline fall back automatically.
        kw.setdefault("use_engine", True)
        super().__init__(**kw)
        self._iterator = None
        self._factory = None
        self._stop = ()

    def _sequences(self) -> Iterable[List[str]]:
        if self._iterator is None:
            raise ValueError("No corpus: call Builder.iterate(...) or pass "
                             "sequences to fit()")
        return SentenceTransformer(self._iterator, self._factory, self._stop)

    def fit(self, sequences=None):
        if sequences is None:
            sequences = [list(t) for t in self._sequences()]
        return super().fit(sequences)

    # reference WordVectors API naming
    def word_vector(self, word: str):
        return self.get_word_vector(word)

    @property
    def vocab_size(self) -> int:
        return self.vocab.num_words() if self.vocab else 0
