"""Constituency trees (reference: text/corpora/treeparser/{TreeParser,
BinarizeTreeTransformer, CollapseUnaries, TreeVectorizer,
HeadWordFinder}.java — UIMA/OpenNLP-backed in the reference; here trees are
parsed from Penn-style bracketed strings, which is what the reference's
tree fixtures serialise to).

Capabilities: parse, binarize (right-factored), collapse unary chains,
yield/leaves, head-word lookup, and vectorisation of constituents by
averaging word vectors — feeding recursive-net style models.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np


@dataclass
class Tree:
    """A constituency tree node (reference rnn/Tree used by treeparser)."""

    label: str
    children: List["Tree"] = field(default_factory=list)
    value: Optional[str] = None  # token for leaves

    def is_leaf(self) -> bool:
        return not self.children

    def is_pre_terminal(self) -> bool:
        return len(self.children) == 1 and self.children[0].is_leaf()

    def yield_words(self) -> List[str]:
        if self.is_leaf():
            return [self.value] if self.value is not None else []
        out: List[str] = []
        for c in self.children:
            out.extend(c.yield_words())
        return out

    def depth(self) -> int:
        if self.is_leaf():
            return 0
        return 1 + max(c.depth() for c in self.children)

    def to_string(self) -> str:
        if self.is_leaf():
            return self.value or ""
        inner = " ".join(c.to_string() for c in self.children)
        return f"({self.label} {inner})"


class TreeParser:
    """Parse Penn-bracketed strings: `(S (NP (DT the) (NN cat)) (VP ...))`
    (reference TreeParser produces the same structure via OpenNLP)."""

    @staticmethod
    def parse(s: str) -> Tree:
        tokens = s.replace("(", " ( ").replace(")", " ) ").split()
        pos = 0

        def read() -> Tree:
            nonlocal pos
            if tokens[pos] != "(":
                raise ValueError(f"expected '(' at token {pos}")
            pos += 1
            label = tokens[pos]
            pos += 1
            node = Tree(label)
            while tokens[pos] != ")":
                if tokens[pos] == "(":
                    node.children.append(read())
                else:
                    node.children.append(Tree("TOK", value=tokens[pos]))
                    pos += 1
            pos += 1
            return node

        tree = read()
        if pos != len(tokens):
            raise ValueError("trailing tokens after tree")
        return tree


def binarize(tree: Tree) -> Tree:
    """Right-factored binarization (BinarizeTreeTransformer): n-ary nodes
    become nested @-labelled binary nodes."""
    if tree.is_leaf():
        return Tree(tree.label, value=tree.value)
    kids = [binarize(c) for c in tree.children]
    while len(kids) > 2:
        right = Tree(f"@{tree.label}", children=kids[-2:])
        kids = kids[:-2] + [right]
    return Tree(tree.label, children=kids, value=tree.value)


def collapse_unaries(tree: Tree) -> Tree:
    """Collapse unary chains A→B→... to a single A_B node (CollapseUnaries);
    pre-terminals are kept so tokens stay attached to their POS."""
    node = tree
    labels = [node.label]
    while (len(node.children) == 1 and not node.is_pre_terminal()
           and not node.children[0].is_leaf()
           and not node.children[0].is_pre_terminal()):
        node = node.children[0]
        labels.append(node.label)
    collapsed = Tree("_".join(labels), value=node.value)
    collapsed.children = [collapse_unaries(c) for c in node.children]
    return collapsed


class HeadWordFinder:
    """Rightmost-leaf head heuristic (reference HeadWordFinder implements
    Collins-style rules; the rightmost-content-word default covers the
    common English head direction)."""

    @staticmethod
    def find_head(tree: Tree) -> Optional[str]:
        words = tree.yield_words()
        return words[-1] if words else None


class TreeVectorizer:
    """Vectorise constituents by averaging word vectors over each subtree's
    yield (reference TreeVectorizer feeds tree-structured models from
    word2vec vectors)."""

    def __init__(self, word_vector_fn: Callable[[str], Optional[np.ndarray]],
                 dim: int):
        self.word_vector_fn = word_vector_fn
        self.dim = dim

    def vectorize(self, tree: Tree) -> np.ndarray:
        vecs = [v for v in (self.word_vector_fn(w)
                            for w in tree.yield_words()) if v is not None]
        if not vecs:
            return np.zeros(self.dim, np.float32)
        return np.mean(vecs, axis=0).astype(np.float32)

    def vectorize_all(self, tree: Tree) -> List[np.ndarray]:
        """One vector per node, preorder."""
        out = [self.vectorize(tree)]
        for c in tree.children:
            out.extend(self.vectorize_all(c))
        return out
