"""On-device skip-gram training pipeline (TPU-native word2vec hot path).

The reference trains word2vec with host-side per-pair loops
(`embeddings/learning/impl/elements/SkipGram.java:160-229`, HogWild threads
racing on shared arrays) and ships pair buffers to workers in the Spark
variant (`spark/dl4j-spark-nlp/.../Word2VecPerformer.java:46-246`). Both
designs are host/IO bound. Here the WHOLE epoch runs on device:

- the token stream (with sentence ids) is uploaded ONCE per epoch;
- dynamic-window pair generation, unigram^0.75 negative sampling, the
  SGNS forward/backward, and the scatter updates are all inside one
  jitted `lax.scan` over fixed-size chunks — zero host round-trips;
- learning-rate decay follows scan progress (word2vec linear alpha);
- optionally the chunk stream is sharded over a mesh 'data' axis
  (DP-5): each device computes gradient tables for its chunks, a psum
  merges them, and one shared update is applied — the synchronous
  equivalent of Word2VecPerformer's accumulated updates, with the same
  result on any device count (gradient sums are order-free).

Semantics follow the batched host path (`lookup.sgns_step`): per-update
summed gradients with the MAX_ROW_STEP trust region; negatives drawn from
the same unigram^0.75 distribution (on device via Walker alias tables).
By default SGNS shares each center's K negatives across its context slots
with pair-count weighting (`share_negatives=True`) — expectation-
equivalent to per-pair draws with ~10x fewer scatter rows; pass
`share_negatives=False` (SequenceVectors: `pipeline_share_negatives`)
for strict per-pair sampling.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.lookup import MAX_ROW_STEP


def build_alias_table(probs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Walker alias tables (J, q) for O(1) categorical sampling on device.

    jnp.searchsorted over the unigram CDF costs tens of ms per update on
    TPU (binary-search gathers don't vectorize well); the alias method is
    two gathers and a select. Host construction is O(V)."""
    p = np.asarray(probs, np.float64)
    p = p / p.sum()
    V = len(p)
    q = p * V
    J = np.zeros(V, np.int32)
    small = [i for i in range(V) if q[i] < 1.0]
    large = [i for i in range(V) if q[i] >= 1.0]
    while small and large:
        s_ = small.pop()
        l_ = large.pop()
        J[s_] = l_
        q[l_] = q[l_] - (1.0 - q[s_])
        (small if q[l_] < 1.0 else large).append(l_)
    for i in small + large:
        q[i] = 1.0
    return J, q.astype(np.float32)


def _alias_sample(key, J, q, shape):
    k1, k2 = jax.random.split(key)
    i = jax.random.randint(k1, shape, 0, J.shape[0])
    coin = jax.random.uniform(k2, shape)
    return jnp.where(coin < q[i], i, J[i]).astype(jnp.int32)


def pack_corpus_flat(tokens: np.ndarray, sent_ids: np.ndarray,
                     multiple: int) -> Tuple[np.ndarray, np.ndarray]:
    """Pad an already-flat (tokens, sent_ids) pair to a multiple of
    `multiple`; padding carries sent_id -1 (never pairs). Pairing only
    compares sent ids for equality, so gaps in the numbering (empty or
    all-OOV sentences) are fine."""
    if len(tokens) == 0:
        raise ValueError("empty corpus")
    tokens = np.asarray(tokens, np.int32)
    sent_ids = np.asarray(sent_ids, np.int32)
    pad = (-len(tokens)) % multiple
    if pad:
        tokens = np.concatenate([tokens, np.zeros(pad, np.int32)])
        sent_ids = np.concatenate([sent_ids, np.full(pad, -1, np.int32)])
    return tokens, sent_ids


def pack_corpus(idx_seqs: List[np.ndarray], multiple: int
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten index sequences into (tokens [N], sent_ids [N]) padded to a
    multiple of `multiple`; padding carries sent_id -1 (never pairs)."""
    seqs = [np.asarray(s, np.int32) for s in idx_seqs if len(s) > 0]
    if not seqs:
        raise ValueError("empty corpus")
    tokens = np.concatenate(seqs)
    sent_ids = np.concatenate(
        [np.full(len(s), i, np.int32) for i, s in enumerate(seqs)])
    return pack_corpus_flat(tokens, sent_ids, multiple)


def _chunk_pair_grads(syn0, syn1neg, tokens, sent_ids, alias_J, alias_q,
                      start, key, *, chunk, window, K, share_negatives=True,
                      neg_oversample=2.0):
    """Pair gradients for `chunk` consecutive center positions.

    Returns per-pair gradient pieces (no dense tables — those are built
    once per update so a vmap over chunks stays memory-light) plus the
    masked loss sum and valid-pair count.

    share_negatives: the K negatives are drawn once PER CENTER and each
    contributes with weight n_valid_pairs(center) — expectation-equivalent
    to per-pair draws (negatives score only against the center vector in
    SGNS) with 2w x fewer sampled rows, so the scatter update shrinks ~10x
    (the negative-sharing batching SURVEY.md §7 calls for). Set False for
    strict per-pair sampling.
    """
    centers, ctx, valid, kn = _window_context(
        tokens, sent_ids, start, key, chunk=chunk, window=window)
    c = syn0[centers]                                      # [S, D]
    posv = syn1neg[ctx]                                    # [S, 2w, D]
    pos_score = jax.nn.sigmoid(jnp.einsum("sd,swd->sw", c, posv))
    vm = valid.astype(c.dtype)
    g_pos = (pos_score - 1.0) * vm                         # [S, 2w]
    grad_pos = g_pos[..., None] * c[:, None, :]            # [S, 2w, D]
    eps = 1e-10
    loss = -jnp.sum(jnp.log(pos_score + eps) * vm)

    grad_c_pos = jnp.einsum("sw,swd->sd", g_pos, posv)     # shared term
    if share_negatives:
        # variance reduction (r5): draw M = oversample*K shared negatives
        # and weight each by pair_cnt * K/M — the objective EXPECTATION
        # stays exactly per-pair SGNS with K negatives (the reference
        # semantics), while the shared-draw variance drops by 1/oversample.
        # Measured on the topic corpus: oversample 2 closes most of the
        # shared-vs-unshared quality gap at ~15% extra step cost (the
        # negatives touch only the center score, not the 2w context rows).
        M = max(int(round(K * neg_oversample)), 1)
        w_neg = K / M
        negs = _alias_sample(kn, alias_J, alias_q, (chunk, M))  # [S, M]
        negv = syn1neg[negs]                               # [S, M, D]
        neg_score = jax.nn.sigmoid(jnp.einsum("sd,skd->sk", c, negv))
        pair_cnt = vm.sum(-1)                              # [S]
        g_neg = neg_score * (w_neg * pair_cnt[:, None])    # [S, M]
        grad_c = grad_c_pos + jnp.einsum("sk,skd->sd", g_neg, negv)
        grad_neg = g_neg[..., None] * c[:, None, :]        # [S, M, D]
        loss = loss - jnp.sum(
            jnp.log(1.0 - neg_score + eps)
            * (w_neg * pair_cnt[:, None]))
    else:
        negs = _alias_sample(kn, alias_J, alias_q,
                             (chunk, 2 * window, K))        # [S, 2w, K]
        negv = syn1neg[negs]                               # [S, 2w, K, D]
        neg_score = jax.nn.sigmoid(jnp.einsum("sd,swkd->swk", c, negv))
        g_neg = neg_score * vm[..., None]                  # [S, 2w, K]
        grad_c = grad_c_pos + jnp.einsum("swk,swkd->sd", g_neg, negv)
        grad_neg = g_neg[..., None] * c[:, None, None, :]  # [S, 2w, K, D]
        loss = loss - jnp.sum(jnp.log(1.0 - neg_score + eps) * vm[..., None])

    return centers, grad_c, ctx, grad_pos, negs, grad_neg, loss, vm.sum()


def _trust_region_apply(table, grad, lr):
    """table - lr*grad with the per-row step-norm cap (see
    lookup._scatter_update — identical trust-region semantics)."""
    step = lr * grad
    n = jnp.linalg.norm(step, axis=1, keepdims=True)
    return table - step * jnp.minimum(1.0, MAX_ROW_STEP / jnp.maximum(n, 1e-12))


def _window_context(tokens, sent_ids, start, key, *, chunk, window):
    """Dynamic-window context extraction shared by SGNS and CBOW chunks:
    returns (centers, ctx [S,2w], valid [S,2w], kn) where kn is the
    remaining rng key for negative sampling."""
    N = tokens.shape[0]
    pos = start + jnp.arange(chunk)
    centers = tokens[pos]
    csent = sent_ids[pos]
    kb, kn = jax.random.split(key)
    # word2vec dynamic window: per center, b ~ uniform{1..window}
    b = jax.random.randint(kb, (chunk,), 1, window + 1)
    offs = jnp.asarray(np.concatenate(
        [np.arange(-window, 0, dtype=np.int32),
         np.arange(1, window + 1, dtype=np.int32)]), jnp.int32)
    cpos = pos[:, None] + offs[None, :]
    cposc = jnp.clip(cpos, 0, N - 1)
    valid = ((cpos >= 0) & (cpos < N)
             & (sent_ids[cposc] == csent[:, None])
             & (jnp.abs(offs)[None, :] <= b[:, None])
             & (csent[:, None] >= 0))
    return centers, tokens[cposc], valid, kn


def _chunk_cbow_grads(syn0, syn1neg, tokens, sent_ids, alias_J, alias_q,
                      start, key, *, chunk, window, K):
    """CBOW pair gradients for `chunk` consecutive center positions:
    h = mean(context vectors) predicts the center against K negatives
    (reference CBOW.java semantics, batched)."""
    centers, ctx, valid, kn = _window_context(
        tokens, sent_ids, start, key, chunk=chunk, window=window)
    vm = valid.astype(syn0.dtype)
    cnt = jnp.maximum(vm.sum(-1, keepdims=True), 1.0)     # [S, 1]
    ctxv = syn0[ctx] * vm[..., None]                       # [S, 2w, D]
    h = ctxv.sum(1) / cnt                                  # [S, D]
    has_ctx = (vm.sum(-1) > 0).astype(syn0.dtype)          # centers w/ window

    negs = _alias_sample(kn, alias_J, alias_q, (chunk, K))  # [S, K]
    tgt = syn1neg[centers]                                  # [S, D]
    negv = syn1neg[negs]                                    # [S, K, D]
    pos_score = jax.nn.sigmoid(jnp.einsum("sd,sd->s", h, tgt))
    neg_score = jax.nn.sigmoid(jnp.einsum("sd,skd->sk", h, negv))
    g_pos = (pos_score - 1.0) * has_ctx                     # [S]
    g_neg = neg_score * has_ctx[:, None]                    # [S, K]

    grad_h = (g_pos[:, None] * tgt
              + jnp.einsum("sk,skd->sd", g_neg, negv))      # [S, D]
    # d h / d ctx_row = vm / cnt
    grad_ctx = grad_h[:, None, :] * (vm / cnt)[..., None]   # [S, 2w, D]
    grad_tgt = g_pos[:, None] * h                           # [S, D]
    grad_neg = g_neg[..., None] * h[:, None, :]             # [S, K, D]

    eps = 1e-10
    loss = -(jnp.sum(jnp.log(pos_score + eps) * has_ctx)
             + jnp.sum(jnp.log(1.0 - neg_score + eps) * has_ctx[:, None]))
    return ctx, grad_ctx, centers, grad_tgt, negs, grad_neg, loss, has_ctx.sum()


def make_cbow_epoch(*, window: int, negative: int, chunk: int = 512,
                    group: int = 4, mesh=None):
    """CBOW analogue of make_sgns_epoch — same scan/update/mesh contract;
    syn0 receives context-row gradients, syn1neg center+negative rows."""
    K = negative
    pair_grads = partial(_chunk_cbow_grads, chunk=chunk, window=window, K=K)

    def local_grads(syn0, syn1neg, tokens, sent_ids, aJ, aq, starts, keys):
        (ctx, grad_ctx, centers, grad_tgt, negs, grad_neg, loss, pairs
         ) = jax.vmap(lambda s, k: pair_grads(
             syn0, syn1neg, tokens, sent_ids, aJ, aq, s, k))(starts, keys)
        D = syn0.shape[1]
        g0 = jnp.zeros_like(syn0).at[ctx.reshape(-1)].add(
            grad_ctx.reshape(-1, D))
        g1 = (jnp.zeros_like(syn1neg)
              .at[centers.reshape(-1)].add(grad_tgt.reshape(-1, D))
              .at[negs.reshape(-1)].add(grad_neg.reshape(-1, D)))
        return g0, g1, jnp.sum(loss), jnp.sum(pairs)

    return _build_epoch(local_grads, chunk=chunk, group=group, mesh=mesh)


def make_sgns_epoch(*, window: int, negative: int, chunk: int = 512,
                    group: int = 4, mesh=None, share_negatives: bool = True,
                    neg_oversample: float = 2.0):
    """Build the jitted epoch function.

    epoch(syn0, syn1neg, tokens, sent_ids, alias_J, alias_q, key, lr0, lr1)
      -> (syn0, syn1neg, per_update_loss [U], per_update_pairs [U])
    (alias_J, alias_q from build_alias_table over the unigram^0.75 dist)

    One update = `group` chunks of `chunk` centers with summed gradients
    (a global batch). With `mesh`, the group dimension is sharded over the
    mesh's 'data' axis and gradients are psum-merged — numerically the
    same update as single-device, so device count never changes results.
    """
    K = negative
    pair_grads = partial(_chunk_pair_grads, chunk=chunk, window=window, K=K,
                         share_negatives=share_negatives,
                         neg_oversample=neg_oversample)

    def local_grads(syn0, syn1neg, tokens, sent_ids, aJ, aq, starts, keys):
        (centers, grad_c, ctx, grad_pos, negs, grad_neg, loss, pairs
         ) = jax.vmap(lambda s, k: pair_grads(
             syn0, syn1neg, tokens, sent_ids, aJ, aq, s, k))(starts, keys)
        D = syn0.shape[1]
        g0 = jnp.zeros_like(syn0).at[centers.reshape(-1)].add(
            grad_c.reshape(-1, D))
        g1 = (jnp.zeros_like(syn1neg)
              .at[ctx.reshape(-1)].add(grad_pos.reshape(-1, D))
              .at[negs.reshape(-1)].add(grad_neg.reshape(-1, D)))
        return g0, g1, jnp.sum(loss), jnp.sum(pairs)

    return _build_epoch(local_grads, chunk=chunk, group=group, mesh=mesh)


def _build_epoch(local_grads, *, chunk, group, mesh):
    """Scan/update/mesh scaffolding shared by the SGNS and CBOW epochs."""
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        from deeplearning4j_tpu.util.compat import shard_map

        n_dev = mesh.shape["data"]
        if group % n_dev:
            raise ValueError(f"group={group} not divisible by mesh data "
                             f"axis size {n_dev}")

        def sharded_grads(syn0, syn1neg, tokens, sent_ids, aJ, aq, starts,
                          keys):
            g0, g1, loss, pairs = local_grads(
                syn0, syn1neg, tokens, sent_ids, aJ, aq, starts, keys)
            return (jax.lax.psum(g0, "data"), jax.lax.psum(g1, "data"),
                    jax.lax.psum(loss, "data"), jax.lax.psum(pairs, "data"))

        grads_fn = shard_map(
            sharded_grads, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P(), P()))
    else:
        grads_fn = local_grads

    @partial(jax.jit, donate_argnums=(0, 1))
    def epoch(syn0, syn1neg, tokens, sent_ids, aJ, aq, key, lr0, lr1):
        N = tokens.shape[0]
        per_update = chunk * group
        n_up = max(N // per_update, 1)

        def body(carry, u):
            s0, s1 = carry
            starts = u * per_update + jnp.arange(group) * chunk
            keys = jax.vmap(jax.random.fold_in, (None, 0))(
                key, u * group + jnp.arange(group))
            g0, g1, loss, pairs = grads_fn(s0, s1, tokens, sent_ids, aJ, aq,
                                           starts, keys)
            lr = lr0 + (lr1 - lr0) * (u.astype(s0.dtype) / n_up)
            s0 = _trust_region_apply(s0, g0, lr)
            s1 = _trust_region_apply(s1, g1, lr)
            return (s0, s1), (loss, pairs)

        (syn0, syn1neg), (losses, pairs) = jax.lax.scan(
            body, (syn0, syn1neg), jnp.arange(n_up))
        return syn0, syn1neg, losses, pairs

    return epoch
