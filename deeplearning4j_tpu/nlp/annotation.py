"""Pluggable text-annotation engines — the UIMA AnalysisEngine slot.

Reference: text/uima/UimaResource.java wraps a UIMA AnalysisEngine +
CasPool; PosUimaTokenizer.java / UimaTokenizer.java and
UimaSentenceIterator.java run sentence-segmentation / tokenization / POS
analysis engines over documents. This module provides the same pluggable
seam without the UIMA machinery: an ``AnnotationEngine`` protocol with

- ``LexiconAnnotationEngine`` (default): pure-python regex sentence
  splitter + whitespace/punct tokenizer + the lexicon/suffix POS tagger
  from `nlp/sentiment.py` — zero dependencies, deterministic.
- ``SpacyAnnotationEngine``: routes all three through a spaCy pipeline
  when spacy + a model are installed (the optional industrial-strength
  engine, like swapping a different UIMA AE descriptor in the reference).

`set_annotation_engine` swaps the process default; the POS-aware
tokenizer factory and sentence detector below route through whatever
engine is current, mirroring how every reference UIMA consumer goes
through UimaResource.
"""

from __future__ import annotations

import re
from typing import Iterable, List, Optional, Tuple


class AnnotationEngine:
    """Protocol of the reference's UIMA AnalysisEngine consumers: sentence
    segmentation (SentenceAnnotator), tokenization (TokenizerAnnotator)
    and POS tagging (PoStagger)."""

    def sentences(self, text: str) -> List[str]:
        raise NotImplementedError

    def tokenize(self, text: str) -> List[str]:
        raise NotImplementedError

    def pos_tags(self, tokens: Iterable[str]) -> List[Tuple[str, str]]:
        raise NotImplementedError

    def annotate(self, text: str) -> List[List[Tuple[str, str]]]:
        """Full document pass: sentences -> tokens -> (token, pos) — the
        shape of the reference's CAS after the sentence/token/POS AEs."""
        return [self.pos_tags(self.tokenize(s)) for s in self.sentences(text)]


_SENT_RE = re.compile(r"(?<=[.!?])\s+(?=[\"'(\[]?[A-Z0-9])")


class LexiconAnnotationEngine(AnnotationEngine):
    """Default engine: regex sentence boundaries (terminal punctuation
    followed by a capitalized/numeric start), regex word tokenizer, and
    the lexicon+suffix POS tagger (`nlp/sentiment.pos_tag`)."""

    def sentences(self, text: str) -> List[str]:
        parts = _SENT_RE.split(text.strip())
        return [p.strip() for p in parts if p.strip()]

    def tokenize(self, text: str) -> List[str]:
        return re.findall(r"\w+(?:'\w+)?|[^\w\s]", text)

    def pos_tags(self, tokens: Iterable[str]) -> List[Tuple[str, str]]:
        from deeplearning4j_tpu.nlp.sentiment import pos_tag

        return pos_tag(tokens)


# spaCy coarse tags -> the SentiWordNet letters the lexicon engine emits
_SPACY_TO_SWN = {
    "NOUN": "n", "PROPN": "n", "PRON": "n", "NUM": "n",
    "VERB": "v", "AUX": "v",
    "ADJ": "a",
    "ADV": "r", "PART": "r",
    "DET": "d", "CCONJ": "c", "SCONJ": "c", "ADP": "p",
}


class SpacyAnnotationEngine(AnnotationEngine):
    """Optional spaCy-backed engine (available() gates on the install).
    Tags map onto the same n/v/a/r/d/c/p letters so SentiWordNet scoring
    and `word#pos` keying work identically across engines."""

    def __init__(self, model: str = "en_core_web_sm"):
        import spacy  # raises ImportError when not installed

        try:
            self._nlp = spacy.load(model)
        except OSError:
            # no downloaded model: blank pipeline with the rule sentencizer
            self._nlp = spacy.blank("en")
            self._nlp.add_pipe("sentencizer")

    @staticmethod
    def available() -> bool:
        try:
            import spacy  # noqa: F401
            return True
        except ImportError:
            return False

    def sentences(self, text: str) -> List[str]:
        return [s.text.strip() for s in self._nlp(text).sents
                if s.text.strip()]

    def tokenize(self, text: str) -> List[str]:
        return [t.text for t in self._nlp(text) if not t.is_space]

    def pos_tags(self, tokens: Iterable[str]) -> List[Tuple[str, str]]:
        toks = list(tokens)
        doc = self._nlp(" ".join(toks))
        tags = [_SPACY_TO_SWN.get(t.pos_, "n") for t in doc if not t.is_space]
        if len(tags) == len(toks):
            return list(zip(toks, tags))
        # tokenization drift (spaCy re-split a token): fall back per-token
        return [(t, _SPACY_TO_SWN.get(self._nlp(t)[0].pos_, "n") if t else "n")
                for t in toks]


_engine: AnnotationEngine = LexiconAnnotationEngine()


def get_annotation_engine() -> AnnotationEngine:
    return _engine


def set_annotation_engine(engine: Optional[AnnotationEngine]) -> None:
    """Swap the process-default engine (None restores the lexicon
    default) — the UimaResource.setAE analogue."""
    global _engine
    _engine = engine if engine is not None else LexiconAnnotationEngine()


class SentenceDetector:
    """Segment raw documents into sentences through the current engine
    (reference UimaSentenceIterator's SentenceAnnotator pass)."""

    def __init__(self, engine: Optional[AnnotationEngine] = None):
        self.engine = engine

    def detect(self, text: str) -> List[str]:
        return (self.engine or get_annotation_engine()).sentences(text)


class AnnotationTokenizerFactory:
    """TokenizerFactory emitting `word#pos` tokens through the current
    engine (reference PosUimaTokenizer: tokens keyed by UIMA POS for
    sense-separated vocabularies)."""

    def __init__(self, engine: Optional[AnnotationEngine] = None):
        self.engine = engine

    def create(self, text: str):
        from deeplearning4j_tpu.nlp.text import Tokenizer

        eng = self.engine or get_annotation_engine()
        tagged = eng.pos_tags(eng.tokenize(text))
        return Tokenizer([f"{w}#{p}" for w, p in tagged])
