"""Embedding lookup table + batched device training steps.

Reference (SURVEY.md §2.3):
- embeddings/inmemory/InMemoryLookupTable.java:62 — syn0/syn1/syn1Neg,
  init rand(vocab,dim).subi(0.5).divi(dim):133, expTable sigmoid lookup
- embeddings/learning/impl/elements/SkipGram.java:160-229 — per-pair
  hierarchical-softmax dot/axpy + negative sampling (HogWild, BLAS-1)
- embeddings/learning/impl/elements/CBOW.java
- embeddings/reader/impl/{BasicModelUtils,FlatModelUtils} — wordsNearest

TPU-native redesign (SURVEY.md §3.4 TPU mapping): the reference updates one
(word, context) pair at a time with racing threads. Here a whole batch of
pairs becomes ONE jitted computation: gather rows → dense dot products →
sigmoid losses → scatter-add updates (`.at[].add` sums duplicate indices,
which XLA lowers to an on-device scatter). No expTable — the MXU/VPU
computes sigmoids directly. Gradients are CLOSED-FORM (the σ(x)−label form
the reference hand-codes), applied with plain SGD exactly like word2vec.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _sigmoid(x):
    return jax.nn.sigmoid(x)


MAX_ROW_STEP = 0.1  # trust-region cap on a row's per-batch movement


def _scatter_update(table, idx, grads, lr, weight=None):
    """Apply -lr * per-row SUM of gradients, with a per-row step-norm cap.

    The reference (and word2vec) applies pairs sequentially, so a word seen
    k times in a batch moves k small lr-steps. The batched sum reproduces
    that k*lr*avg_grad movement, but for degenerate corpora (tiny vocab →
    hundreds of duplicates per batch) the summed step overshoots the
    logistic-loss stability bound and diverges. Capping each row's step
    L2-norm (trust region) keeps sequential-SGD-speed learning for
    realistic sparse duplication and bounded steps in the worst case.
    Masked/padding entries must carry zero grads (they add nothing to the
    row sums). idx [N], grads [N, D]."""
    del weight  # masked grads are zeroed by callers
    sums = jnp.zeros_like(table).at[idx].add(grads.astype(table.dtype))
    step = lr * sums
    n = jnp.linalg.norm(step, axis=1, keepdims=True)
    step = step * jnp.minimum(1.0, MAX_ROW_STEP / jnp.maximum(n, 1e-12))
    return table - step


# --------------------------------------------------------------------------
# Skip-gram with negative sampling — batched
# --------------------------------------------------------------------------
@partial(jax.jit, donate_argnums=(0, 1))
def sgns_step(syn0, syn1neg, center, context, negatives, lr):
    """One SGD step on a batch of skip-gram pairs with K negatives each.

    center [B], context [B], negatives [B,K] int32; lr scalar.
    loss = -log σ(c·v_pos) - Σ_k log σ(-c·v_negk)   (word2vec SGNS)
    """
    c = syn0[center]                       # [B, D]
    pos = syn1neg[context]                 # [B, D]
    neg = syn1neg[negatives]               # [B, K, D]

    pos_score = _sigmoid(jnp.einsum("bd,bd->b", c, pos))        # [B]
    neg_score = _sigmoid(jnp.einsum("bd,bkd->bk", c, neg))      # [B, K]

    g_pos = (pos_score - 1.0)[:, None]     # dL/d(c·pos)
    g_neg = neg_score[:, :, None]          # dL/d(c·neg)

    grad_c = g_pos * pos + jnp.einsum("bko,bkd->bd", g_neg, neg)
    grad_pos = g_pos * c
    grad_neg = g_neg * c[:, None, :]       # [B, K, D]

    B, K = negatives.shape
    syn0 = _scatter_update(syn0, center, grad_c, lr)
    out_idx = jnp.concatenate([context, negatives.reshape(B * K)])
    out_grad = jnp.concatenate([grad_pos, grad_neg.reshape(B * K, -1)])
    syn1neg = _scatter_update(syn1neg, out_idx, out_grad, lr)

    loss = -(jnp.sum(jnp.log(pos_score + 1e-10))
             + jnp.sum(jnp.log(1.0 - neg_score + 1e-10)))
    return syn0, syn1neg, loss / B


# --------------------------------------------------------------------------
# Skip-gram with hierarchical softmax — batched
# --------------------------------------------------------------------------
@partial(jax.jit, donate_argnums=(0, 1))
def sg_hs_step(syn0, syn1, center, codes, points, mask, lr):
    """Hierarchical-softmax step (reference SkipGram.iterateSample:181-197).

    center [B]; codes [B,L] (0/1 per tree branch); points [B,L] inner-node
    rows of syn1; mask [B,L] valid-depth mask.
    loss = -Σ_d log σ((1-2*code_d) * c·syn1[point_d])
    """
    c = syn0[center]                       # [B, D]
    nodes = syn1[points]                   # [B, L, D]
    sign = 1.0 - 2.0 * codes.astype(c.dtype)                    # [B, L]
    logit = jnp.einsum("bd,bld->bl", c, nodes)
    p = _sigmoid(sign * logit)
    m = mask.astype(c.dtype)

    # dL/dlogit = -sign*(1-p)  (masked)
    g = -sign * (1.0 - p) * m              # [B, L]
    grad_c = jnp.einsum("bl,bld->bd", g, nodes)
    grad_nodes = g[:, :, None] * c[:, None, :]

    B, L = codes.shape
    syn0 = _scatter_update(syn0, center, grad_c, lr)
    # masked-out depths carry zero grads; route them to row 0 with weight 0
    flat_pts = jnp.where(mask, points, 0).reshape(B * L)
    syn1 = _scatter_update(
        syn1, flat_pts, (grad_nodes * m[:, :, None]).reshape(B * L, -1), lr,
        weight=None)

    loss = -jnp.sum(jnp.log(p + 1e-10) * m)
    return syn0, syn1, loss / B


# --------------------------------------------------------------------------
# CBOW — batched (negative sampling); also serves PV-DM with doc column
# --------------------------------------------------------------------------
@partial(jax.jit, donate_argnums=(0, 1))
def cbow_ns_step(syn0, syn1neg, context, context_mask, target, negatives, lr):
    """CBOW: mean of context vectors predicts the target
    (reference CBOW.java). context [B,W] padded, context_mask [B,W],
    target [B], negatives [B,K].
    """
    ctx = syn0[context]                                  # [B, W, D]
    m = context_mask.astype(ctx.dtype)[:, :, None]
    denom = jnp.maximum(m.sum(axis=1), 1.0)              # [B, 1]
    h = (ctx * m).sum(axis=1) / denom                    # [B, D]

    pos = syn1neg[target]
    neg = syn1neg[negatives]
    pos_score = _sigmoid(jnp.einsum("bd,bd->b", h, pos))
    neg_score = _sigmoid(jnp.einsum("bd,bkd->bk", h, neg))

    g_pos = (pos_score - 1.0)[:, None]
    g_neg = neg_score[:, :, None]
    grad_h = g_pos * pos + jnp.einsum("bko,bkd->bd", g_neg, neg)   # [B, D]
    grad_ctx = (grad_h[:, None, :] / denom[:, None, :]) * m        # [B, W, D]

    B, W = context.shape
    K = negatives.shape[1]
    flat_ctx = jnp.where(context_mask, context, 0).reshape(B * W)
    syn0 = _scatter_update(syn0, flat_ctx, grad_ctx.reshape(B * W, -1), lr,
                         weight=None)
    out_idx = jnp.concatenate([target, negatives.reshape(B * K)])
    out_grad = jnp.concatenate(
        [g_pos * h, (g_neg * h[:, None, :]).reshape(B * K, -1)])
    syn1neg = _scatter_update(syn1neg, out_idx, out_grad, lr)

    loss = -(jnp.sum(jnp.log(pos_score + 1e-10))
             + jnp.sum(jnp.log(1.0 - neg_score + 1e-10)))
    return syn0, syn1neg, loss / B


# --------------------------------------------------------------------------
# Inference-only variants (frozen syn1) for ParagraphVectors.infer_vector
# --------------------------------------------------------------------------
@jax.jit
def infer_sgns_step(vec, syn1neg, context, negatives, lr):
    """Train a single free vector against frozen output weights.
    vec [D]; context [B]; negatives [B,K]."""
    pos = syn1neg[context]                               # [B, D]
    neg = syn1neg[negatives]                             # [B, K, D]
    pos_score = _sigmoid(pos @ vec)                      # [B]
    neg_score = _sigmoid(jnp.einsum("bkd,d->bk", neg, vec))
    grad = ((pos_score - 1.0)[:, None] * pos).sum(0) + \
        jnp.einsum("bk,bkd->d", neg_score, neg)
    loss = -(jnp.sum(jnp.log(pos_score + 1e-10))
             + jnp.sum(jnp.log(1.0 - neg_score + 1e-10)))
    return vec - lr * grad, loss


@jax.jit
def infer_hs_step(vec, syn1, codes, points, mask, lr):
    """Hierarchical-softmax counterpart of infer_sgns_step: one free vector
    against the frozen Huffman inner nodes. codes/points/mask [B, L]."""
    nodes = syn1[points]                                 # [B, L, D]
    sign = 1.0 - 2.0 * codes.astype(vec.dtype)
    p = _sigmoid(sign * jnp.einsum("d,bld->bl", vec, nodes))
    m = mask.astype(vec.dtype)
    g = -sign * (1.0 - p) * m
    grad = jnp.einsum("bl,bld->d", g, nodes)
    loss = -jnp.sum(jnp.log(p + 1e-10) * m)
    return vec - lr * grad, loss


# --------------------------------------------------------------------------
# The lookup table object
# --------------------------------------------------------------------------
class InMemoryLookupTable:
    """Embedding storage (reference InMemoryLookupTable.java:62).

    syn0: input embeddings [V, D]; syn1: HS inner nodes; syn1neg: NS output
    embeddings. Device arrays — updates happen in the jitted steps above.
    """

    def __init__(self, vocab_size: int, vector_length: int,
                 seed: int = 123, use_hs: bool = False, negative: int = 5,
                 dtype=jnp.float32):
        self.vocab_size = vocab_size
        self.vector_length = vector_length
        self.use_hs = use_hs
        self.negative = negative
        self.dtype = dtype
        self.seed = seed
        self.reset_weights()

    def reset_weights(self):
        key = jax.random.PRNGKey(self.seed)
        # reference init: (rand - 0.5) / dim   (InMemoryLookupTable.java:133)
        self.syn0 = ((jax.random.uniform(
            key, (self.vocab_size, self.vector_length)) - 0.5)
            / self.vector_length).astype(self.dtype)
        self.syn1 = jnp.zeros((self.vocab_size, self.vector_length), self.dtype)
        self.syn1neg = jnp.zeros(
            (self.vocab_size, self.vector_length), self.dtype)

    # vectors --------------------------------------------------------------
    def vector(self, index: int) -> np.ndarray:
        return np.asarray(self.syn0[index])

    def vectors(self) -> np.ndarray:
        return np.asarray(self.syn0)

    def set_vectors(self, arr: np.ndarray):
        self.syn0 = jnp.asarray(arr, self.dtype)
        self.vocab_size, self.vector_length = arr.shape

    # similarity (reference BasicModelUtils.wordsNearest — brute-force
    # cosine; on TPU one normalized matmul + top_k) ------------------------
    def _normed(self):
        n = jnp.linalg.norm(self.syn0, axis=1, keepdims=True)
        return self.syn0 / jnp.maximum(n, 1e-12)

    def nearest(self, query_vec: np.ndarray, top_n: int = 10,
                exclude=()) -> list:
        normed = self._normed()
        q = jnp.asarray(query_vec, self.dtype)
        q = q / jnp.maximum(jnp.linalg.norm(q), 1e-12)
        sims = normed @ q
        if exclude:
            sims = sims.at[jnp.asarray(list(exclude))].set(-jnp.inf)
        vals, idx = jax.lax.top_k(sims, min(top_n, self.vocab_size))
        return list(zip(np.asarray(idx).tolist(), np.asarray(vals).tolist()))

    def similarity(self, i: int, j: int) -> float:
        a, b = self.syn0[i], self.syn0[j]
        denom = jnp.linalg.norm(a) * jnp.linalg.norm(b)
        return float(jnp.vdot(a, b) / jnp.maximum(denom, 1e-12))
