"""ParagraphVectors (doc2vec) — PV-DBOW and PV-DM.

Reference: models/paragraphvectors/ParagraphVectors.java (labels as extra
sequence elements trained alongside words; inferVector for unseen docs);
sequence learning algorithms impl/sequence/{DBOW,DM}.java.

TPU design: label vectors are extra rows of syn0 (rows [V, V+n_labels)).
PV-DBOW = skip-gram pairs (label → every word); PV-DM = CBOW windows with
the label appended as a context column. infer_vector trains ONE free row
against frozen output weights (nlp/lookup.infer_sgns_step).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.lookup import infer_hs_step, infer_sgns_step
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.text import (
    LabelAwareIterator,
    LabelAwareListSentenceIterator,
    SentenceTransformer,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import sample_negatives


class ParagraphVectors(SequenceVectors):
    """Doc embeddings. sequence_learning_algorithm: 'dbow' (default,
    reference DBOW.java) or 'dm' (reference DM.java)."""

    def __init__(self, sequence_learning_algorithm: str = "dbow", **kw):
        algo = sequence_learning_algorithm.lower()
        kw.setdefault("elements_learning_algorithm",
                      "cbow" if algo == "dm" else "skipgram")
        self.train_words = kw.pop("train_words", True)
        super().__init__(**kw)
        self.sequence_algorithm = algo
        self.labels: List[str] = []
        self._label_index: Dict[str, int] = {}
        self._doc_labels: List[List[str]] = []
        self._max_labels_per_doc = 1
        self._iterator: Optional[LabelAwareIterator] = None
        self._factory: Optional[TokenizerFactory] = None

    # Builder is attached at module bottom (shares Word2Vec.Builder surface)

    # ----------------------------------------------------------- corpus
    def _load_corpus(self, docs=None, labels=None):
        """Returns (token_sequences, per-sequence label lists)."""
        if docs is not None:
            it = LabelAwareListSentenceIterator(docs, labels)
        else:
            it = self._iterator
        if it is None:
            raise ValueError("No corpus: pass docs or set an iterator")
        seqs, doc_labels = [], []
        factory = self._factory
        for d in it:
            toks = (factory.create(d.content).get_tokens() if factory
                    else d.content.split())
            if toks:
                seqs.append(toks)
                doc_labels.append(list(d.labels))
        return seqs, doc_labels

    def _extra_rows(self) -> int:
        return len(self.labels)

    def _max_extra_context(self) -> int:
        # PV-DM appends every doc label as a context column
        return (self._max_labels_per_doc
                if self.sequence_algorithm == "dm" else 0)

    # ----------------------------------------------------------- training
    def fit(self, docs=None, labels=None):
        if self.use_device_pipeline:
            raise ValueError(
                "device pipeline does not support extra label rows "
                "(ParagraphVectors) — use the host path")
        seqs, doc_labels = self._load_corpus(docs, labels)
        self._doc_labels = doc_labels
        # register labels before vocab init so syn0 gets the extra rows
        self.labels = sorted({l for ls in doc_labels for l in ls})
        self._max_labels_per_doc = max(
            (len(ls) for ls in doc_labels), default=1)
        self.build_vocab(seqs)
        V = self.vocab.num_words()
        self._label_index = {l: V + i for i, l in enumerate(self.labels)}
        label_rows = [[self._label_index[l] for l in ls] for ls in doc_labels]

        total = self.vocab.total_word_occurrences * self.epochs
        done = 0.0
        for _ in range(self.epochs):
            done = self._train_corpus(
                seqs, total, label_for_sequence=lambda si: label_rows[si],
                words_done=done)
        self._finalize_losses()
        return self

    # ----------------------------------------------------------- queries
    def get_label_vector(self, label: str) -> Optional[np.ndarray]:
        i = self._label_index.get(label)
        return None if i is None else self.lookup_table.vector(i)

    def similarity_to_label(self, words: Sequence[str], label: str) -> float:
        lv = self.get_label_vector(label)
        if lv is None:
            return float("nan")
        vecs = [self.get_word_vector(w) for w in words]
        vecs = [v for v in vecs if v is not None]
        if not vecs:
            return float("nan")
        m = np.mean(vecs, axis=0)
        denom = np.linalg.norm(m) * np.linalg.norm(lv)
        return float(m @ lv / max(denom, 1e-12))

    def nearest_labels(self, text: str, top_n: int = 3) -> List[str]:
        vec = self.infer_vector(text)
        sims = []
        for l in self.labels:
            lv = self.get_label_vector(l)
            denom = np.linalg.norm(vec) * np.linalg.norm(lv)
            sims.append((float(vec @ lv / max(denom, 1e-12)), l))
        sims.sort(reverse=True)
        return [l for _, l in sims[:top_n]]

    def infer_vector(self, text: str, steps: int = 20,
                     lr: Optional[float] = None) -> np.ndarray:
        """Embed an unseen document (reference ParagraphVectors.inferVector):
        gradient steps on ONE new vector, output weights frozen."""
        toks = (self._factory.create(text).get_tokens() if self._factory
                else text.split())
        idx = np.array([i for i in (self.vocab.index_of(t) for t in toks)
                        if i >= 0], np.int32)
        if idx.size == 0:
            return np.zeros(self.layer_size, np.float32)
        lr = lr or self.learning_rate
        rng = np.random.default_rng(self.seed)
        vec = jnp.asarray(
            (rng.random(self.layer_size) - 0.5) / self.layer_size,
            self.lookup_table.dtype)
        if self.use_hs:
            codes, points, mask = (self._codes[idx], self._points[idx],
                                   self._mask[idx])
            for _ in range(steps):
                vec, _ = infer_hs_step(vec, self.lookup_table.syn1,
                                       codes, points, mask, lr)
        else:
            for _ in range(steps):
                negs = sample_negatives(self._cum_table,
                                        (idx.size, max(self.negative, 1)),
                                        rng)
                vec, _ = infer_sgns_step(vec, self.lookup_table.syn1neg,
                                         idx, negs, lr)
        return np.asarray(vec)


# Builder with the same chainable surface as Word2Vec.Builder ---------------
from deeplearning4j_tpu.nlp.word2vec import Word2Vec as _W2V  # noqa: E402


class _PVBuilder(_W2V.Builder):
    def __init__(self):
        super().__init__()
        self._seq_algo = "dbow"
        self._label_iterator = None

    def sequence_learning_algorithm(self, name: str):
        self._seq_algo = "dm" if "dm" in name.lower() else "dbow"
        return self

    def label_aware_iterator(self, it: LabelAwareIterator):
        self._label_iterator = it
        return self

    def build(self) -> ParagraphVectors:
        pv = ParagraphVectors(sequence_learning_algorithm=self._seq_algo,
                              **self._kw)
        pv._iterator = self._label_iterator
        pv._factory = self._factory
        return pv


ParagraphVectors.Builder = _PVBuilder
ParagraphVectors.builder = staticmethod(lambda: _PVBuilder())
