"""Parallel and distributed vocabulary construction.

The reference builds vocabulary ACROSS the cluster: Spark-parallel
tokenization with accumulator-based word counts
(spark/dl4j-spark-nlp TextPipeline.java:48-191 buildVocabCache /
WordFreqAccumulator) and a multi-threaded parallel VocabConstructor
(models/word2vec/wordstore/VocabConstructor.java:163). The single-host
`nlp/vocab.VocabConstructor` loop is the throughput ceiling of the whole
word2vec pipeline once the training epoch runs on-device (SURVEY.md's
hard-parts note: words/sec at text8+ scale is host-tokenization-bound).

Two TPU-era equivalents:

- `parallel_count` / `VocabConstructor(n_workers=...)`: host
  multiprocessing over corpus chunks — workers tokenize (optionally) and
  count; Counters merge associatively, so the result is bit-identical to
  the serial pass (the accumulator is commutative like Spark's).
- `build_vocab_distributed`: every cluster worker counts ITS corpus
  shard, publishes the counts through the coordinator's config registry,
  barriers, and merges all shards in sorted-worker order — each worker
  ends with the IDENTICAL VocabCache (same counts, same index order,
  same Huffman codes), the invariant the downstream device pipeline
  needs for device-count-invariant training.
"""

from __future__ import annotations

import multiprocessing
from collections import Counter
from typing import Iterable, List, Optional

from deeplearning4j_tpu.nlp.vocab import Huffman, VocabCache, VocabWord


def _count_chunk(args):
    chunk, tokenizer_factory = args
    counts: Counter = Counter()
    n = 0
    for item in chunk:
        tokens = (tokenizer_factory.create(item).get_tokens()
                  if tokenizer_factory is not None else item)
        counts.update(tokens)
        n += 1
    return counts, n


def parallel_count(sequences: Iterable, tokenizer_factory=None,
                   n_workers: Optional[int] = None, chunk_size: int = 2000):
    """(Counter, n_sequences) over `sequences` using a process pool.

    sequences: token lists, or raw strings when `tokenizer_factory` is
    given (tokenization happens IN the workers — it is the expensive
    part). Falls back to inline counting for n_workers <= 1.
    """
    n_workers = n_workers or multiprocessing.cpu_count()
    if n_workers <= 1:
        # stream — never buffer the corpus (the serial constructor's
        # memory profile)
        return _count_chunk((sequences, tokenizer_factory))
    chunks: List[list] = []
    buf: list = []
    for s in sequences:
        buf.append(s)
        if len(buf) >= chunk_size:
            chunks.append(buf)
            buf = []
    if buf:
        chunks.append(buf)
    if len(chunks) <= 1:
        only = chunks[0] if chunks else []
        return _count_chunk((only, tokenizer_factory))
    total: Counter = Counter()
    n_seq = 0
    with multiprocessing.Pool(min(n_workers, len(chunks))) as pool:
        for counts, n in pool.imap_unordered(
                _count_chunk,
                ((c, tokenizer_factory) for c in chunks)):
            total.update(counts)
            n_seq += n
    return total, n_seq


def cache_from_counts(counts: Counter, n_sequences: int,
                      min_word_frequency: int = 1,
                      limit: Optional[int] = None,
                      build_huffman: bool = True) -> VocabCache:
    """Finish a VocabCache from merged counts (shared tail of the serial,
    parallel, and distributed constructors)."""
    cache = VocabCache()
    for word, c in counts.items():
        cache.add_token(VocabWord(word, float(c)))
    cache.finish(min_word_frequency, limit)
    if build_huffman:
        Huffman(cache.vocab_words()).build()
    cache.n_sequences = n_sequences
    return cache


def build_vocab_distributed(client, local_sequences: Iterable[List[str]],
                            *, min_word_frequency: int = 1,
                            limit: Optional[int] = None,
                            build_huffman: bool = True,
                            n_workers: int = 1,
                            key: str = "vocab") -> VocabCache:
    """Cluster-wide vocabulary from per-worker corpus shards.

    client: a connected parallel.cluster.ClusterClient. Every worker
    calls this with its OWN shard; all workers return the same cache.
    """
    counts, n_seq = parallel_count(local_sequences, n_workers=n_workers)
    client.set_config(f"{key}/counts/{client.worker_id}",
                      {"counts": dict(counts), "n_sequences": n_seq})
    client.barrier(f"{key}/counted")
    merged: Counter = Counter()
    total_seq = 0
    for wid in sorted(client.workers()):
        shard = client.get_config(f"{key}/counts/{wid}")
        if shard is None:
            continue  # worker died between counting and the barrier
        merged.update(shard["counts"])
        total_seq += int(shard["n_sequences"])
    return cache_from_counts(merged, total_seq, min_word_frequency, limit,
                             build_huffman)
