"""SequenceVectors — the generic embedding training engine.

Reference (SURVEY.md §2.3 "SequenceVectors engine" row):
models/sequencevectors/SequenceVectors.java:47 — fit():125 builds vocab,
spawns an AsyncSequencer producer + N HogWild VectorCalculationsThread
consumers racing on shared syn0/syn1 (:773,:867), per-sequence dispatch to
pluggable learning algorithms (SkipGram/CBOW/DBOW/DM).

TPU-native redesign (SURVEY.md §3.4): no racing threads — the host walks
sequences and fills fixed-size pair buffers (center, context, negatives /
huffman paths); each full buffer is ONE jitted device step
(nlp/lookup.py). Alpha decays linearly over total expected words exactly
like word2vec/the reference's alpha scheduling. Determinism by construction:
a single seeded numpy Generator replaces the reference's racing
AtomicLong nextRandom.

Word2Vec (strings), ParagraphVectors (labels as extra elements) and
DeepWalk (graph-walk vertex ids) all drive this engine.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.lookup import (
    InMemoryLookupTable,
    cbow_ns_step,
    sg_hs_step,
    sgns_step,
)
from deeplearning4j_tpu.nlp.vocab import (
    Huffman,
    VocabCache,
    VocabConstructor,
    keep_probabilities,
    sample_negatives,
    subsample_mask,
    unigram_table,
)


_LOSS_FETCH_CHUNK = 512


def _fetch_loss_scalars(history):
    """Resolve a list of float|device-scalar losses to floats with few
    host round-trips: stack device scalars in fixed-size chunks (the
    chunked concatenate trace is cached across chunks) and fetch each
    chunk as one transfer. Already-float entries pass through, so
    repeated fits don't re-fetch."""
    dev = [l for l in history if not isinstance(l, float)]
    vals = []
    for i in range(0, len(dev), _LOSS_FETCH_CHUNK):
        vals.extend(np.asarray(jnp.stack(dev[i:i + _LOSS_FETCH_CHUNK])).tolist())
    it = iter(vals)
    return [l if isinstance(l, float) else float(next(it)) for l in history]


class SequenceVectors:
    """Batched-TPU embedding trainer over token sequences.

    Parameters mirror the reference Builder: layer_size (vectorLength),
    window_size, min_word_frequency, iterations→epochs, learning_rate
    (alpha 0.025 default), min_learning_rate, negative samples, use_hs
    (hierarchical softmax), sampling (frequent-word subsampling), batch_size
    (device step size), seed.
    """

    def __init__(self, layer_size: int = 100, window_size: int = 5,
                 min_word_frequency: int = 1, epochs: int = 1,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4, negative: int = 5,
                 use_hs: bool = False, sampling: float = 0.0,
                 batch_size: int = 2048, seed: int = 123,
                 elements_learning_algorithm: str = "skipgram",
                 vocab_limit: Optional[int] = None,
                 use_device_pipeline: bool = False, device_mesh=None,
                 pipeline_chunk: int = 512, pipeline_group=None,
                 pipeline_share_negatives: bool = True,
                 pipeline_neg_oversample: float = 2.0,
                 n_workers: int = 1, use_engine: bool = False,
                 engine_ep: int = 1, engine_dp: int = 1):
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.use_hs = use_hs or negative == 0
        self.sampling = sampling
        self.batch_size = batch_size
        self.seed = seed
        self.algorithm = elements_learning_algorithm
        self.vocab_limit = vocab_limit
        self.use_device_pipeline = use_device_pipeline
        self.device_mesh = device_mesh
        self.pipeline_chunk = pipeline_chunk
        # None = auto: 2 (1024-token updates, the r5 quality default), or
        # the smallest mesh-data-axis multiple >= 2 when a device_mesh is
        # set. PIN an explicit group for strict device-count invariance —
        # auto adapts the update granularity to the mesh, a pinned group
        # gives bit-identical results on any device count (DP-5).
        self.pipeline_group = pipeline_group
        self.pipeline_share_negatives = pipeline_share_negatives
        # shared-negative variance reduction: draw oversample*K negatives
        # per center, each weighted K/M — expectation-identical to
        # per-pair SGNS, most of the unshared quality at shared speed (r5)
        self.pipeline_neg_oversample = pipeline_neg_oversample
        self.n_workers = n_workers  # host-parallel vocab counting
        # route skip-gram flushes through the sharded embedding engine
        # (embedding/engine.py): ep/dp axes, sparse scatter-add
        # gradients, fused scoring kernel. ep=1 is bit-identical to the
        # legacy dense path (the parity contract tests/test_embedding.py
        # pins); ep>1 row-shards the tables across the expert axis.
        self.use_engine = use_engine
        self.engine_ep = engine_ep
        self.engine_dp = engine_dp
        self._engine = None
        self._epoch_fn = None

        self.vocab: Optional[VocabCache] = None
        self.lookup_table: Optional[InMemoryLookupTable] = None
        self._rng = np.random.default_rng(seed)
        self._cum_table = None
        self._keep_prob = None
        self._codes = self._points = self._mask = None
        self.loss_history: List[float] = []

    # ------------------------------------------------------------ vocab
    def build_vocab(self, sequences: Iterable[List[str]]):
        constructor = VocabConstructor(self.min_word_frequency,
                                       self.vocab_limit,
                                       build_huffman=self.use_hs,
                                       n_workers=self.n_workers)
        constructor.add_source(sequences)
        self.vocab = constructor.build_joint_vocabulary()
        self._init_from_vocab()
        return self

    def _init_from_vocab(self):
        V = self.vocab.num_words()
        if V == 0:
            raise ValueError("Empty vocabulary — corpus too small or "
                             "min_word_frequency too high")
        if self._engine_eligible():
            from deeplearning4j_tpu.embedding.engine import (
                EngineLookupView,
                ShardedEmbeddingEngine,
            )

            self._engine = ShardedEmbeddingEngine(
                V, self.layer_size, ep=self.engine_ep, dp=self.engine_dp,
                negative=self.negative, use_hs=self.use_hs,
                seed=self.seed)
            self.lookup_table = EngineLookupView(self._engine)
        else:
            self._engine = None
            self.lookup_table = InMemoryLookupTable(
                V + self._extra_rows(), self.layer_size, seed=self.seed,
                use_hs=self.use_hs, negative=self.negative)
        if self.negative > 0:
            self._cum_table = unigram_table(self.vocab)
        if self.use_hs:
            self._codes, self._points, self._mask = Huffman(
                self.vocab.vocab_words()).build().padded_arrays()
        if self.sampling > 0:
            self._keep_prob = keep_probabilities(self.vocab, self.sampling)

    def _extra_rows(self) -> int:
        """Extra syn0 rows beyond the word vocab (ParagraphVectors labels)."""
        return 0

    def _engine_eligible(self) -> bool:
        """The engine serves plain skip-gram over the word vocab; CBOW,
        label rows (ParagraphVectors), and the device pipeline keep the
        legacy dense tables."""
        return (self.use_engine and self.algorithm == "skipgram"
                and self._extra_rows() == 0
                and not self.use_device_pipeline)

    # ------------------------------------------------------------ training
    def _sequence_indices(self, tokens: List[str]) -> np.ndarray:
        idx = [self.vocab.index_of(t) for t in tokens]
        arr = np.array([i for i in idx if i >= 0], dtype=np.int32)
        if self.sampling > 0 and arr.size:
            arr = arr[subsample_mask(arr, self._keep_prob, self._rng)]
        return arr

    def _pairs_for_sequence(self, idx: np.ndarray,
                            extra_centers: Sequence[int] = ()):
        """Skip-gram pair generation with the word2vec random-shrunk window
        (reference SkipGram windows: b = random(window)). Returns
        (centers, contexts) arrays. extra_centers (e.g. a doc label) pair
        with EVERY word (PV-DBOW)."""
        n = idx.size
        if n < 2:
            cen = np.repeat(np.asarray(extra_centers, np.int32), n)
            return cen, np.tile(idx, len(extra_centers))
        centers, contexts = [], []
        shrink = self._rng.integers(0, self.window_size, size=n)
        for i in range(n):
            w = self.window_size - shrink[i]
            lo, hi = max(0, i - w), min(n, i + w + 1)
            for j in range(lo, hi):
                if j != i:
                    centers.append(idx[i])
                    contexts.append(idx[j])
        for c in extra_centers:
            centers += [c] * n
            contexts += idx.tolist()
        return (np.asarray(centers, np.int32), np.asarray(contexts, np.int32))

    def _windows_for_sequence(self, idx: np.ndarray,
                              extra_context: Sequence[int] = ()):
        """CBOW windows: (context [n,W], mask [n,W], target [n]).
        extra_context columns (PV-DM doc label) are appended to every
        window."""
        n = idx.size
        W = 2 * self.window_size + len(extra_context)
        ctx = np.zeros((n, W), np.int32)
        mask = np.zeros((n, W), bool)
        shrink = self._rng.integers(0, self.window_size, size=max(n, 1))
        for i in range(n):
            w = self.window_size - shrink[i]
            neigh = [idx[j] for j in range(max(0, i - w), min(n, i + w + 1))
                     if j != i]
            k = len(neigh)
            ctx[i, :k] = neigh
            mask[i, :k] = True
            if extra_context:
                ctx[i, -len(extra_context):] = extra_context
                mask[i, -len(extra_context):] = True
        return ctx, mask, idx.copy()

    def _alpha(self, words_done: float, total_words: float) -> float:
        frac = min(1.0, words_done / max(total_words, 1.0))
        return max(self.min_learning_rate, self.learning_rate * (1.0 - frac))

    def _flush_sg(self, centers, contexts, lr):
        if self._engine is not None:
            if self.use_hs:
                loss = self._engine.hs_step(
                    centers, self._codes[contexts],
                    self._points[contexts], self._mask[contexts], lr)
            else:
                negs = sample_negatives(
                    self._cum_table, (len(centers), self.negative),
                    self._rng)
                loss = self._engine.sgns_step(centers, contexts, negs, lr)
            self.loss_history.append(loss)
            return
        t = self.lookup_table
        if self.use_hs:
            t.syn0, t.syn1, loss = sg_hs_step(
                t.syn0, t.syn1, centers, self._codes[contexts],
                self._points[contexts], self._mask[contexts], lr)
        else:
            negs = sample_negatives(self._cum_table,
                                    (len(centers), self.negative), self._rng)
            t.syn0, t.syn1neg, loss = sgns_step(
                t.syn0, t.syn1neg, centers, contexts, negs, lr)
        # keep the device scalar — a float() here would force a host
        # round-trip per batch and serialize the async dispatch stream
        self.loss_history.append(loss)

    def _flush_cbow(self, ctx, mask, targets, lr):
        t = self.lookup_table
        negs = sample_negatives(self._cum_table,
                                (len(targets), self.negative), self._rng)
        t.syn0, t.syn1neg, loss = cbow_ns_step(
            t.syn0, t.syn1neg, ctx, mask, targets, negs, lr)
        self.loss_history.append(loss)

    def _train_corpus(self, sequences, total_words: float,
                      label_for_sequence=None, words_done: float = 0.0):
        """One pass; label_for_sequence(seq_index) -> list of extra element
        indices (ParagraphVectors hooks in here). words_done carries the
        cross-epoch word count so alpha decays over the WHOLE run."""
        B = self.batch_size
        if self.algorithm == "skipgram":
            buf_c = np.empty(0, np.int32)
            buf_x = np.empty(0, np.int32)
            for si, tokens in enumerate(sequences):
                idx = self._sequence_indices(tokens)
                if idx.size == 0:
                    continue
                extra = label_for_sequence(si) if label_for_sequence else ()
                c, x = self._pairs_for_sequence(idx, extra)
                buf_c = np.concatenate([buf_c, c])
                buf_x = np.concatenate([buf_x, x])
                words_done += idx.size
                while buf_c.size >= B:
                    lr = self._alpha(words_done, total_words)
                    self._flush_sg(buf_c[:B], buf_x[:B], lr)
                    buf_c, buf_x = buf_c[B:], buf_x[B:]
            if buf_c.size:  # tail: pad by resampling existing pairs
                pad = self._rng.integers(0, buf_c.size, B - buf_c.size)
                self._flush_sg(np.concatenate([buf_c, buf_c[pad]]),
                               np.concatenate([buf_x, buf_x[pad]]),
                               self._alpha(words_done, total_words))
        elif self.algorithm == "cbow":
            W = 2 * self.window_size + self._max_extra_context()
            buf_ctx = np.empty((0, W), np.int32)
            buf_m = np.empty((0, W), bool)
            buf_t = np.empty(0, np.int32)
            for si, tokens in enumerate(sequences):
                idx = self._sequence_indices(tokens)
                if idx.size == 0:
                    continue
                extra = label_for_sequence(si) if label_for_sequence else ()
                ctx, m, tg = self._windows_for_sequence(idx, extra)
                if ctx.shape[1] < W:  # pad width for fixed device shapes
                    pad = W - ctx.shape[1]
                    ctx = np.pad(ctx, ((0, 0), (0, pad)))
                    m = np.pad(m, ((0, 0), (0, pad)))
                buf_ctx = np.concatenate([buf_ctx, ctx])
                buf_m = np.concatenate([buf_m, m])
                buf_t = np.concatenate([buf_t, tg])
                words_done += idx.size
                while buf_t.size >= B:
                    lr = self._alpha(words_done, total_words)
                    self._flush_cbow(buf_ctx[:B], buf_m[:B], buf_t[:B], lr)
                    buf_ctx, buf_m, buf_t = buf_ctx[B:], buf_m[B:], buf_t[B:]
            if buf_t.size:
                pad = self._rng.integers(0, buf_t.size, B - buf_t.size)
                self._flush_cbow(np.concatenate([buf_ctx, buf_ctx[pad]]),
                                 np.concatenate([buf_m, buf_m[pad]]),
                                 np.concatenate([buf_t, buf_t[pad]]),
                                 self._alpha(words_done, total_words))
        else:
            raise ValueError(f"Unknown learning algorithm {self.algorithm!r}")
        return words_done

    def _max_extra_context(self) -> int:
        return 0

    def fit(self, sequences):
        """Build vocab (if needed) and train (reference fit():125).
        `sequences`: reiterable of token lists (e.g. SentenceTransformer)."""
        seq_list = sequences if isinstance(sequences, list) else None
        if seq_list is None:
            # materialize BEFORE any per-element conversion: list(str) would
            # silently explode raw sentences into characters
            seq_list = list(sequences)
        if seq_list and not isinstance(seq_list[0], (str, list)):
            seq_list = [list(s) for s in seq_list]
        if self.vocab is None:
            vocab_src = ([line.split() for line in seq_list]
                         if seq_list and isinstance(seq_list[0], str)
                         else seq_list)
            self.build_vocab(vocab_src)
        corpus = seq_list
        if self.use_device_pipeline:
            return self._fit_device_pipeline(corpus)
        if isinstance(corpus, list) and corpus and isinstance(corpus[0], str):
            # the host loop consumes token lists; raw sentences would be
            # iterated character-by-character (training nothing)
            corpus = [line.split() for line in corpus]
        total = self.vocab.total_word_occurrences * self.epochs
        done = 0.0
        for _ in range(self.epochs):
            done = self._train_corpus(corpus, total, words_done=done)
        self._finalize_losses()
        return self

    def _fit_device_pipeline(self, corpus):
        """Whole-epoch on-device training (nlp/device_pipeline.py): the
        corpus is uploaded once per epoch and pair generation, negative
        sampling, and updates all run inside one jitted scan. Supports
        skip-gram and CBOW with negative sampling; other combinations
        (hierarchical softmax) raise — requesting the pipeline is
        explicit, so a silent host-loop fallback would hide a perf cliff."""
        from deeplearning4j_tpu.nlp.device_pipeline import (
            build_alias_table,
            make_cbow_epoch,
            make_sgns_epoch,
            pack_corpus,
            pack_corpus_flat,
        )

        if (self.algorithm not in ("skipgram", "cbow") or self.use_hs
                or self.negative <= 0):
            raise ValueError(
                "device pipeline supports skip-gram/CBOW with negative "
                "sampling (use_hs=False, negative>0); use the host path "
                "otherwise")
        if self._extra_rows():
            raise ValueError("device pipeline does not support extra label "
                             "rows (ParagraphVectors) — use the host path")
        group = self.pipeline_group
        if group is None:
            group = 2
            if self.device_mesh is not None:
                n_dev = self.device_mesh.shape["data"]
                group = -(-group // n_dev) * n_dev
        elif (self.device_mesh is not None
              and group % self.device_mesh.shape["data"]):
            n_dev = self.device_mesh.shape["data"]
            raise ValueError(
                f"pipeline_group={group} does not divide over the "
                f"{n_dev}-way mesh data axis — set pipeline_group to a "
                f"multiple of {n_dev} (or leave it None for auto)")
        cfg = (self.algorithm, self.window_size, self.negative,
               self.pipeline_chunk, group,
               self.pipeline_share_negatives,
               self.pipeline_neg_oversample, id(self.device_mesh))
        if self._epoch_fn is None or getattr(self, "_epoch_cfg", None) != cfg:
            if self.algorithm == "cbow":
                self._epoch_fn = make_cbow_epoch(
                    window=self.window_size, negative=self.negative,
                    chunk=self.pipeline_chunk, group=group,
                    mesh=self.device_mesh)
            else:
                self._epoch_fn = make_sgns_epoch(
                    window=self.window_size, negative=self.negative,
                    chunk=self.pipeline_chunk, group=group,
                    mesh=self.device_mesh,
                    share_negatives=self.pipeline_share_negatives,
                    neg_oversample=self.pipeline_neg_oversample)
            self._epoch_cfg = cfg
        t = self.lookup_table
        probs = np.diff(self._cum_table, prepend=0.0)
        aJ, aq = build_alias_table(probs)
        aJ, aq = jnp.asarray(aJ), jnp.asarray(aq)
        total = self.vocab.total_word_occurrences * self.epochs
        per_update = self.pipeline_chunk * group
        done = 0.0
        packed = None
        losses = []
        for _ in range(self.epochs):
            if packed is None or self.sampling > 0:
                # subsampling redraws per epoch (host rng, like the
                # reference); without it the packed corpus is uploaded once
                # and reused across epochs
                flat = self._corpus_flat_indices(corpus)
                if flat is not None:
                    # skip the per-sentence split/re-concatenate round trip
                    tokens_np, sent_ids_np = pack_corpus_flat(
                        *flat, per_update)
                else:
                    # flat path declined (subsampling / tiny corpus):
                    # the per-sentence tokenizing path
                    idx_seqs = self._corpus_indices_seq(corpus)
                    tokens_np, sent_ids_np = pack_corpus(idx_seqs,
                                                         per_update)
                packed = (jnp.asarray(tokens_np), jnp.asarray(sent_ids_np))
            tokens, sent_ids = packed
            lr0 = self._alpha(done, total)
            lr1 = self._alpha(done + len(tokens), total)
            key = jax.random.PRNGKey(self.seed + int(done) % (2**31))
            t.syn0, t.syn1neg, ls, pairs = self._epoch_fn(
                t.syn0, t.syn1neg, tokens, sent_ids, aJ, aq, key, lr0, lr1)
            losses.append((ls, pairs))
            done += len(tokens)
        # one host fetch for the whole run
        for ls, pairs in losses:
            ls = np.asarray(ls)
            pairs = np.maximum(np.asarray(pairs), 1.0)
            self.loss_history.extend((ls / pairs).tolist())
        return self

    def _corpus_flat_indices(self, corpus):
        """Corpus → flat (ids, sentence_ids) with OOV dropped, or None
        when only the per-sentence path applies (subsampling needs the
        host rng). Raw-string sentences go through the native ONE-PASS
        corpus encoder (native.encode_corpus: whitespace split + vocab
        hash lookups for the whole corpus in a single call — the hash
        table is built once); larger pre-tokenized corpora use one flat
        vectorized vocab lookup."""
        if self.sampling != 0:
            return None
        if corpus and isinstance(corpus[0], str):
            from deeplearning4j_tpu import native

            enc = native.encode_corpus(corpus, self.vocab.words())
            if enc is not None:
                ids, sent = enc
                keep = ids >= 0
                return ids[keep], sent[keep]
            corpus = [line.split() for line in corpus]
        if len(corpus) > 64:
            # flat dict lookup over the whole corpus instead of a Python
            # loop per sentence (~4x faster at 1M words; identical output)
            get = {w: i for i, w in enumerate(self.vocab.words())}.get
            flat_ids = np.fromiter(
                (get(w, -1) for toks in corpus for w in toks),
                np.int32)
            lengths = np.fromiter((len(t) for t in corpus), np.int64,
                                  len(corpus))
            sent = np.repeat(np.arange(len(corpus)), lengths)
            keep = flat_ids >= 0
            return flat_ids[keep], sent[keep].astype(np.int32)
        return None

    def _corpus_indices_seq(self, corpus):
        """Per-sentence fallback: tokenize raw-string sentences, then the
        (rng-dependent) per-sequence path."""
        if corpus and isinstance(corpus[0], str):
            corpus = [line.split() for line in corpus]
        return [self._sequence_indices(toks) for toks in corpus]


    def _finalize_losses(self):
        """One deferred host sync for the whole run (see _flush_sg): stack
        on device and fetch in chunked transfers — per-scalar float() would
        pay one full host round-trip each, while a single giant stack
        traces a concatenate whose operand count scales superlinearly."""
        if not self.loss_history:
            return
        self.loss_history = _fetch_loss_scalars(self.loss_history)

    # ------------------------------------------------------- vector queries
    # (reference embeddings/wordvectors/WordVectorsImpl.java API)
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self.lookup_table.vector(i)

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and word in self.vocab

    def similarity(self, a: str, b: str) -> float:
        ia, ib = self.vocab.index_of(a), self.vocab.index_of(b)
        if ia < 0 or ib < 0:
            return float("nan")
        return self.lookup_table.similarity(ia, ib)

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            i = self.vocab.index_of(word_or_vec)
            if i < 0:
                return []
            vec, exclude = self.lookup_table.vector(i), {i}
        else:
            vec, exclude = np.asarray(word_or_vec), set()
        V = self.vocab.num_words()
        # non-word rows (e.g. ParagraphVectors labels) may dominate the
        # neighborhood — fetch enough candidates to still return top_n words
        extra = self.lookup_table.vocab_size - V
        hits = self.lookup_table.nearest(vec, top_n + len(exclude) + extra,
                                         exclude=exclude)
        return [self.vocab.word_at_index(i) for i, _ in hits if i < V][:top_n]

    def words_nearest_sum(self, positive: List[str], negative: List[str],
                          top_n: int = 10) -> List[str]:
        """Analogy queries (reference WordVectorsImpl.wordsNearest(pos,neg))."""
        vec = np.zeros(self.layer_size, np.float32)
        exclude = set()
        for w in positive:
            i = self.vocab.index_of(w)
            if i >= 0:
                vec += self.lookup_table.vector(i)
                exclude.add(i)
        for w in negative:
            i = self.vocab.index_of(w)
            if i >= 0:
                vec -= self.lookup_table.vector(i)
                exclude.add(i)
        V = self.vocab.num_words()
        extra = self.lookup_table.vocab_size - V
        hits = self.lookup_table.nearest(vec, top_n + len(exclude) + extra,
                                         exclude=exclude)
        return [self.vocab.word_at_index(i) for i, _ in hits if i < V][:top_n]
