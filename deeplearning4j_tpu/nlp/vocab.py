"""Vocabulary construction + Huffman coding for hierarchical softmax.

Reference (SURVEY.md §2.3 "Lookup table / vocab" row):
- models/word2vec/VocabWord.java (word + count + huffman code/points)
- models/word2vec/wordstore/VocabConstructor.java:34
  (buildJointVocabulary:163 — corpus count, min-count filter, Huffman)
- models/word2vec/wordstore/inmemory/AbstractCache.java (VocabCache impl)
- models/word2vec/Huffman.java:34-66 (binary tree over counts → per-word
  code/point arrays, max code length 40)

Host-side pure Python; emits padded numpy arrays (codes/points/mask) so the
device-side hierarchical-softmax step works on fixed shapes.
"""

from __future__ import annotations

import heapq
import itertools
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

MAX_CODE_LENGTH = 40  # reference Huffman.java MAX_CODE_LENGTH


class VocabWord:
    """A sequence element: word, count, huffman code/points
    (reference VocabWord.java / SequenceElement)."""

    __slots__ = ("word", "count", "index", "code", "points", "labels")

    def __init__(self, word: str, count: float = 1.0):
        self.word = word
        self.count = count
        self.index = -1
        self.code: List[int] = []
        self.points: List[int] = []
        self.labels: List[str] = []

    def increment(self, by: float = 1.0):
        self.count += by

    def __repr__(self):
        return f"VocabWord({self.word!r}, count={self.count})"


class VocabCache:
    """Word ↔ index ↔ count store (reference wordstore/VocabCache +
    inmemory/AbstractCache)."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._index: List[VocabWord] = []
        self.total_word_occurrences = 0.0

    # construction ---------------------------------------------------------
    def add_token(self, vw: VocabWord):
        existing = self._words.get(vw.word)
        if existing is not None:
            existing.increment(vw.count)
        else:
            self._words[vw.word] = vw

    def finish(self, min_word_frequency: int = 1,
               limit: Optional[int] = None):
        """Filter by min count, sort by descending count, assign indices
        (reference VocabConstructor.buildJointVocabulary:163)."""
        kept = [w for w in self._words.values()
                if w.count >= min_word_frequency]
        kept.sort(key=lambda w: (-w.count, w.word))
        if limit:
            kept = kept[:limit]
        self._words = {w.word: w for w in kept}
        self._index = kept
        for i, w in enumerate(kept):
            w.index = i
        self.total_word_occurrences = float(sum(w.count for w in kept))
        return self

    # queries --------------------------------------------------------------
    def contains_word(self, word: str) -> bool:
        return word in self._words

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.index if vw else -1

    def word_at_index(self, i: int) -> str:
        return self._index[i].word

    def element_at_index(self, i: int) -> VocabWord:
        return self._index[i]

    def word_frequency(self, word: str) -> float:
        vw = self._words.get(word)
        return vw.count if vw else 0.0

    def num_words(self) -> int:
        return len(self._index)

    def words(self) -> List[str]:
        return [w.word for w in self._index]

    def vocab_words(self) -> List[VocabWord]:
        return list(self._index)

    def __len__(self):
        return len(self._index)

    def __contains__(self, word):
        return word in self._words


class Huffman:
    """Huffman tree over word counts → per-word (code, points)
    (reference Huffman.java:34-66).

    code[d]  ∈ {0,1}: branch taken at depth d
    points[d]: inner-node index at depth d (relative, as syn1 row)
    """

    def __init__(self, words: Sequence[VocabWord]):
        self.words = list(words)

    def build(self):
        n = len(self.words)
        if n == 0:
            return self
        counter = itertools.count()
        # heap of (count, tiebreak, node_id); leaves are 0..n-1, inner n..2n-2
        heap = [(w.count, next(counter), i) for i, w in enumerate(self.words)]
        heapq.heapify(heap)
        parent = np.zeros(2 * n, dtype=np.int64)
        binary = np.zeros(2 * n, dtype=np.int8)
        next_inner = n
        while len(heap) > 1:
            c1, _, i1 = heapq.heappop(heap)
            c2, _, i2 = heapq.heappop(heap)
            parent[i1] = next_inner
            parent[i2] = next_inner
            binary[i2] = 1
            heapq.heappush(heap, (c1 + c2, next(counter), next_inner))
            next_inner += 1
        root = next_inner - 1 if n > 1 else n
        for i, w in enumerate(self.words):
            code, points = [], []
            node = i
            while n > 1 and node != root:
                code.append(int(binary[node]))
                node = int(parent[node])
                points.append(node - n)  # inner-node id → syn1 row
            # reference stores root→leaf order
            w.code = code[::-1][:MAX_CODE_LENGTH]
            w.points = points[::-1][:MAX_CODE_LENGTH]
        return self

    def padded_arrays(self, max_len: Optional[int] = None):
        """(codes [V,L] int8, points [V,L] int32, mask [V,L] bool) for the
        fixed-shape device hierarchical-softmax step."""
        L = max_len or max((len(w.code) for w in self.words), default=1) or 1
        V = len(self.words)
        codes = np.zeros((V, L), dtype=np.int8)
        points = np.zeros((V, L), dtype=np.int32)
        mask = np.zeros((V, L), dtype=bool)
        for i, w in enumerate(self.words):
            k = min(len(w.code), L)
            codes[i, :k] = w.code[:k]
            points[i, :k] = w.points[:k]
            mask[i, :k] = True
        return codes, points, mask


class VocabConstructor:
    """Builds a joint vocabulary from token-sequence sources (reference
    VocabConstructor.buildJointVocabulary:163 — count, filter, Huffman).
    n_workers > 1 counts corpus chunks in a process pool (the reference
    constructor is multi-threaded; Counter merge is associative, so the
    result is identical to the serial pass)."""

    def __init__(self, min_word_frequency: int = 1,
                 limit: Optional[int] = None, build_huffman: bool = True,
                 n_workers: int = 1):
        self.min_word_frequency = min_word_frequency
        self.limit = limit
        self.build_huffman = build_huffman
        self.n_workers = n_workers
        self._sources: List[Iterable[List[str]]] = []

    def add_source(self, token_sequences: Iterable[List[str]]):
        self._sources.append(token_sequences)
        return self

    def build_joint_vocabulary(self) -> VocabCache:
        from deeplearning4j_tpu.nlp.distributed_vocab import (
            cache_from_counts,
            parallel_count,
        )

        counts: Counter = Counter()
        n_sequences = 0
        for source in self._sources:
            if self.n_workers > 1:
                c, n = parallel_count(source, n_workers=self.n_workers)
                counts.update(c)
                n_sequences += n
            else:
                for tokens in source:
                    counts.update(tokens)
                    n_sequences += 1
        return cache_from_counts(counts, n_sequences,
                                 self.min_word_frequency, self.limit,
                                 self.build_huffman)


def unigram_table(cache: VocabCache, power: float = 0.75) -> np.ndarray:
    """Negative-sampling table: word index repeated ∝ count^0.75
    (reference InMemoryLookupTable.makeTable). Stored compactly as a
    cumulative-probability array sampled by searchsorted instead of the
    reference's 100M-entry int table."""
    counts = np.array([w.count for w in cache.vocab_words()], dtype=np.float64)
    probs = counts ** power
    probs /= probs.sum()
    return np.cumsum(probs)


def sample_negatives(cumprobs: np.ndarray, shape, rng: np.random.Generator):
    """Draw negative-sample word indices from the unigram^0.75 table."""
    u = rng.random(shape)
    return np.searchsorted(cumprobs, u).astype(np.int32)


def subsample_mask(indices: np.ndarray, keep_prob: np.ndarray,
                   rng: np.random.Generator) -> np.ndarray:
    """Frequent-word subsampling (reference SequenceVectors sampling>0:
    p_keep = (sqrt(f/t) + 1) * t/f)."""
    return rng.random(indices.shape) < keep_prob[indices]


def keep_probabilities(cache: VocabCache, sampling: float) -> np.ndarray:
    counts = np.array([w.count for w in cache.vocab_words()], dtype=np.float64)
    freq = counts / max(cache.total_word_occurrences, 1.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        p = (np.sqrt(freq / sampling) + 1.0) * sampling / np.maximum(freq, 1e-12)
    return np.clip(p, 0.0, 1.0)
