"""Inverted corpus index (reference: text/invertedindex/{InvertedIndex,
LuceneInvertedIndex}.java — term→document postings over tokenised docs,
mini-batch iteration and document sampling for embedding training).

The Lucene dependency is replaced by a plain in-memory postings dict; the
capability surface (addWordsToDoc, documents(word), numDocuments, docs,
miniBatches, sample, search) matches the reference interface.
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple


class InvertedIndex:
    """In-memory inverted index over tokenised documents."""

    def __init__(self, seed: int = 0):
        self._docs: List[List[str]] = []
        self._labels: List[Optional[List[str]]] = []
        self._postings: Dict[str, List[int]] = defaultdict(list)
        self._rng = random.Random(seed)

    # ---------------------------------------------------------- population
    def add_words_to_doc(self, doc_id: int, words: Sequence[str],
                         labels: Optional[Sequence[str]] = None) -> None:
        """Append words to document `doc_id`, creating it if needed
        (InvertedIndex.addWordsToDoc)."""
        while len(self._docs) <= doc_id:
            self._docs.append([])
            self._labels.append(None)
        seen_here = set(self._docs[doc_id])
        for w in words:
            self._docs[doc_id].append(w)
            if w not in seen_here:
                self._postings[w].append(doc_id)
                seen_here.add(w)
        if labels is not None:
            self._labels[doc_id] = list(labels)

    def add_doc(self, words: Sequence[str],
                labels: Optional[Sequence[str]] = None) -> int:
        doc_id = len(self._docs)
        self.add_words_to_doc(doc_id, words, labels)
        return doc_id

    # ------------------------------------------------------------- queries
    def document(self, index: int) -> List[str]:
        return list(self._docs[index])

    def document_with_labels(self, index: int) -> Tuple[List[str], Optional[List[str]]]:
        return list(self._docs[index]), self._labels[index]

    def documents(self, word: str) -> List[int]:
        """Doc ids containing `word` (InvertedIndex.documents)."""
        return list(self._postings.get(word, []))

    def num_documents(self) -> int:
        return len(self._docs)

    def all_docs(self) -> List[int]:
        return list(range(len(self._docs)))

    def docs(self) -> Iterator[List[str]]:
        return iter(list(d) for d in self._docs)

    def mini_batches(self, batch_size: int) -> Iterator[List[List[str]]]:
        """Documents in batches (InvertedIndex.batchIter/miniBatches)."""
        for s in range(0, len(self._docs), batch_size):
            yield [list(d) for d in self._docs[s:s + batch_size]]

    def sample(self) -> List[str]:
        """A uniformly random document (InvertedIndex.sample)."""
        if not self._docs:
            raise IndexError("empty index")
        return list(self._docs[self._rng.randrange(len(self._docs))])

    # ------------------------------------------------------ search/scoring
    def search(self, *words: str) -> List[int]:
        """Conjunctive (AND) search: ids of docs containing every word."""
        if not words:
            return []
        sets = [set(self._postings.get(w, ())) for w in words]
        hit = set.intersection(*sets) if sets else set()
        return sorted(hit)

    def tfidf_search(self, *words: str, top_n: int = 10) -> List[Tuple[int, float]]:
        """Disjunctive search ranked by summed tf-idf."""
        n = max(len(self._docs), 1)
        scores: Dict[int, float] = defaultdict(float)
        for w in words:
            posting = self._postings.get(w, [])
            if not posting:
                continue
            idf = math.log(n / len(posting))
            for d in posting:
                tf = self._docs[d].count(w) / max(len(self._docs[d]), 1)
                scores[d] += tf * idf
        return sorted(scores.items(), key=lambda kv: -kv[1])[:top_n]
