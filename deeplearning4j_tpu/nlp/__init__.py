"""NLP stack — reference deeplearning4j-nlp (SURVEY.md §2.3).

Host side (pure Python): tokenization, sentence/document iterators, vocab
construction, Huffman coding, co-occurrence counting.
Device side (JAX/XLA): batched skip-gram/CBOW/GloVe updates as dense
gather → matmul → scatter-add steps (the reference's per-pair HogWild
BLAS-1 loop does not map to TPU — SURVEY.md §3.4 TPU mapping).
"""

from deeplearning4j_tpu.nlp.vocab import Huffman, VocabCache, VocabConstructor, VocabWord
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove

__all__ = [
    "Huffman", "VocabCache", "VocabConstructor", "VocabWord",
    "Word2Vec", "ParagraphVectors", "Glove",
]
