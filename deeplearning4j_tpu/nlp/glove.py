"""GloVe — global vectors from co-occurrence statistics.

Reference: models/glove/Glove.java + AbstractCoOccurrences.java
(co-occurrence counting with 1/distance weighting, shuffled batches,
AdaGrad per-element updates — SURVEY.md §2.3).

TPU design: co-occurrence counting stays host-side (dict accumulation over
windows, as the reference spills binary CoOccurrence files); training is
batched weighted-least-squares on device — gather rows, compute
f(X)·(w·w̃ + b + b̃ − log X)², AdaGrad scatter updates — with each EPOCH a
single jitted dispatch (device-side shuffle + lax.scan over batches).
Passing `device_mesh` shards every batch's triples over the mesh 'data'
axis (the distributed path replacing dl4j-spark-nlp GlovePerformer's
broadcast-weights/per-partition scheme). Final vectors are w + w̃
(standard GloVe practice).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import VocabConstructor


class AbstractCoOccurrences:
    """Symmetric windowed co-occurrence counts with 1/d weighting
    (reference glove/AbstractCoOccurrences.java)."""

    def __init__(self, window_size: int = 15, symmetric: bool = True):
        self.window_size = window_size
        self.symmetric = symmetric
        self.counts: Dict[Tuple[int, int], float] = defaultdict(float)

    def accumulate(self, idx: np.ndarray):
        n = idx.size
        for i in range(n):
            for j in range(max(0, i - self.window_size), i):
                w = 1.0 / (i - j)
                a, b = int(idx[i]), int(idx[j])
                self.counts[(a, b)] += w
                if self.symmetric:
                    self.counts[(b, a)] += w

    def arrays(self):
        if not self.counts:
            return (np.zeros(0, np.int32), np.zeros(0, np.int32),
                    np.zeros(0, np.float32))
        ij = np.array(list(self.counts.keys()), np.int32)
        x = np.array(list(self.counts.values()), np.float32)
        return ij[:, 0].copy(), ij[:, 1].copy(), x


def _glove_update(carry, xs, lr):
    """AdaGrad step on a batch of (i, j, log X_ij, f(X_ij)) triples.
    Padded triples carry fx == 0 (and logx == 0), so they contribute
    neither loss nor updates."""
    W, Wc, b, bc, hW, hWc, hb, hbc = carry
    i, j, logx, fx = xs
    wi, wj = W[i], Wc[j]                                  # [B, D]
    diff = jnp.einsum("bd,bd->b", wi, wj) + b[i] + bc[j] - logx
    wdiff = fx * diff                                     # [B]
    loss = 0.5 * jnp.sum(wdiff * diff)

    gwi = wdiff[:, None] * wj
    gwj = wdiff[:, None] * wi
    gb = wdiff

    # AdaGrad: accumulate squared grads, scale updates
    hW = hW.at[i].add(gwi ** 2)
    hWc = hWc.at[j].add(gwj ** 2)
    hb = hb.at[i].add(gb ** 2)
    hbc = hbc.at[j].add(gb ** 2)
    W = W.at[i].add(-lr * gwi / jnp.sqrt(hW[i] + 1e-8))
    Wc = Wc.at[j].add(-lr * gwj / jnp.sqrt(hWc[j] + 1e-8))
    b = b.at[i].add(-lr * gb / jnp.sqrt(hb[i] + 1e-8))
    bc = bc.at[j].add(-lr * gb / jnp.sqrt(hbc[j] + 1e-8))
    return (W, Wc, b, bc, hW, hWc, hb, hbc), loss


def make_glove_epoch(batch: int, shuffle: bool, mesh=None):
    """One full epoch as a single jitted dispatch: device-side shuffle,
    reshape into [n_batches, batch], lax.scan of AdaGrad steps. With a
    mesh, each batch's triples shard over the 'data' axis — the gathers
    read replicated W and XLA turns the scatter-adds into psum'd updates
    (the distributed GloVe path; reference dl4j-spark-nlp GlovePerformer
    trains per-partition against broadcast weights the same way)."""

    @partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
    def epoch(W, Wc, b, bc, hW, hWc, hb, hbc, ii, jj, logx, fx, key, lr):
        if shuffle:
            perm = jax.random.permutation(key, ii.shape[0])
        else:
            perm = jnp.arange(ii.shape[0])

        def stage(a):
            a = a[perm].reshape(-1, batch)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                a = jax.lax.with_sharding_constraint(
                    a, NamedSharding(mesh, P(None, "data")))
            return a

        xs = (stage(ii), stage(jj), stage(logx), stage(fx))
        carry, losses = jax.lax.scan(
            partial(_glove_update, lr=lr),
            (W, Wc, b, bc, hW, hWc, hb, hbc), xs)
        return carry + (losses,)

    return epoch


class Glove(SequenceVectors):
    """GloVe trainer with the SequenceVectors query API (similarity,
    words_nearest). Builder mirrors reference Glove.Builder (xMax, alpha,
    shuffle, symmetric)."""

    def __init__(self, layer_size: int = 100, window_size: int = 15,
                 min_word_frequency: int = 1, epochs: int = 25,
                 learning_rate: float = 0.05, x_max: float = 100.0,
                 alpha: float = 0.75, batch_size: int = 4096,
                 seed: int = 123, symmetric: bool = True, shuffle: bool = True,
                 vocab_limit: Optional[int] = None, device_mesh=None):
        super().__init__(layer_size=layer_size, window_size=window_size,
                         min_word_frequency=min_word_frequency, epochs=epochs,
                         learning_rate=learning_rate, batch_size=batch_size,
                         seed=seed, negative=0, use_hs=False,
                         vocab_limit=vocab_limit, device_mesh=device_mesh)
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self.shuffle = shuffle
        self.use_hs = False  # glove has no output tree

    def _init_from_vocab(self):
        V = self.vocab.num_words()
        if V == 0:
            raise ValueError("Empty vocabulary")
        from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable

        self.lookup_table = InMemoryLookupTable(V, self.layer_size,
                                                seed=self.seed, negative=0)

    def fit(self, sequences):
        seq_list = [list(s) for s in sequences]
        if self.vocab is None:
            self.build_vocab(seq_list)
        V = self.vocab.num_words()
        D = self.layer_size

        cooc = AbstractCoOccurrences(self.window_size, self.symmetric)
        for tokens in seq_list:
            idx = self._sequence_indices(tokens)
            if idx.size:
                cooc.accumulate(idx)
        ii, jj, xx = cooc.arrays()
        if ii.size == 0:
            raise ValueError("No co-occurrences — corpus too small")
        logx = np.log(xx).astype(np.float32)
        fx = np.minimum(1.0, (xx / self.x_max) ** self.alpha).astype(np.float32)

        # pad to whole batches ONCE with weight-zero triples (fx == 0 kills
        # both the loss term and every update; logx == 0 keeps diff finite)
        B = self.batch_size
        pad = (-ii.size) % B
        if pad:
            ii = np.concatenate([ii, np.zeros(pad, np.int32)])
            jj = np.concatenate([jj, np.zeros(pad, np.int32)])
            logx = np.concatenate([logx, np.zeros(pad, np.float32)])
            fx = np.concatenate([fx, np.zeros(pad, np.float32)])

        key, k1, k2 = jax.random.split(jax.random.PRNGKey(self.seed), 3)
        scale = 0.5 / D
        W = (jax.random.uniform(k1, (V, D)) - 0.5) * 2 * scale
        Wc = (jax.random.uniform(k2, (V, D)) - 0.5) * 2 * scale
        b = jnp.zeros(V)
        bc = jnp.zeros(V)
        hW = jnp.full((V, D), 1e-8)
        hWc = jnp.full((V, D), 1e-8)
        hb = jnp.full(V, 1e-8)
        hbc = jnp.full(V, 1e-8)

        epoch_fn = make_glove_epoch(B, self.shuffle, mesh=self.device_mesh)
        ii_d, jj_d = jnp.asarray(ii), jnp.asarray(jj)
        logx_d, fx_d = jnp.asarray(logx), jnp.asarray(fx)
        # `key` continues the stream already split for W/Wc init above —
        # never reuse a key across init and shuffling
        epoch_losses = []
        for _ in range(self.epochs):
            key, sub = jax.random.split(key)
            (W, Wc, b, bc, hW, hWc, hb, hbc, losses) = epoch_fn(
                W, Wc, b, bc, hW, hWc, hb, hbc,
                ii_d, jj_d, logx_d, fx_d, sub, self.learning_rate)
            epoch_losses.append(losses)  # device arrays; one sync below
        for losses in epoch_losses:
            self.loss_history.extend((np.asarray(losses) / B).tolist())
        self.lookup_table.set_vectors(np.asarray(W + Wc))
        return self
