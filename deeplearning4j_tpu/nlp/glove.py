"""GloVe — global vectors from co-occurrence statistics.

Reference: models/glove/Glove.java + AbstractCoOccurrences.java
(co-occurrence counting with 1/distance weighting, shuffled batches,
AdaGrad per-element updates — SURVEY.md §2.3).

TPU design: co-occurrence counting stays host-side (dict accumulation over
windows, as the reference spills binary CoOccurrence files); training is
batched weighted-least-squares on device — gather rows, compute
f(X)·(w·w̃ + b + b̃ − log X)², AdaGrad scatter updates. Final vectors are
w + w̃ (standard GloVe practice).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import VocabConstructor


class AbstractCoOccurrences:
    """Symmetric windowed co-occurrence counts with 1/d weighting
    (reference glove/AbstractCoOccurrences.java)."""

    def __init__(self, window_size: int = 15, symmetric: bool = True):
        self.window_size = window_size
        self.symmetric = symmetric
        self.counts: Dict[Tuple[int, int], float] = defaultdict(float)

    def accumulate(self, idx: np.ndarray):
        n = idx.size
        for i in range(n):
            for j in range(max(0, i - self.window_size), i):
                w = 1.0 / (i - j)
                a, b = int(idx[i]), int(idx[j])
                self.counts[(a, b)] += w
                if self.symmetric:
                    self.counts[(b, a)] += w

    def arrays(self):
        if not self.counts:
            return (np.zeros(0, np.int32), np.zeros(0, np.int32),
                    np.zeros(0, np.float32))
        ij = np.array(list(self.counts.keys()), np.int32)
        x = np.array(list(self.counts.values()), np.float32)
        return ij[:, 0].copy(), ij[:, 1].copy(), x


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def glove_step(W, Wc, b, bc, hW, hWc, hb, hbc, i, j, logx, fx, lr):
    """AdaGrad step on a batch of (i, j, X_ij) triples."""
    wi, wj = W[i], Wc[j]                                  # [B, D]
    diff = jnp.einsum("bd,bd->b", wi, wj) + b[i] + bc[j] - logx
    wdiff = fx * diff                                     # [B]
    loss = 0.5 * jnp.sum(wdiff * diff)

    gwi = wdiff[:, None] * wj
    gwj = wdiff[:, None] * wi
    gb = wdiff

    # AdaGrad: accumulate squared grads, scale updates
    hW = hW.at[i].add(gwi ** 2)
    hWc = hWc.at[j].add(gwj ** 2)
    hb = hb.at[i].add(gb ** 2)
    hbc = hbc.at[j].add(gb ** 2)
    W = W.at[i].add(-lr * gwi / jnp.sqrt(hW[i] + 1e-8))
    Wc = Wc.at[j].add(-lr * gwj / jnp.sqrt(hWc[j] + 1e-8))
    b = b.at[i].add(-lr * gb / jnp.sqrt(hb[i] + 1e-8))
    bc = bc.at[j].add(-lr * gb / jnp.sqrt(hbc[j] + 1e-8))
    return W, Wc, b, bc, hW, hWc, hb, hbc, loss


class Glove(SequenceVectors):
    """GloVe trainer with the SequenceVectors query API (similarity,
    words_nearest). Builder mirrors reference Glove.Builder (xMax, alpha,
    shuffle, symmetric)."""

    def __init__(self, layer_size: int = 100, window_size: int = 15,
                 min_word_frequency: int = 1, epochs: int = 25,
                 learning_rate: float = 0.05, x_max: float = 100.0,
                 alpha: float = 0.75, batch_size: int = 4096,
                 seed: int = 123, symmetric: bool = True, shuffle: bool = True,
                 vocab_limit: Optional[int] = None):
        super().__init__(layer_size=layer_size, window_size=window_size,
                         min_word_frequency=min_word_frequency, epochs=epochs,
                         learning_rate=learning_rate, batch_size=batch_size,
                         seed=seed, negative=0, use_hs=False,
                         vocab_limit=vocab_limit)
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self.shuffle = shuffle
        self.use_hs = False  # glove has no output tree

    def _init_from_vocab(self):
        V = self.vocab.num_words()
        if V == 0:
            raise ValueError("Empty vocabulary")
        from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable

        self.lookup_table = InMemoryLookupTable(V, self.layer_size,
                                                seed=self.seed, negative=0)

    def fit(self, sequences):
        seq_list = [list(s) for s in sequences]
        if self.vocab is None:
            self.build_vocab(seq_list)
        V = self.vocab.num_words()
        D = self.layer_size

        cooc = AbstractCoOccurrences(self.window_size, self.symmetric)
        for tokens in seq_list:
            idx = self._sequence_indices(tokens)
            if idx.size:
                cooc.accumulate(idx)
        ii, jj, xx = cooc.arrays()
        if ii.size == 0:
            raise ValueError("No co-occurrences — corpus too small")
        logx = np.log(xx)
        fx = np.minimum(1.0, (xx / self.x_max) ** self.alpha).astype(np.float32)

        key = jax.random.PRNGKey(self.seed)
        k1, k2 = jax.random.split(key)
        scale = 0.5 / D
        W = (jax.random.uniform(k1, (V, D)) - 0.5) * 2 * scale
        Wc = (jax.random.uniform(k2, (V, D)) - 0.5) * 2 * scale
        b = jnp.zeros(V)
        bc = jnp.zeros(V)
        hW = jnp.full((V, D), 1e-8)
        hWc = jnp.full((V, D), 1e-8)
        hb = jnp.full(V, 1e-8)
        hbc = jnp.full(V, 1e-8)

        B = self.batch_size
        n = ii.size
        for _ in range(self.epochs):
            order = self._rng.permutation(n) if self.shuffle else np.arange(n)
            for s in range(0, n, B):
                sel = order[s:s + B]
                if sel.size < B:  # pad tail to keep one compiled shape
                    sel = np.concatenate(
                        [sel, self._rng.integers(0, n, B - sel.size)])
                (W, Wc, b, bc, hW, hWc, hb, hbc, loss) = glove_step(
                    W, Wc, b, bc, hW, hWc, hb, hbc,
                    ii[sel], jj[sel], logx[sel], fx[sel], self.learning_rate)
                # device scalar; one host sync after the run (below)
                self.loss_history.append(loss)
        # fetch fresh device entries, then normalize only those — floats
        # from a previous fit() are already normalized, and dividing on
        # host avoids one tiny device dispatch per recorded batch
        from deeplearning4j_tpu.nlp.sequencevectors import _fetch_loss_scalars

        fresh = {i for i, l in enumerate(self.loss_history)
                 if not isinstance(l, float)}
        self.loss_history = [
            l / B if i in fresh else l
            for i, l in enumerate(_fetch_loss_scalars(self.loss_history))]
        self.lookup_table.set_vectors(np.asarray(W + Wc))
        return self
