"""Document vectorizers: bag-of-words counts and TF-IDF.

Reference: bagofwords/vectorizer/{BagOfWordsVectorizer, TfidfVectorizer,
BaseTextVectorizer}.java (SURVEY.md §2.3 "Bag-of-words" row) — vectorize a
labelled corpus into a DataSet for the classifiers.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.nlp.text import (
    DefaultTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


class BaseTextVectorizer:
    """Shared corpus→matrix machinery (reference BaseTextVectorizer)."""

    def __init__(self, min_word_frequency: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Sequence[str] = (),
                 vocab_limit: Optional[int] = None):
        self.min_word_frequency = min_word_frequency
        self.factory = tokenizer_factory or DefaultTokenizerFactory()
        self.stop = frozenset(stop_words)
        self.vocab_limit = vocab_limit
        self.vocab: Optional[VocabCache] = None
        self.n_docs = 0
        self._doc_freq: Optional[np.ndarray] = None

    def _tokenize(self, text: str) -> List[str]:
        toks = self.factory.create(text).get_tokens()
        return [t for t in toks if t not in self.stop] if self.stop else toks

    def fit(self, documents: Sequence[str]):
        seqs = [self._tokenize(d) for d in documents]
        self.vocab = (VocabConstructor(self.min_word_frequency,
                                       self.vocab_limit, build_huffman=False)
                      .add_source(seqs).build_joint_vocabulary())
        V = self.vocab.num_words()
        self.n_docs = len(seqs)
        df = np.zeros(V, np.float64)
        for toks in seqs:
            seen = {self.vocab.index_of(t) for t in toks}
            for i in seen:
                if i >= 0:
                    df[i] += 1
        self._doc_freq = df
        return self

    def counts(self, text: str) -> np.ndarray:
        v = np.zeros(self.vocab.num_words(), np.float32)
        for t in self._tokenize(text):
            i = self.vocab.index_of(t)
            if i >= 0:
                v[i] += 1
        return v

    def transform(self, text: str) -> np.ndarray:
        raise NotImplementedError

    def vectorize(self, documents: Sequence[str],
                  labels: Sequence[str]) -> DataSet:
        """Corpus → DataSet (reference TextVectorizer.vectorize)."""
        label_names = sorted(set(labels))
        lab_idx = {l: i for i, l in enumerate(label_names)}
        X = np.stack([self.transform(d) for d in documents])
        Y = np.eye(len(label_names), dtype=np.float32)[
            [lab_idx[l] for l in labels]]
        ds = DataSet(X, Y)
        ds.label_names = label_names
        return ds


class BagOfWordsVectorizer(BaseTextVectorizer):
    """Raw term counts (reference BagOfWordsVectorizer)."""

    def transform(self, text: str) -> np.ndarray:
        return self.counts(text)


class TfidfVectorizer(BaseTextVectorizer):
    """TF-IDF weights (reference TfidfVectorizer: tf * log(N/df))."""

    def transform(self, text: str) -> np.ndarray:
        tf = self.counts(text)
        total = max(tf.sum(), 1.0)
        idf = np.log(self.n_docs / np.maximum(self._doc_freq, 1.0))
        return (tf / total * idf).astype(np.float32)

    def tfidf_word(self, word: str, document: str) -> float:
        i = self.vocab.index_of(word)
        if i < 0:
            return 0.0
        return float(self.transform(document)[i])
