"""WordVectorSerializer — model I/O in the word2vec interchange formats.

Reference: embeddings/loader/WordVectorSerializer.java (Google word2vec
binary and text formats, zip full-model serialization — SURVEY.md §2.3).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zipfile
from typing import Optional

import numpy as np

from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord


class WordVectorSerializer:
    # ------------------------------------------------ Google text format
    @staticmethod
    def write_word_vectors(model: SequenceVectors, path: str):
        """One `word v1 v2 ... vD` line per word (reference
        writeWordVectors)."""
        V = model.vocab.num_words()
        vecs = model.lookup_table.vectors()
        with open(path, "w", encoding="utf-8") as fh:
            for i in range(V):
                vals = " ".join(f"{v:.6f}" for v in vecs[i])
                fh.write(f"{model.vocab.word_at_index(i)} {vals}\n")

    @staticmethod
    def load_txt_vectors(path: str) -> SequenceVectors:
        words, rows = [], []
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                parts = line.rstrip("\n").split(" ")
                if len(parts) == 2 and all(p.isdigit() for p in parts):
                    continue  # optional "V D" header
                words.append(parts[0])
                rows.append(np.array(parts[1:], dtype=np.float32))
        return _model_from_arrays(words, np.stack(rows))

    # ----------------------------------------------- Google binary format
    @staticmethod
    def write_binary(model: SequenceVectors, path: str):
        """Google word2vec .bin: header `V D\\n`, then per word
        `word<space><D float32 LE><\\n>` (reference loadGoogleModel
        counterpart)."""
        V = model.vocab.num_words()
        vecs = model.lookup_table.vectors().astype("<f4")
        with open(path, "wb") as fh:
            fh.write(f"{V} {vecs.shape[1]}\n".encode())
            for i in range(V):
                fh.write(model.vocab.word_at_index(i).encode("utf-8") + b" ")
                fh.write(vecs[i].tobytes())
                fh.write(b"\n")

    @staticmethod
    def load_google_model(path: str, binary: bool = True) -> SequenceVectors:
        if not binary:
            return WordVectorSerializer.load_txt_vectors(path)
        with open(path, "rb") as fh:
            header = fh.readline().decode("utf-8").strip().split()
            V, D = int(header[0]), int(header[1])
            words, rows = [], []
            for _ in range(V):
                chars = bytearray()
                while True:
                    c = fh.read(1)
                    if c in (b" ", b""):
                        break
                    chars += c
                words.append(chars.decode("utf-8"))
                rows.append(np.frombuffer(fh.read(4 * D), dtype="<f4"))
                nl = fh.peek(1)[:1] if hasattr(fh, "peek") else b""
                if nl == b"\n":
                    fh.read(1)
        return _model_from_arrays(words, np.stack(rows))

    # --------------------------------------------------- full-model zip
    @staticmethod
    def write_full_model(model: SequenceVectors, path: str):
        """Zip with config.json + vocab.json + syn0/syn1/syn1neg .npy
        (reference zip serialization; analogue of ModelSerializer zips)."""
        t = model.lookup_table
        cfg = {"layer_size": model.layer_size,
               "window_size": model.window_size,
               "negative": model.negative, "use_hs": model.use_hs,
               "learning_rate": model.learning_rate, "seed": model.seed}
        vocab = [{"word": w.word, "count": w.count, "code": w.code,
                  "points": w.points} for w in model.vocab.vocab_words()]
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("config.json", json.dumps(cfg))
            z.writestr("vocab.json", json.dumps(vocab))
            for name, arr in (("syn0", t.syn0), ("syn1", t.syn1),
                              ("syn1neg", t.syn1neg)):
                buf = io.BytesIO()
                np.save(buf, np.asarray(arr))
                z.writestr(f"{name}.npy", buf.getvalue())

    @staticmethod
    def read_full_model(path: str) -> SequenceVectors:
        import jax.numpy as jnp

        with zipfile.ZipFile(path) as z:
            cfg = json.loads(z.read("config.json"))
            vocab_entries = json.loads(z.read("vocab.json"))
            arrays = {name: np.load(io.BytesIO(z.read(f"{name}.npy")))
                      for name in ("syn0", "syn1", "syn1neg")}
        model = SequenceVectors(
            layer_size=cfg["layer_size"], window_size=cfg["window_size"],
            negative=cfg["negative"], use_hs=cfg["use_hs"],
            learning_rate=cfg["learning_rate"], seed=cfg["seed"])
        cache = VocabCache()
        for e in vocab_entries:
            vw = VocabWord(e["word"], e["count"])
            vw.code, vw.points = e["code"], e["points"]
            cache.add_token(vw)
        cache.finish(min_word_frequency=0)
        model.vocab = cache
        model.lookup_table = InMemoryLookupTable(
            arrays["syn0"].shape[0], cfg["layer_size"], seed=cfg["seed"],
            use_hs=cfg["use_hs"], negative=cfg["negative"])
        model.lookup_table.syn0 = jnp.asarray(arrays["syn0"])
        model.lookup_table.syn1 = jnp.asarray(arrays["syn1"])
        model.lookup_table.syn1neg = jnp.asarray(arrays["syn1neg"])
        if cfg["negative"] > 0:
            from deeplearning4j_tpu.nlp.vocab import unigram_table

            model._cum_table = unigram_table(cache)
        if cfg["use_hs"]:
            # rebuild the padded huffman arrays from the stored codes so a
            # loaded model can continue training / infer
            from deeplearning4j_tpu.nlp.vocab import Huffman

            model._codes, model._points, model._mask = Huffman(
                cache.vocab_words()).padded_arrays()
        return model


def _model_from_arrays(words, matrix: np.ndarray) -> SequenceVectors:
    import jax.numpy as jnp

    model = SequenceVectors(layer_size=matrix.shape[1])
    cache = VocabCache()
    # preserve file order: counts descend with position
    for rank, w in enumerate(words):
        cache.add_token(VocabWord(w, float(len(words) - rank)))
    cache.finish(min_word_frequency=0)
    model.vocab = cache
    model.lookup_table = InMemoryLookupTable(len(words), matrix.shape[1])
    order = [cache.index_of(w) for w in words]
    reordered = np.empty_like(matrix)
    for src, dst in enumerate(order):
        reordered[dst] = matrix[src]
    model.lookup_table.syn0 = jnp.asarray(reordered)
    return model
