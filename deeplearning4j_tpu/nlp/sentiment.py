"""Sentiment lexicon scoring + POS-aware tokenization.

Reference:
- `deeplearning4j-nlp/.../text/corpora/sentiwordnet/SWN3.java` — loads the
  SentiWordNet 3.0 TSV (`POS<TAB>id<TAB>posScore<TAB>negScore<TAB>terms`),
  averages pos-neg per word#pos across senses weighted 1/rank, and maps a
  score to the strings weak/strong_positive/negative/neutral.
- `deeplearning4j-nlp/.../text/annotator/PoStagger.java` (UIMA) — the POS
  annotations the reference pipeline attaches; here a compact rule-based
  perceptron-free tagger (suffix + lexicon heuristics) provides the same
  `word#pos` keys without the UIMA dependency.

Zero egress: when no SentiWordNet file is supplied, a small built-in seed
lexicon (hand-picked common sentiment words) keeps the API functional;
`SentiWordNet(path)` loads the real file when the user has it.
"""

from __future__ import annotations

import re
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

# score -> label thresholds (reference SWN3.classifyScore)
_STRONG = 0.5
_WEAK = 0.25

# seed lexicon used when no SentiWordNet file is available: word#pos -> score
_SEED: Dict[str, float] = {
    "good#a": 0.625, "great#a": 0.75, "excellent#a": 0.875,
    "wonderful#a": 0.75, "amazing#a": 0.625, "love#v": 0.625,
    "like#v": 0.375, "enjoy#v": 0.5, "happy#a": 0.625, "best#a": 0.75,
    "nice#a": 0.5, "awesome#a": 0.75, "fantastic#a": 0.75,
    "bad#a": -0.625, "terrible#a": -0.75, "awful#a": -0.75,
    "horrible#a": -0.75, "hate#v": -0.75, "dislike#v": -0.5,
    "worst#a": -0.875, "poor#a": -0.5, "sad#a": -0.5, "angry#a": -0.625,
    "disappointing#a": -0.625, "boring#a": -0.5, "broken#a": -0.375,
}


class SentiWordNet:
    """SWN3 equivalent: per-`word#pos` sentiment scores + classification."""

    def __init__(self, path: Optional[str] = None):
        if path is not None:
            self.scores = self._load(path)
        else:
            self.scores = dict(_SEED)

    @staticmethod
    def _load(path: str) -> Dict[str, float]:
        """Parse the SentiWordNet 3.0 TSV exactly like SWN3.java: each
        `term#rank` contributes (pos-neg)/rank, normalized by sum 1/rank."""
        acc: Dict[str, List[Tuple[int, float]]] = defaultdict(list)
        with open(path, encoding="utf-8") as f:
            for line in f:
                if not line.strip() or line.startswith("#"):
                    continue
                parts = line.rstrip("\n").split("\t")
                if len(parts) < 5:
                    continue
                pos, _id, p, n, terms = parts[:5]
                try:
                    delta = float(p) - float(n)
                except ValueError:
                    continue
                for term in terms.split():
                    if "#" not in term:
                        continue
                    word, rank = term.rsplit("#", 1)
                    try:
                        acc[f"{word}#{pos}"].append((int(rank), delta))
                    except ValueError:
                        continue
        out: Dict[str, float] = {}
        for key, senses in acc.items():
            total = sum(d / r for r, d in senses)
            norm = sum(1.0 / r for r, _ in senses)
            out[key] = total / norm if norm else 0.0
        return out

    # ------------------------------------------------------------- scoring
    def extract(self, word: str, pos: str = "a") -> float:
        return self.scores.get(f"{word.lower()}#{pos}", 0.0)

    def classify(self, word: str, pos: str = "a") -> str:
        """Reference SWN3 classification strings."""
        return self.classify_score(self.extract(word, pos))

    @staticmethod
    def classify_score(score: float) -> str:
        if score >= _STRONG:
            return "strong_positive"
        if score >= _WEAK:
            return "positive"
        if score > 0:
            return "weak_positive"
        if score <= -_STRONG:
            return "strong_negative"
        if score <= -_WEAK:
            return "negative"
        if score < 0:
            return "weak_negative"
        return "neutral"

    def extract_any(self, word: str, pos: str = "a") -> Optional[float]:
        """Score for word#pos, falling back to the word's other POS senses
        (the tagger is heuristic; a miss shouldn't zero the sentiment)."""
        w = word.lower()
        if f"{w}#{pos}" in self.scores:
            return self.scores[f"{w}#{pos}"]
        for alt in ("a", "n", "v", "r"):
            if f"{w}#{alt}" in self.scores:
                return self.scores[f"{w}#{alt}"]
        return None

    def score_tokens(self, tagged: Iterable[Tuple[str, str]]) -> float:
        """Mean sentiment over (word, pos) pairs with a lexicon hit."""
        hits = [v for v in (self.extract_any(w, p) for w, p in tagged)
                if v is not None]
        return sum(hits) / len(hits) if hits else 0.0


# --------------------------------------------------------------- POS tagger

_POS_LEXICON = {
    "the": "d", "a": "d", "an": "d", "this": "d", "that": "d",
    "i": "n", "you": "n", "he": "n", "she": "n", "it": "n", "we": "n",
    "they": "n", "is": "v", "are": "v", "was": "v", "were": "v", "be": "v",
    "been": "v", "am": "v", "have": "v", "has": "v", "had": "v", "do": "v",
    "does": "v", "did": "v", "will": "v", "would": "v", "can": "v",
    "could": "v", "not": "r", "very": "r", "really": "r", "quite": "r",
    "and": "c", "or": "c", "but": "c", "of": "p", "in": "p", "on": "p",
    "at": "p", "to": "p", "with": "p", "for": "p",
    # common suffix-less adjectives (the seed lexicon keys these as #a)
    "good": "a", "bad": "a", "great": "a", "nice": "a", "best": "a",
    "worst": "a", "poor": "a", "sad": "a", "happy": "a", "cool": "a",
    "new": "a", "old": "a", "big": "a", "small": "a", "fine": "a",
}

_SUFFIX_RULES: List[Tuple[str, str]] = [
    ("ly", "r"),                       # adverbs
    ("ing", "v"), ("ed", "v"),         # verb forms
    ("ous", "a"), ("ful", "a"), ("ive", "a"), ("able", "a"), ("ible", "a"),
    ("al", "a"), ("ic", "a"), ("less", "a"),
    ("ness", "n"), ("ment", "n"), ("tion", "n"), ("sion", "n"), ("ity", "n"),
    ("er", "n"), ("ism", "n"), ("ist", "n"),
]


def pos_tag(tokens: Iterable[str]) -> List[Tuple[str, str]]:
    """Tag tokens with SentiWordNet POS letters (n/v/a/r + d/c/p for
    function words): lexicon first, then suffix heuristics, noun default —
    the shape of the reference's UIMA PoStagger output keyed for SWN3."""
    out = []
    for tok in tokens:
        w = tok.lower()
        if w in _POS_LEXICON:
            out.append((tok, _POS_LEXICON[w]))
            continue
        if re.fullmatch(r"[0-9.,%-]+", w):
            out.append((tok, "n"))
            continue
        for suffix, tag in _SUFFIX_RULES:
            if w.endswith(suffix) and len(w) > len(suffix) + 2:
                out.append((tok, tag))
                break
        else:
            out.append((tok, "n"))
    return out


class PosAwareTokenizerFactory:
    """TokenizerFactory-compatible wrapper that attaches POS tags: its
    tokenizers yield `word#pos` strings (the reference PoStagger + SWN3
    keying), so downstream vocab/embedding pipelines can train on
    sense-separated tokens. Tagging routes through the pluggable
    annotation engine (nlp/annotation.py — the UIMA AnalysisEngine slot),
    so a spaCy engine upgrades this factory without code changes."""

    def __init__(self, base_factory=None, engine=None):
        from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory

        self.base = base_factory or DefaultTokenizerFactory()
        self.engine = engine

    def create(self, text: str):
        from deeplearning4j_tpu.nlp.annotation import get_annotation_engine
        from deeplearning4j_tpu.nlp.text import Tokenizer

        eng = self.engine or get_annotation_engine()
        toks = self.base.create(text).get_tokens()
        return Tokenizer([f"{w}#{p}" for w, p in eng.pos_tags(toks)])
