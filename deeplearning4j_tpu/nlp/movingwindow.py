"""Moving-window training glue (reference text/movingwindow/
{WindowConverter,ContextLabelRetriever}.java; Window/windows themselves
live in nlp/text.py).

WindowConverter turns context windows into dense examples by concatenating
the word vectors of each window position — the input featurization for
word-level classifiers (e.g. NER over windows). ContextLabelRetriever
strips inline ``<LABEL> ... </LABEL>`` span markup from a sentence and
returns the clean text plus labeled token spans.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.text import Window

_BEGIN = re.compile(r"^<([A-Za-z0-9_-]+)>$")
_END = re.compile(r"^</([A-Za-z0-9_-]+)>$")

NONE_LABEL = "NONE"


class WindowConverter:
    @staticmethod
    def as_example_array(window: Window, vec, normalize: bool = False
                         ) -> np.ndarray:
        """Concatenate the window's word vectors into one [w * dim] row
        (reference WindowConverter.asExampleArray). Unknown words get the
        zero vector. `vec` is a Word2Vec-like model exposing
        word_vector(word)."""
        parts = []
        for word in window.words:
            v = vec.word_vector(word)
            if v is None:
                dim = vec.layer_size if hasattr(vec, "layer_size") else None
                if dim is None:
                    raise ValueError("cannot infer vector size for OOV word")
                v = np.zeros((dim,), np.float32)
            v = np.asarray(v, np.float32)
            if normalize:
                n = float(np.linalg.norm(v))
                if n > 0:
                    v = v / n
            parts.append(v)
        return np.concatenate(parts)

    @staticmethod
    def as_example_matrix(windows: List[Window], vec,
                          normalize: bool = False) -> np.ndarray:
        return np.stack([
            WindowConverter.as_example_array(w, vec, normalize)
            for w in windows])


def string_with_labels(sentence: str, tokenizer_factory=None
                       ) -> Tuple[str, Dict[Tuple[int, int], str]]:
    """Strip ``<L> ... </L>`` markup and return (clean sentence,
    {(begin_token, end_token): label}) with NONE spans omitted from the
    map (reference ContextLabelRetriever.stringWithLabels — mismatched or
    nested markers raise, matching its assertions)."""
    if tokenizer_factory is not None:
        tokens = tokenizer_factory.create(sentence).get_tokens()
    else:
        tokens = sentence.split()

    clean: List[str] = []
    spans: Dict[Tuple[int, int], str] = {}
    curr_label: Optional[str] = None
    span_start = 0
    for tok in tokens:
        mb = _BEGIN.match(tok)
        me = _END.match(tok)
        if mb:
            if curr_label is not None:
                raise ValueError(
                    f"nested begin label <{mb.group(1)}> inside "
                    f"<{curr_label}>")
            curr_label = mb.group(1)
            span_start = len(clean)
        elif me:
            if curr_label is None:
                raise ValueError(
                    f"end label </{me.group(1)}> with no begin label")
            if me.group(1) != curr_label:
                raise ValueError(
                    f"label mismatch: <{curr_label}> closed by "
                    f"</{me.group(1)}>")
            if curr_label != NONE_LABEL:
                spans[(span_start, len(clean))] = curr_label
            curr_label = None
        else:
            clean.append(tok)
    if curr_label is not None:
        raise ValueError(f"unclosed label <{curr_label}>")
    return " ".join(clean), spans
