"""Text pipeline: tokenizers, preprocessors, sentence/document iterators.

Reference (SURVEY.md §2.3 "Text pipeline" row):
- text/tokenization/tokenizer/DefaultTokenizer.java, NGramTokenizer.java,
  preprocessor/{CommonPreprocessor, EndingPreProcessor}.java
- text/tokenization/tokenizerfactory/*
- text/sentenceiterator/{BasicLineIterator, FileSentenceIterator,
  CollectionSentenceIterator, PrefetchingSentenceIterator, labelaware/*}
- text/documentiterator/{LabelAwareIterator, LabelsSource}
- text/stopwords/StopWords.java
- text/inputsanitation/InputHomogenization.java
- text/movingwindow/{Window, Windows}.java

Host-side pure Python — corpus ingestion never touches the device.
"""

from __future__ import annotations

import os
import re
import unicodedata
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

from deeplearning4j_tpu.data.prefetcher import EOS, Prefetcher


# --------------------------------------------------------------------------
# Token preprocessors (reference tokenization/tokenizer/preprocessor/*)
# --------------------------------------------------------------------------
class TokenPreProcess:
    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (reference CommonPreprocessor)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer (reference EndingPreProcessor: strips s/ed/ing/ly...)."""

    def pre_process(self, token: str) -> str:
        for suffix in ("ing", "ed", "ly", "s"):
            if token.endswith(suffix) and len(token) > len(suffix) + 2:
                return token[: -len(suffix)]
        return token


class StemmingPreprocessor(CommonPreprocessor):
    def pre_process(self, token: str) -> str:
        return EndingPreProcessor().pre_process(super().pre_process(token))


def input_homogenization(s: str, preserve_case: bool = False) -> str:
    """Strip accents/punctuation (reference InputHomogenization.transform)."""
    s = unicodedata.normalize("NFD", s)
    s = "".join(c for c in s if unicodedata.category(c) != "Mn")
    s = re.sub(r"[^\w\s]", "", s)
    return s if preserve_case else s.lower()


# --------------------------------------------------------------------------
# Tokenizers (reference tokenization/tokenizer/*, tokenizerfactory/*)
# --------------------------------------------------------------------------
class Tokenizer:
    """Iterator-style tokenizer (reference Tokenizer interface:
    hasMoreTokens/nextToken/countTokens/getTokens)."""

    def __init__(self, tokens: List[str],
                 pre_processor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._i = 0
        self._pre = pre_processor

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self._pre = pre

    def has_more_tokens(self) -> bool:
        return self._i < len(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)

    def next_token(self) -> str:
        t = self._tokens[self._i]
        self._i += 1
        return self._pre.pre_process(t) if self._pre else t

    def get_tokens(self) -> List[str]:
        out = []
        while self.has_more_tokens():
            t = self.next_token()
            if t:
                out.append(t)
        return out

    def __iter__(self):
        while self.has_more_tokens():
            t = self.next_token()
            if t:
                yield t


class DefaultTokenizer(Tokenizer):
    """Whitespace tokenizer (reference DefaultTokenizer uses StringTokenizer)."""

    def __init__(self, text: str, pre_processor=None):
        super().__init__(text.split(), pre_processor)


class NGramTokenizer(Tokenizer):
    """Emits n-grams joined by spaces (reference NGramTokenizer)."""

    def __init__(self, text: str, min_n: int, max_n: int, pre_processor=None):
        base = DefaultTokenizer(text, pre_processor).get_tokens()
        tokens = list(base) if min_n <= 1 else []
        for n in range(max(2, min_n), max_n + 1):
            for i in range(len(base) - n + 1):
                tokens.append(" ".join(base[i:i + n]))
        super().__init__(tokens, None)


class TokenizerFactory:
    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self._pre = pre


class DefaultTokenizerFactory(TokenizerFactory):
    def __init__(self):
        self._pre = None

    def create(self, text: str) -> Tokenizer:
        return DefaultTokenizer(text, self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    def __init__(self, min_n: int, max_n: int):
        self._pre = None
        self.min_n, self.max_n = min_n, max_n

    def create(self, text: str) -> Tokenizer:
        return NGramTokenizer(text, self.min_n, self.max_n, self._pre)


# --------------------------------------------------------------------------
# Sentence iterators (reference text/sentenceiterator/*)
# --------------------------------------------------------------------------
class SentenceIterator:
    """next_sentence/has_next/reset protocol + optional preprocessor
    (reference SentenceIterator interface)."""

    def __init__(self, pre_processor: Optional[Callable[[str], str]] = None):
        self.pre_processor = pre_processor

    def _apply(self, s: str) -> str:
        return self.pre_processor(s) if self.pre_processor else s

    def next_sentence(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            yield self.next_sentence()


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Sequence[str], pre_processor=None):
        super().__init__(pre_processor)
        self._sentences = list(sentences)
        self._i = 0

    def next_sentence(self) -> str:
        s = self._sentences[self._i]
        self._i += 1
        return self._apply(s)

    def has_next(self) -> bool:
        return self._i < len(self._sentences)

    def reset(self):
        self._i = 0


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (reference BasicLineIterator)."""

    def __init__(self, path: str, pre_processor=None):
        super().__init__(pre_processor)
        self.path = path
        self._fh = None
        self._next = None
        self.reset()

    def _advance(self):
        line = self._fh.readline()
        self._next = line.rstrip("\n") if line else None

    def next_sentence(self) -> str:
        s = self._next
        self._advance()
        return self._apply(s)

    def has_next(self) -> bool:
        return self._next is not None

    def reset(self):
        if self._fh:
            self._fh.close()
        self._fh = open(self.path, "r", encoding="utf-8", errors="replace")
        self._advance()


class FileSentenceIterator(SentenceIterator):
    """Every line of every file under a directory (reference
    FileSentenceIterator)."""

    def __init__(self, root: str, pre_processor=None):
        super().__init__(pre_processor)
        self.root = root
        self.reset()

    def reset(self):
        self._files = []
        if os.path.isdir(self.root):
            for d, _, fs in sorted(os.walk(self.root)):
                self._files += [os.path.join(d, f) for f in sorted(fs)]
        else:
            self._files = [self.root]
        self._lines: List[str] = []
        self._fi = 0
        self._li = 0
        self._load_next_file()

    def _load_next_file(self):
        while self._fi < len(self._files):
            with open(self._files[self._fi], encoding="utf-8",
                      errors="replace") as fh:
                self._lines = [ln.rstrip("\n") for ln in fh if ln.strip()]
            self._fi += 1
            self._li = 0
            if self._lines:
                return
        self._lines = []

    def has_next(self) -> bool:
        return self._li < len(self._lines)

    def next_sentence(self) -> str:
        s = self._lines[self._li]
        self._li += 1
        if self._li >= len(self._lines):
            self._load_next_file()
        return self._apply(s)


class LineSentenceIterator(BasicLineIterator):
    pass


class PrefetchingSentenceIterator(SentenceIterator):
    """Background-thread prefetch wrapper (reference
    PrefetchingSentenceIterator) — overlaps disk IO with vocab/training.

    An adapter over `data/prefetcher.Prefetcher` (ISSUE 12 deduped the
    hand-rolled polling queue this class carried onto the one
    event-driven prefetch implementation in the tree): the backend's
    ``reset()`` runs inside the producer thread via the callable-source
    form, and `Prefetcher.stop` joins the superseded producer before a
    successor starts — both generations share the backend iterator, so
    they must never run concurrently."""

    def __init__(self, backend: SentenceIterator, buffer_size: int = 10000):
        super().__init__(None)
        self._backend = backend
        self._size = buffer_size
        self._start()

    def _start(self):
        backend = self._backend

        def source():
            backend.reset()
            while backend.has_next():
                yield backend.next_sentence()

        self._pf = Prefetcher(source, depth=self._size,
                              name="sentence-prefetch")
        self._advance()

    def _advance(self):
        item = self._pf.get()
        self._next = None if item is EOS else item

    def has_next(self) -> bool:
        return self._next is not None

    def next_sentence(self) -> str:
        s = self._next
        self._advance()
        return s

    def reset(self):
        # stop() joins the old producer FULLY (waking it if blocked on a
        # full channel) before the successor touches the shared backend
        self._pf.stop()
        self._start()


# --------------------------------------------------------------------------
# Label-aware iterators (reference sentenceiterator/labelaware/*,
# documentiterator/*)
# --------------------------------------------------------------------------
class LabelsSource:
    """Generates/stores document labels (reference
    documentiterator/LabelsSource)."""

    def __init__(self, template: str = "DOC_%d"):
        self.template = template
        self.labels: List[str] = []

    def next_label(self) -> str:
        label = self.template % len(self.labels)
        self.labels.append(label)
        return label

    def store_label(self, label: str):
        if label not in self.labels:
            self.labels.append(label)

    def get_labels(self) -> List[str]:
        return list(self.labels)


class LabelledDocument:
    def __init__(self, content: str, labels: List[str]):
        self.content = content
        self.labels = labels


class LabelAwareIterator:
    """has_next/next_document protocol (reference LabelAwareIterator)."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_document(self) -> LabelledDocument:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def get_labels_source(self) -> LabelsSource:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_document()


class LabelAwareListSentenceIterator(LabelAwareIterator):
    """Sentences + parallel label list (reference
    labelaware/LabelAwareListSentenceIterator)."""

    def __init__(self, sentences: Sequence[str],
                 labels: Optional[Sequence[str]] = None):
        self._sentences = list(sentences)
        self._source = LabelsSource()
        if labels is None:
            self._labels = [self._source.next_label() for _ in self._sentences]
        else:
            self._labels = list(labels)
            for l in self._labels:
                self._source.store_label(l)
        self._i = 0

    def has_next(self):
        return self._i < len(self._sentences)

    def next_document(self):
        d = LabelledDocument(self._sentences[self._i], [self._labels[self._i]])
        self._i += 1
        return d

    def reset(self):
        self._i = 0

    def get_labels_source(self):
        return self._source


class FileLabelAwareIterator(LabelAwareIterator):
    """Directory-per-label corpus (reference FileLabelAwareIterator):
    root/labelA/doc1.txt, root/labelB/doc2.txt ..."""

    def __init__(self, root: str):
        self.root = root
        self._source = LabelsSource()
        self.reset()

    def reset(self):
        self._docs: List[LabelledDocument] = []
        for label in sorted(os.listdir(self.root)):
            d = os.path.join(self.root, label)
            if not os.path.isdir(d):
                continue
            self._source.store_label(label)
            for f in sorted(os.listdir(d)):
                with open(os.path.join(d, f), encoding="utf-8",
                          errors="replace") as fh:
                    self._docs.append(LabelledDocument(fh.read(), [label]))
        self._i = 0

    def has_next(self):
        return self._i < len(self._docs)

    def next_document(self):
        d = self._docs[self._i]
        self._i += 1
        return d

    def get_labels_source(self):
        return self._source


# --------------------------------------------------------------------------
# Stop words (reference text/stopwords/StopWords.java — bundled english list)
# --------------------------------------------------------------------------
STOP_WORDS = frozenset("""a an and are as at be but by for if in into is it no
not of on or such that the their then there these they this to was will with
he she his her him i me my we our you your them from has have had do does did
than too very can cannot could should would about after all also am any been
before being between both down during each few further here how more most
other out over own same so some up what when where which while who whom why
""".split())


def get_stop_words() -> List[str]:
    return sorted(STOP_WORDS)


# --------------------------------------------------------------------------
# Moving window (reference text/movingwindow/{Window,Windows}.java)
# --------------------------------------------------------------------------
class Window:
    """A focus word with surrounding context (reference Window.java)."""

    def __init__(self, words: List[str], focus: int, begin: bool, end: bool):
        self.words = words
        self.focus_index = focus
        self.begin = begin
        self.end = end

    def focus_word(self) -> str:
        return self.words[self.focus_index]


def windows(tokens: List[str], window_size: int = 5,
            pad: str = "<none>") -> List[Window]:
    """Sliding windows with edge padding (reference Windows.windows)."""
    half = window_size // 2
    out = []
    for i in range(len(tokens)):
        left = tokens[max(0, i - half):i]
        right = tokens[i + 1:i + 1 + half]
        lpad = [pad] * (half - len(left))
        rpad = [pad] * (half - len(right))
        w = lpad + left + [tokens[i]] + right + rpad
        out.append(Window(w, half, i - half < 0, i + half >= len(tokens)))
    return out


# --------------------------------------------------------------------------
# Sentence → tokens transformer (reference SentenceTransformer in
# models/word2vec — wires iterator + tokenizer factory)
# --------------------------------------------------------------------------
class SentenceTransformer:
    def __init__(self, iterator: SentenceIterator,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Iterable[str] = ()):
        self.iterator = iterator
        self.factory = tokenizer_factory or DefaultTokenizerFactory()
        self.stop = frozenset(stop_words)

    def __iter__(self) -> Iterator[List[str]]:
        for sentence in self.iterator:
            toks = self.factory.create(sentence).get_tokens()
            if self.stop:
                toks = [t for t in toks if t not in self.stop]
            if toks:
                yield toks
