"""Command-line interface (reference: deeplearning4j-cli —
driver/CommandLineInterfaceDriver.java, subcommands/{Train, Test,
Predict}.java with args4j flags -conf/-input/-output/-model/-type;
SURVEY.md §2.6 L10 row)."""

from .driver import main

__all__ = ["main"]
