"""CLI driver: train / test / predict on config files + CSV/SVMLight input
(reference: cli/driver/CommandLineInterfaceDriver.java routing to
subcommands/Train.java:66 with flags -conf -input -output -model -type
:80-108, Test.java, Predict.java; Canova record readers supply the input).

Usage:
    python -m deeplearning4j_tpu.cli train   --conf conf.json --input d.csv \
        --model out.zip --num-classes 3 [--epochs 5] [--batch 32]
    python -m deeplearning4j_tpu.cli test    --model out.zip --input d.csv \
        --num-classes 3
    python -m deeplearning4j_tpu.cli predict --model out.zip --input d.csv \
        --output preds.csv
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="deeplearning4j_tpu",
        description="Train/test/predict on declarative model configs")
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp, model_required=True):
        sp.add_argument("--input", "-i", required=True,
                        help="input data file (CSV or SVMLight)")
        sp.add_argument("--format", choices=["csv", "svmlight"],
                        default="csv", help="input format (default csv)")
        sp.add_argument("--model", "-m", required=model_required,
                        help="model zip path")
        sp.add_argument("--batch", type=int, default=32)
        sp.add_argument("--label-index", type=int, default=-1,
                        help="label column in CSV (default: last)")
        sp.add_argument("--num-features", type=int, default=0,
                        help="feature count (required for svmlight)")
        sp.add_argument("--num-classes", type=int, default=-1,
                        help="one-hot classes; omit for regression input")
        sp.add_argument("--regression", action="store_true")

    t = sub.add_parser("train", help="fit a model config on a dataset")
    t.add_argument("--conf", "-c", required=True,
                   help="model configuration JSON "
                        "(MultiLayerConfiguration or ComputationGraph)")
    t.add_argument("--type", choices=["multi_layer_network",
                                      "computation_graph"],
                   default="multi_layer_network")
    t.add_argument("--epochs", type=int, default=1)
    t.add_argument("--output", "-o", default=None,
                   help="alias of --model for reference-flag parity")
    common(t, model_required=False)

    te = sub.add_parser("test", help="evaluate a trained model")
    common(te)

    pr = sub.add_parser("predict", help="write predictions for a dataset")
    pr.add_argument("--output", "-o", required=True,
                    help="predictions output CSV")
    common(pr)
    return p


def _make_iterator(args):
    from deeplearning4j_tpu.datasets.records import (
        CSVRecordReader,
        RecordReaderDataSetIterator,
        SVMLightRecordReader,
    )

    if args.format == "svmlight":
        if args.num_features <= 0:
            raise SystemExit("--num-features is required for svmlight input")
        reader = SVMLightRecordReader(args.input, args.num_features)
    else:
        reader = CSVRecordReader(args.input)
    return RecordReaderDataSetIterator(
        reader, args.batch,
        label_index=args.label_index,
        num_classes=args.num_classes,
        regression=args.regression)


def _load_model(path: str):
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    return ModelSerializer.restore(path)


def _cmd_train(args) -> int:
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration,
    )
    from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
        MultiLayerConfiguration,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    with open(args.conf) as f:
        conf_json = f.read()
    if args.type == "computation_graph":
        net = ComputationGraph(ComputationGraphConfiguration.from_json(conf_json))
    else:
        net = MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json))
    net.init()
    net.set_listeners(ScoreIterationListener(10, printer=print))

    it = _make_iterator(args)
    net.fit(it, epochs=args.epochs)

    out = args.model or args.output
    if not out:
        raise SystemExit("need --model (or --output) to save the trained model")
    ModelSerializer.write_model(net, out)
    print(f"model saved to {out}")
    return 0


def _cmd_test(args) -> int:
    net = _load_model(args.model)
    it = _make_iterator(args)
    ev = net.evaluate(it)
    print(ev.stats())
    return 0


def _cmd_predict(args) -> int:
    from deeplearning4j_tpu.datasets.records import (
        CSVRecordReader,
        SVMLightRecordReader,
    )

    net = _load_model(args.model)
    # prediction input has no label column: every CSV value is a feature
    # (svmlight rows still carry a label field; it is ignored)
    if args.format == "svmlight":
        if args.num_features <= 0:
            raise SystemExit("--num-features is required for svmlight input")
        feats = [f for _, f in SVMLightRecordReader(args.input,
                                                    args.num_features)]
    else:
        feats = [np.asarray([float(v) for v in rec], np.float32)
                 for rec in CSVRecordReader(args.input)]
    x = np.stack(feats)
    rows = []
    for s in range(0, len(x), args.batch):
        rows.append(np.asarray(net.output(x[s:s + args.batch])))
    preds = np.concatenate(rows)
    with open(args.output, "w") as f:
        for row in preds:
            f.write(",".join(f"{v:.8g}" for v in np.atleast_1d(row)) + "\n")
    print(f"wrote {len(preds)} predictions to {args.output}")
    return 0


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    return {"train": _cmd_train, "test": _cmd_test,
            "predict": _cmd_predict}[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
