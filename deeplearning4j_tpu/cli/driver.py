"""CLI driver: train / test / predict on config files + CSV/SVMLight input
(reference: cli/driver/CommandLineInterfaceDriver.java routing to
subcommands/Train.java:66 with flags -conf -input -output -model -type
:80-108, Test.java, Predict.java; Canova record readers supply the input).

Usage:
    python -m deeplearning4j_tpu.cli train   --conf conf.json --input d.csv \
        --model out.zip --num-classes 3 [--epochs 5] [--batch 32]
    python -m deeplearning4j_tpu.cli test    --model out.zip --input d.csv \
        --num-classes 3
    python -m deeplearning4j_tpu.cli predict --model out.zip --input d.csv \
        --output preds.csv

Serving (the continuous-batching inference server, serving/):

    python -m deeplearning4j_tpu.cli serve --model out.zip --port 9090 \
        --buckets 1,2,4,8 --max-wait-ms 5 [--replicas 2]
    python -m deeplearning4j_tpu.cli serve --conf conf.json \
        --checkpoint ckpt_dir ...        # resume a trained checkpoint
    python -m deeplearning4j_tpu.cli predict --server http://host:9090 \
        --input d.csv --output preds.csv # rows ride the server's batcher
    python -m deeplearning4j_tpu.cli serve --conf lm.json \
        --buckets 1x64,1x256 --generate-slots 4 --max-new-tokens 64 \
        ...                              # autoregressive generation:
                                         # prefill/decode split, paged
                                         # KV cache, POST /generate

Resharding (the portable resharding engine, reshard/ — train on one
mesh, restore and serve on any other):

    python -m deeplearning4j_tpu.cli reshard --checkpoint ckpt_dir \
        --target-mesh data=1            # dry-run: print the plan +
                                        # bytes moved vs lower bound

Placement search (reshard/search.py — the cost model picks the mesh):

    python -m deeplearning4j_tpu.cli plan --model mlp --fleet 2x4 \
        [--global-batch 24] [--hbm-gb 16] [--artifact PLAN_r01.json]
                                        # dry-run: ranked top-k
                                        # candidate table (memory /
                                        # collective bytes / bubble)

Distributed runtimes (reference Train.java `-runtime local|spark|hadoop`
+ cli-spark/SparkTrain.java; here the TPU-native equivalents):

    # single-process mesh (pjit over local devices — the Spark-local case)
    ... train --mesh data=4[,model=2][,pipe=2] [--microbatches 4] ...
    # multi-process elastic cluster (the Spark/Akka-cluster case)
    python -m deeplearning4j_tpu.cli coordinator [--port P]
    ... train --cluster HOST:PORT --num-workers 2 [--worker-id w0] \
        [--sync-every 1] [--checkpoint ck.zip] ...
    # multi-process pjit fleet (jax.distributed over the rendezvous env
    # contract; --multiprocess prints the dry-run launch plan)
    ... train --mesh data=8 --multiprocess 2 [--local-devices 4] ...
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="deeplearning4j_tpu",
        description="Train/test/predict on declarative model configs")
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp, model_required=True):
        sp.add_argument("--input", "-i", required=True,
                        help="input data file (CSV or SVMLight)")
        sp.add_argument("--format", choices=["csv", "svmlight"],
                        default="csv", help="input format (default csv)")
        sp.add_argument("--model", "-m", required=model_required,
                        help="model zip path")
        sp.add_argument("--batch", type=int, default=32)
        sp.add_argument("--label-index", type=int, default=-1,
                        help="label column in CSV (default: last)")
        sp.add_argument("--num-features", type=int, default=0,
                        help="feature count (required for svmlight)")
        sp.add_argument("--num-classes", type=int, default=-1,
                        help="one-hot classes; omit for regression input")
        sp.add_argument("--regression", action="store_true")

    t = sub.add_parser("train", help="fit a model config on a dataset")
    t.add_argument("--conf", "-c", required=True,
                   help="model configuration JSON "
                        "(MultiLayerConfiguration or ComputationGraph)")
    t.add_argument("--type", choices=["multi_layer_network",
                                      "computation_graph"],
                   default="multi_layer_network")
    t.add_argument("--epochs", type=int, default=1)
    t.add_argument("--output", "-o", default=None,
                   help="alias of --model for reference-flag parity")
    t.add_argument("--mesh", default=None,
                   help="single-process mesh axes, e.g. data=4 or "
                        "data=2,model=2,pipe=2 (roles: data/model/pipe/"
                        "expert; uses jax.sharding over local devices)")
    t.add_argument("--microbatches", type=int, default=None,
                   help="pipeline microbatches (with a pipe mesh axis)")
    t.add_argument("--multiprocess", type=int, default=None, metavar="N",
                   help="dry run: print the N-process local rendezvous "
                        "launch plan (DL4J_TPU_* env contract + one "
                        "command per process over virtual CPU devices) "
                        "and exit. Run the printed lines — each process "
                        "auto-initializes jax.distributed from the env "
                        "contract — or drive the fleet programmatically "
                        "via deeplearning4j_tpu.distributed.launch_local")
    t.add_argument("--local-devices", type=int, default=4,
                   help="virtual CPU devices per process in the "
                        "--multiprocess plan (default 4)")
    t.add_argument("--cluster", default=None,
                   help="coordinator HOST:PORT for multi-process elastic "
                        "data-parallel training (parameter averaging)")
    t.add_argument("--num-workers", type=int, default=1,
                   help="expected cluster size (data shards by rank)")
    t.add_argument("--worker-id", default=None,
                   help="stable worker id (default: host-pid)")
    t.add_argument("--sync-every", type=int, default=1,
                   help="local steps between cluster averaging rounds")
    t.add_argument("--checkpoint", default=None,
                   help="worker checkpoint path (elastic restart resumes)")
    t.add_argument("--prefetch-depth", type=int, default=None,
                   metavar="K",
                   help="input-pipeline queue depth (device-resident "
                        "batches prefetched ahead of the step loop; "
                        "0 = synchronous, default 2 — "
                        "data/pipeline.py)")
    common(t, model_required=False)

    co = sub.add_parser("coordinator",
                        help="run the cluster coordinator (registry + "
                             "heartbeats + averaging rounds)")
    co.add_argument("--host", default="0.0.0.0")
    co.add_argument("--port", type=int, default=9085)
    co.add_argument("--heartbeat-timeout", type=float, default=10.0)

    te = sub.add_parser("test", help="evaluate a trained model")
    common(te)

    pr = sub.add_parser("predict", help="write predictions for a dataset")
    pr.add_argument("--output", "-o", required=True,
                    help="predictions output CSV")
    pr.add_argument("--server", default=None, metavar="URL",
                    help="POST rows to a running `serve` instance "
                         "(http://host:port) instead of loading the "
                         "model in-process — rows ride the server's "
                         "continuous batcher")
    common(pr, model_required=False)

    sv = sub.add_parser(
        "serve", help="continuous-batching inference server "
                      "(serving/: bucket lattice + dynamic batching + "
                      "replica dispatch over HTTP)")
    sv.add_argument("--port", type=int, default=9090)
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--model", "-m", default=None,
                    help="model zip to serve (ModelSerializer format)")
    sv.add_argument("--conf", "-c", default=None,
                    help="model configuration JSON (with --checkpoint: "
                         "build the net, then resume its params)")
    sv.add_argument("--type", choices=["multi_layer_network",
                                       "computation_graph"],
                    default="multi_layer_network")
    sv.add_argument("--checkpoint", default=None,
                    help="Orbax checkpoint dir to resume from at "
                         "startup. The checkpoint may have been written "
                         "under ANY training mesh (2x4 TP fleet, zero1 "
                         "DP, ...) — the portable resharding engine "
                         "(reshard/) plans its placement onto this "
                         "serving process and reads only the slices it "
                         "needs")
    sv.add_argument("--buckets", default="1,2,4,8",
                    help="padding-bucket lattice: batch sizes "
                         "('1,2,4,8') or explicit BxT pairs "
                         "('1x64,4x64,4x256') for sequence models")
    sv.add_argument("--max-wait-ms", type=float, default=5.0,
                    help="batcher deadline: the longest a request waits "
                         "for coalescing before its batch cuts")
    sv.add_argument("--replicas", type=int, default=1,
                    help="jitted forward workers (round-robin dispatch)")
    sv.add_argument("--sequence", action="store_true",
                    help="requests are variable-length sequences (padded "
                         "to seq buckets with a validity mask)")
    sv.add_argument("--warmup-features", default=None,
                    help="example request row (comma floats, or ints for "
                         "token models) to warm every bucket before "
                         "traffic; required for the zero-retrace promise")
    sv.add_argument("--generate-slots", type=int, default=0, metavar="N",
                    help="serve autoregressive generation instead of "
                         "one-shot predict: a GenerationEngine with N "
                         "decode slots per replica (prefill/decode "
                         "split over a paged KV cache; POST /generate "
                         "streams tokens). Needs a BxT --buckets "
                         "lattice; warmup is automatic")
    sv.add_argument("--max-new-tokens", type=int, default=64,
                    help="generation output budget per request (also "
                         "sizes the KV cache: capacity = max prompt "
                         "bucket + this, page-quantized)")
    sv.add_argument("--page-size", type=int, default=16,
                    help="KV-cache page size in tokens "
                         "(serving/kvcache.py accounting grid)")
    sv.add_argument("--prefill-chunk", type=int, default=None,
                    help="prompt chunk length for interleaved prefill "
                         "(a lattice seq bucket; default: the largest)")
    sv.add_argument("--speculative-k", type=int, default=0, metavar="K",
                    help="speculative decode window width (0 = off; "
                         ">= 2: an n-gram proposer drafts K-1 tokens "
                         "per slot and ONE fixed-shape verify step "
                         "checks the window — greedy output stays "
                         "bit-identical, accepted drafts cut steps)")
    sv.add_argument("--kv-dtype", choices=["f32", "int8"], default="f32",
                    help="KV-cache storage dtype: int8 stores "
                         "per-page-scale quantized pages (~4x more "
                         "decode slots per HBM byte, greedy-parity "
                         "gated in the serving bench)")
    sv.add_argument("--watch-checkpoint", action="store_true",
                    help="fleet operations: keep watching --checkpoint "
                         "for newly committed steps and hot-swap each "
                         "one live (serving/fleet.CheckpointWatcher — "
                         "double-buffered restore off the request path, "
                         "atomic flip, zero dropped requests; a step "
                         "failing validation is rejected with the old "
                         "weights still serving)")
    sv.add_argument("--autoscale-max", type=int, default=0, metavar="N",
                    help="fleet operations: run a FleetSupervisor that "
                         "heals dead replicas and autoscales between "
                         "--replicas and N replicas from telemetry "
                         "queue-depth/p99 (0 = self-healing only, no "
                         "autoscaling)")
    sv.add_argument("--chaos", default=None, metavar="SPEC",
                    help="inject replica-scoped faults (distributed/"
                         "faults.py grammar, e.g. 'r0:kill@batch4') — "
                         "the self-healing demo/test hook")
    sv.add_argument("--multiprocess", type=int, default=None, metavar="N",
                    help="dry run: print the N-process serving fleet "
                         "plan (one engine per process on the "
                         "distributed runtime's env contract, ports "
                         "--port..--port+N-1) and exit")
    sv.add_argument("--local-devices", type=int, default=4,
                    help="virtual CPU devices per process in the "
                         "--multiprocess plan (default 4)")

    pl = sub.add_parser(
        "plan", help="dry-run the automatic placement search "
                     "(reshard/search.py): enumerate every valid "
                     "dp x tp x pp x sp x ep placement for a model + "
                     "fleet shape, rank them with the per-step cost "
                     "model, and print the top-k table with the score "
                     "breakdown (memory, collective bytes, bubble). "
                     "Nothing is placed: the search is a pure function "
                     "and builtin profiles need no jax backend")
    pl.add_argument("--model", "-m", default=None,
                    help="builtin profile name (mlp, lm — jax-free) or "
                         "a trained model zip to profile")
    pl.add_argument("--conf", "-c", default=None,
                    help="model configuration JSON to profile instead "
                         "of --model")
    pl.add_argument("--type", choices=["multi_layer_network",
                                       "computation_graph"],
                    default="multi_layer_network")
    pl.add_argument("--fleet", required=True,
                    help="fleet shape PxK (processes x devices each), "
                         "e.g. 2x4; plain N means 1xN")
    pl.add_argument("--global-batch", type=int, default=None,
                    help="per-step global batch the cost model sizes "
                         "activations and microbatches with")
    pl.add_argument("--hbm-gb", type=float, default=None,
                    help="per-device HBM budget in GiB; candidates "
                         "whose memory estimate exceeds it are pruned")
    pl.add_argument("--no-zero1", action="store_true",
                    help="drop the zero1 weight-update-sharding "
                         "variants from the candidate set")
    pl.add_argument("--top", type=int, default=5,
                    help="table rows to print (default 5)")
    pl.add_argument("--artifact", default=None,
                    help="also write the ranked scores as a PLAN "
                         "artifact (JSONL) for tools/benchdiff")

    rs = sub.add_parser(
        "reshard", help="dry-run the portable resharding planner: map a "
                        "checkpoint's recorded placement onto a target "
                        "mesh and print the per-action plan with bytes "
                        "moved vs the lower bound (reshard/planner.py; "
                        "nothing is restored or written)")
    rs.add_argument("--checkpoint", required=True,
                    help="Orbax checkpoint dir (ShardedCheckpointer "
                         "layout; the step's meta.json carries the "
                         "source placement)")
    rs.add_argument("--target-mesh", required=True,
                    help="target mesh axes, e.g. data=1 or "
                         "data=2,model=2 (same role grammar as train "
                         "--mesh; purely planned — no devices needed)")
    rs.add_argument("--step", type=int, default=None,
                    help="checkpoint step (default: latest)")
    rs.add_argument("--processes", type=int, default=1,
                    help="target process count (default 1 — the serve "
                         "case)")
    rs.add_argument("--zero1", action="store_true",
                    help="plan zero1 optimizer-state shardings on the "
                         "target data axis")
    rs.add_argument("--artifact", default=None,
                    help="also write the metric lines + summary as a "
                         "RESHARD artifact (JSONL) for tools/benchdiff")
    return p


def _fetch_input(path: str) -> str:
    """Resolve a possibly-remote input to a local file. The reference CLI
    trains straight from HDFS URIs (Train.java `-runtime hadoop`); here
    `gs://...` inputs download through datasets/cloud.GcsDownloader into
    the local cache (VERDICT r3 missing #3: the cloud IO layer existed
    but was not CLI-reachable)."""
    from deeplearning4j_tpu.datasets.cloud import GcsDownloader, _is_remote

    if _is_remote(path):
        return GcsDownloader().download(path)
    return path


def _put_output(local_path: str, dest: str) -> None:
    """Upload a saved model when the destination is remote."""
    from deeplearning4j_tpu.datasets.cloud import GcsUploader, _is_remote

    if _is_remote(dest):
        GcsUploader().upload(local_path, dest)


def _make_iterator(args):
    from deeplearning4j_tpu.datasets.records import (
        CSVRecordReader,
        RecordReaderDataSetIterator,
        SVMLightRecordReader,
    )

    args.input = _fetch_input(args.input)
    if args.format == "svmlight":
        if args.num_features <= 0:
            raise SystemExit("--num-features is required for svmlight input")
        reader = SVMLightRecordReader(args.input, args.num_features)
    else:
        reader = CSVRecordReader(args.input)
    return RecordReaderDataSetIterator(
        reader, args.batch,
        label_index=args.label_index,
        num_classes=args.num_classes,
        regression=args.regression)


def _load_model(path: str):
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    return ModelSerializer.restore(_fetch_input(path))


def _parse_mesh(spec: str):
    """'data=2,model=2' -> {"data": 2, "model": 2} (ordered)."""
    axes = {}
    for part in spec.split(","):
        if "=" not in part:
            raise SystemExit(f"bad --mesh entry {part!r}; expected role=N")
        role, _, n = part.partition("=")
        role = role.strip()
        if role not in ("data", "model", "pipe", "expert", "seq"):
            raise SystemExit(f"unknown mesh role {role!r} "
                             "(valid: data, model, pipe, expert, seq)")
        try:
            size = int(n)
        except ValueError:
            raise SystemExit(f"bad --mesh size {n!r} for {role}; "
                             "expected a positive integer") from None
        if size < 1:
            raise SystemExit(f"--mesh {role}={size}: size must be >= 1")
        axes[role] = size
    return axes


def _apply_mesh(net, args) -> None:
    """Route --mesh through the unified set_mesh entry point
    (parallel/placement.py) — the Spark-local runtime analogue."""
    import jax

    from deeplearning4j_tpu.parallel.mesh import make_mesh

    axes = _parse_mesh(args.mesh)
    need = int(np.prod(list(axes.values())))
    have = len(jax.devices())
    if need > have:
        raise SystemExit(
            f"--mesh {args.mesh} needs {need} devices but only {have} are "
            "visible (for CPU simulation set JAX_PLATFORMS=cpu and "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={need})")
    mesh = make_mesh(axes)
    net.set_mesh(mesh, axes={r: r for r in axes},
                 n_microbatches=args.microbatches)
    print(f"mesh: {dict(axes)} over {need} {jax.devices()[0].platform} "
          "devices")


def _scrub_multiprocess_argv(argv) -> list:
    """The per-process command of a --multiprocess plan is this same CLI
    invocation minus the plan flags themselves (a spawned process must
    train, not print another plan)."""
    out = []
    skip = False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok in ("--multiprocess", "--local-devices"):
            skip = True
            continue
        if tok.startswith(("--multiprocess=", "--local-devices=")):
            continue
        out.append(tok)
    return out


def _print_multiprocess_plan(args) -> int:
    """`train --multiprocess N` dry run: the copy-pastable local fleet
    (reference Train.java's `-runtime spark` analogue, rendered as
    explicit rendezvous launch lines instead of a cluster submit)."""
    from deeplearning4j_tpu.distributed.launcher import launch_plan

    worker_argv = ([sys.executable, "-m", "deeplearning4j_tpu.cli"]
                   + _scrub_multiprocess_argv(args._raw_argv))
    print(f"# {args.multiprocess}-process local rendezvous fleet "
          f"({args.local_devices} virtual CPU devices each); run these "
          "lines from the repo root:")
    for line in launch_plan(worker_argv, args.multiprocess,
                            local_device_count=args.local_devices):
        print(line)
    return 0


def _train_on_cluster(net, args, it) -> None:
    """Multi-process elastic parameter-averaging worker (the Spark/Akka
    cluster runtime analogue — reference cli-spark/SparkTrain.java):
    register with the coordinator, wait for the expected fleet, shard the
    batches by rank, then run the elastic averaging loop."""
    import os
    import socket
    import time

    from deeplearning4j_tpu.parallel.cluster import (
        ClusterClient,
        run_elastic_worker,
    )

    worker_id = args.worker_id or f"{socket.gethostname()}-{os.getpid()}"
    probe = ClusterClient(args.cluster, worker_id)
    try:
        deadline = time.monotonic() + 120
        while len(probe.workers()) < args.num_workers:
            if time.monotonic() > deadline:
                raise SystemExit(
                    f"cluster at {args.cluster} has {len(probe.workers())} "
                    f"workers; expected {args.num_workers}")
            time.sleep(0.2)
        # claim a shard slot ATOMICALLY on the coordinator instead of
        # `rank % num_workers` — an elastically replaced worker's fresh
        # monotonic rank could collide with a survivor's modulo
        # num_workers, duplicating one shard while another went
        # unprocessed (ADVICE r3). claim_slot does the read-modify-write
        # under the coordinator lock (a set/read-back protocol lets two
        # sweepers confirm the same slot).
        while True:
            shard_idx = probe.claim_slot(args.num_workers)
            if shard_idx is not None:
                break
            if time.monotonic() > deadline:
                raise SystemExit(f"no free shard slot at {args.cluster}")
            time.sleep(0.5)
        # single pass over the source iterator keeping only this shard
        # (the full dataset is never materialized on one worker); done
        # while the probe still heartbeats so the claim cannot be stolen
        batches = [ds for i, ds in enumerate(it)
                   if i % args.num_workers == shard_idx]
    except BaseException:
        # the claim survives one heartbeat_timeout for a same-id restart
        probe.close(deregister=False)
        raise
    print(f"worker {worker_id} shard {shard_idx}: {len(batches)} local batches")
    # hand the LIVE probe to the worker loop: its heartbeat keeps the
    # claimed slot protected through net/data setup — closing here would
    # leave the slot sweepable for one heartbeat_timeout (ADVICE r4)
    run_elastic_worker(args.cluster, worker_id, net, batches,
                       sync_every=args.sync_every,
                       checkpoint_path=args.checkpoint, epochs=args.epochs,
                       client=probe)


def _cmd_train(args) -> int:
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration,
    )
    from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
        MultiLayerConfiguration,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    if args.mesh and args.cluster:
        raise SystemExit("--mesh (single-process pjit) and --cluster "
                         "(multi-process averaging) are separate runtimes; "
                         "pick one per process")
    if args.multiprocess:
        return _print_multiprocess_plan(args)
    # spawned fleet member (env contract set by the launcher / a printed
    # --multiprocess plan / tpu_vm's pod launch script): bring up
    # jax.distributed before any mesh is built so jax.devices() is global
    from deeplearning4j_tpu.distributed import bootstrap

    if bootstrap.env_contract_present():
        bootstrap.initialize()
    with open(_fetch_input(args.conf)) as f:
        conf_json = f.read()
    if args.type == "computation_graph":
        net = ComputationGraph(ComputationGraphConfiguration.from_json(conf_json))
    else:
        net = MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json))
    net.init()
    net.set_listeners(ScoreIterationListener(10, printer=print))
    if args.mesh:
        _apply_mesh(net, args)
    if getattr(args, "prefetch_depth", None) is not None:
        from deeplearning4j_tpu.data.pipeline import set_prefetch_depth

        set_prefetch_depth(args.prefetch_depth)

    it = _make_iterator(args)
    if args.cluster:
        _train_on_cluster(net, args, it)
    else:
        if getattr(net, "_multiprocess", False):
            # every fleet member read the same input file; feed each its
            # process-major slice of every batch (the global batch the
            # jitted step sees is the original, assembled by
            # distributed.global_mesh.globalize_batch in _batch_dict)
            it = _shard_batches_by_process(it)
        net.fit(it, epochs=args.epochs)

    out = args.model or args.output
    if not out:
        raise SystemExit("need --model (or --output) to save the trained model")
    from deeplearning4j_tpu.datasets.cloud import _is_remote

    if _is_remote(out):
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            local = os.path.join(td, "model.zip")
            ModelSerializer.write_model(net, local)
            _put_output(local, out)
    else:
        ModelSerializer.write_model(net, out)
    print(f"model saved to {out}")
    return 0


def _shard_batches_by_process(it):
    """Slice every DataSet to this process's rows (process-spanning mesh:
    all members must step in lockstep over the same batch COUNT, so the
    split is within each batch, not across batches). The split rule is
    `data/sharding.process_slice` — identical to what the input
    pipeline's `ShardAssignment` and `global_mesh.local_shard` apply."""
    import jax

    from deeplearning4j_tpu.data.sharding import local_rows
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    p, n = jax.process_index(), jax.process_count()

    def cut(a):
        return None if a is None else local_rows(a, p, n)

    return ListDataSetIterator([
        DataSet(cut(ds.features), cut(ds.labels),
                cut(ds.features_mask), cut(ds.labels_mask))
        for ds in it])


def _cmd_coordinator(args) -> int:
    from deeplearning4j_tpu.parallel.cluster import ClusterCoordinator

    coord = ClusterCoordinator(host=args.host, port=args.port,
                               heartbeat_timeout=args.heartbeat_timeout)
    coord.start()
    print(f"coordinator listening on {coord.address}", flush=True)
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        coord.shutdown()
    return 0


def _serve_multiprocess_plan(args) -> int:
    """`serve --multiprocess N` dry run: one serving process per rank on
    the distributed runtime's env contract (per-process telemetry
    suffixes ride it), each behind its own port — the serving twin of
    train's fleet plan. A front-end balances over the printed ports."""
    from deeplearning4j_tpu.distributed.launcher import (free_port,
                                                         launch_plan)

    base = [sys.executable, "-m", "deeplearning4j_tpu.cli"]
    scrubbed = _scrub_multiprocess_argv(args._raw_argv)
    # each rank serves its own port: strip any --port from the shared
    # argv and append the per-rank one
    core = []
    skip = False
    for tok in scrubbed:
        if skip:
            skip = False
            continue
        if tok == "--port":
            skip = True
            continue
        if tok.startswith("--port="):
            continue
        core.append(tok)
    coordinator = f"127.0.0.1:{free_port()}"
    print(f"# {args.multiprocess}-process serving fleet "
          f"(ports {args.port}..{args.port + args.multiprocess - 1}); "
          "run these lines from the repo root:")
    lines = []
    for i in range(args.multiprocess):
        plan = launch_plan(base + core + ["--port", str(args.port + i)],
                           args.multiprocess,
                           local_device_count=args.local_devices,
                           coordinator=coordinator)
        lines.append(plan[i])
    for line in lines + ["wait"]:
        print(line)
    return 0


def _parse_warmup_features(spec: str, sequence: bool):
    vals = [v.strip() for v in spec.split(",") if v.strip()]
    try:
        return np.asarray([int(v) for v in vals],
                          np.int32 if sequence else np.float32)
    except ValueError:
        return np.asarray([float(v) for v in vals], np.float32)


def _cmd_serve(args) -> int:
    from deeplearning4j_tpu.serving import (BucketLattice, InferenceEngine,
                                            ServingServer)
    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    if args.multiprocess:
        return _serve_multiprocess_plan(args)
    if bool(args.model) == bool(args.conf):
        raise SystemExit("serve needs exactly one of --model (a trained "
                         "zip) or --conf (a config JSON, optionally with "
                         "--checkpoint to resume params)")
    # fleet member (a printed --multiprocess plan line): bring up the
    # rendezvous contract so the per-process telemetry suffix and any
    # process-spanning placement are in effect before compiles
    from deeplearning4j_tpu.distributed import bootstrap

    if bootstrap.env_contract_present():
        bootstrap.initialize()
    if args.model:
        net = _load_model(args.model)
    else:
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
            MultiLayerConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with open(_fetch_input(args.conf)) as f:
            conf_json = f.read()
        if args.type == "computation_graph":
            net = ComputationGraph(
                ComputationGraphConfiguration.from_json(conf_json))
        else:
            net = MultiLayerNetwork(
                MultiLayerConfiguration.from_json(conf_json))
        net.init()
    lattice = BucketLattice.from_spec(args.buckets)
    if args.generate_slots > 0:
        from deeplearning4j_tpu.serving import GenerationEngine

        engine = GenerationEngine(
            net, lattice, slots=args.generate_slots,
            max_new_tokens=args.max_new_tokens,
            page_size=args.page_size,
            prefill_chunk=args.prefill_chunk,
            speculative_k=args.speculative_k, kv_dtype=args.kv_dtype,
            replicas=args.replicas, checkpoint=args.checkpoint,
            faults=args.chaos)
        n = engine.warmup()
        print(f"warmed {n} prefill/decode shapes")
    else:
        engine = InferenceEngine(net, lattice, replicas=args.replicas,
                                 max_wait_ms=args.max_wait_ms,
                                 sequence=args.sequence,
                                 checkpoint=args.checkpoint,
                                 faults=args.chaos)
        if args.warmup_features:
            n = engine.warmup(_parse_warmup_features(args.warmup_features,
                                                     args.sequence))
            print(f"warmed {n} bucket shapes")
    supervisor = watcher = None
    if args.autoscale_max or args.chaos:
        from deeplearning4j_tpu.serving import (AutoscalePolicy,
                                                FleetSupervisor)

        policy = None
        if args.autoscale_max:
            policy = AutoscalePolicy(min_replicas=args.replicas,
                                     max_replicas=args.autoscale_max)
        supervisor = FleetSupervisor(engine, policy=policy).run_in_thread()
        print("fleet supervisor up"
              + (f" (autoscale {args.replicas}..{args.autoscale_max})"
                 if policy else " (self-healing only)"), flush=True)
    if args.watch_checkpoint:
        if not args.checkpoint:
            raise SystemExit("--watch-checkpoint needs --checkpoint (the "
                             "directory the training fleet publishes to)")
        from deeplearning4j_tpu.serving import CheckpointWatcher

        watcher = CheckpointWatcher(engine, args.checkpoint).start()
        print(f"hot-swap watcher on {args.checkpoint}", flush=True)
    server = ServingServer(engine, port=args.port, host=args.host).start()
    print(f"serving on {server.url} "
          f"(replicas={args.replicas}, buckets={args.buckets}, "
          f"max-wait={args.max_wait_ms}ms"
          + (f", generate-slots={args.generate_slots}"
             if args.generate_slots > 0 else "")
          + (f", speculative-k={args.speculative_k}"
             if args.generate_slots > 0 and args.speculative_k >= 2 else "")
          + (f", kv-dtype={args.kv_dtype}"
             if args.generate_slots > 0 and args.kv_dtype != "f32" else "")
          + ")", flush=True)
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        print("draining...", flush=True)
        if watcher is not None:
            watcher.stop()
        if supervisor is not None:
            supervisor.stop()
        server.stop()
    return 0


def _predict_via_server(args, feats) -> "np.ndarray":
    """POST each row to a running `serve` instance; concurrent requests
    let the server's batcher coalesce them (order restored by index)."""
    import concurrent.futures
    import json as _json
    import urllib.request

    url = args.server.rstrip("/")

    def one(i):
        body = _json.dumps({"features": np.asarray(feats[i]).tolist(),
                            "id": f"cli-{i}"}).encode()
        req = urllib.request.Request(
            f"{url}/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            return _json.loads(resp.read())["output"]

    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        rows = list(pool.map(one, range(len(feats))))
    return np.asarray(rows, np.float32)


def _plan_profile(args):
    """Resolve `plan`'s model argument: a builtin pure-data profile
    (no jax import — the laptop-plans-a-pod path), a trained zip, or a
    config JSON built + profiled in-process."""
    from deeplearning4j_tpu.reshard.search import BUILTIN_PROFILES

    if bool(args.model) == bool(args.conf):
        raise SystemExit(
            "plan needs exactly one of --model (a builtin profile name: "
            f"{sorted(BUILTIN_PROFILES)}, or a trained zip) or --conf "
            "(a config JSON)")
    if args.model and args.model in BUILTIN_PROFILES:
        return BUILTIN_PROFILES[args.model]
    from deeplearning4j_tpu.reshard.search import profile_net

    if args.model:
        return profile_net(_load_model(args.model),
                           name=os.path.basename(args.model))
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration,
    )
    from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
        MultiLayerConfiguration,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with open(_fetch_input(args.conf)) as f:
        conf_json = f.read()
    if args.type == "computation_graph":
        net = ComputationGraph(
            ComputationGraphConfiguration.from_json(conf_json))
    else:
        net = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(conf_json))
    return profile_net(net.init(), name=os.path.basename(args.conf))


def _cmd_plan(args) -> int:
    """`plan --model --fleet` dry run: the ranked placement table with
    its score breakdown plus benchdiff-consumable PLAN metric lines
    (scores and search time are lower-is-better rows; the winner row
    carries the placement description for winner-change diffs)."""
    import json as _json
    import time

    from deeplearning4j_tpu.reshard.search import (
        FleetShape,
        Objective,
        SearchError,
        emit_search_event,
        search_placement,
    )
    from deeplearning4j_tpu.telemetry.artifact import build_summary

    profile = _plan_profile(args)
    try:
        fleet = FleetShape.parse(args.fleet)
    except ValueError as exc:
        raise SystemExit(f"plan: {exc}") from None
    obj_kwargs = {}
    if args.global_batch is not None:
        obj_kwargs["global_batch"] = args.global_batch
    if args.hbm_gb is not None:
        obj_kwargs["hbm_bytes_per_device"] = int(args.hbm_gb * (1 << 30))
    if args.no_zero1:
        obj_kwargs["zero1_options"] = (False,)
    objective = Objective(**obj_kwargs)
    t0 = time.perf_counter()
    try:
        result = search_placement(profile, fleet, objective=objective)
    except SearchError as exc:
        # "no feasible placement fits the HBM budget" and friends: a
        # refused plan is a usage error, never a traceback
        raise SystemExit(f"plan: {exc}") from None
    search_ms = round((time.perf_counter() - t0) * 1e3, 3)
    emit_search_event(result, path="cli", search_ms=search_ms)

    for line in result.table_lines(args.top):
        print(line)
    best = result.best
    lines = [
        {"metric": "plan_candidates", "value": len(result.candidates),
         "fleet": fleet.describe(), "profile": result.profile_name},
        {"metric": "plan_pruned", "value": len(result.pruned)},
        {"metric": "plan_winner_score", "value": float(best.score),
         "lower_is_better": True, "winner": best.describe(),
         "memory_bytes": float(best.memory_bytes),
         "collective_bytes": float(best.collective_bytes),
         "bubble_cost": float(best.bubble_cost),
         "idle_cost": float(best.idle_cost)},
        {"metric": "plan_search_ms", "value": search_ms,
         "lower_is_better": True},
    ]
    for c in result.candidates:
        lines.append({"metric": f"plan_score::{c.describe()}",
                      "value": float(c.score), "lower_is_better": True})
    out = [_json.dumps(line) for line in lines]
    out.append(_json.dumps(build_summary(lines)))
    for line in out:
        print(line)
    if args.artifact:
        with open(args.artifact, "w") as fh:
            fh.write("\n".join(out) + "\n")
        print(f"# wrote PLAN artifact to {args.artifact}")
    return 0


def _cmd_reshard(args) -> int:
    """`reshard --checkpoint --target-mesh` dry run: plan the
    checkpoint->mesh redistribution through reshard/planner.py and
    print it — per-action leaf counts, bytes moved vs the collective
    lower bound, and benchdiff-consumable metric lines (bytes_moved /
    plan_us are lower-is-better rows). Nothing moves: the planner is a
    pure function and no target devices are required."""
    import json as _json
    import time

    from deeplearning4j_tpu.reshard.executor import plan_for_placements
    from deeplearning4j_tpu.reshard.planner import Placement, PlacementError
    from deeplearning4j_tpu.telemetry.artifact import build_summary

    step_dir, meta = _load_checkpoint_meta(args.checkpoint, args.step)
    net = _net_from_checkpoint_config(step_dir, meta)
    src = (Placement.from_json(meta["placement"])
           if meta.get("placement") else Placement.solo())
    try:
        axes = _parse_mesh(args.target_mesh)
        dst = Placement.of(axes, {r: r for r in axes},
                           process_count=args.processes, zero1=args.zero1)
        t0 = time.perf_counter()
        plan, _, _ = plan_for_placements(net, src, dst)
    except PlacementError as exc:
        # the planner refuses (target-mesh-larger-than-checkpoint and
        # friends) BEFORE anything moves — surface it as a usage error
        raise SystemExit(f"reshard: {exc}") from None
    plan_us = round((time.perf_counter() - t0) * 1e6, 1)

    s = plan.summary()
    print(f"# reshard plan: {s['src']} -> {s['dst']} "
          f"(step {meta.get('iteration')})")
    for action, n in sorted(s["actions"].items()):
        moved = sum(l.bytes_moved for l in plan.leaves
                    if l.action == action)
        print(f"#   {action:<16} {n:>4} leaves  {moved:>12} bytes")
    lines = [
        {"metric": "reshard_plan_leaves", "value": s["n_leaves"]},
        {"metric": "reshard_bytes_total", "value": s["bytes_total"]},
        {"metric": "reshard_bytes_moved", "value": s["bytes_moved"],
         "lower_is_better": True},
        {"metric": "reshard_bytes_lower_bound",
         "value": s["bytes_lower_bound"], "lower_is_better": True},
        {"metric": "reshard_plan_us", "value": plan_us,
         "lower_is_better": True},
    ]
    out = [_json.dumps(line) for line in lines]
    out.append(_json.dumps(build_summary(lines)))
    for line in out:
        print(line)
    if args.artifact:
        with open(args.artifact, "w") as fh:
            fh.write("\n".join(out) + "\n")
        print(f"# wrote RESHARD artifact to {args.artifact}")
    return 0


def _load_checkpoint_meta(directory: str, step):
    """(step_dir, meta dict) for the latest (or named) committed step."""
    import json as _json

    steps = sorted(
        int(d.split("_", 1)[1]) for d in os.listdir(directory)
        if d.startswith("step_") and d.split("_", 1)[1].isdigit()
        and os.path.exists(os.path.join(directory, d, "meta.json")))
    if not steps:
        raise SystemExit(f"no committed checkpoints under {directory}")
    if step is None:
        step = steps[-1]
    elif step not in steps:
        raise SystemExit(f"no checkpoint for step {step} (have {steps})")
    step_dir = os.path.join(directory, f"step_{step}")
    with open(os.path.join(step_dir, "meta.json")) as fh:
        return step_dir, _json.load(fh)


def _net_from_checkpoint_config(step_dir: str, meta: dict):
    """Rebuild the checkpointed net (init'd, for leaf shapes only) from
    the step's config.json; meta's `kind` picks the container."""
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration,
    )
    from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
        MultiLayerConfiguration,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with open(os.path.join(step_dir, "config.json")) as fh:
        conf_json = fh.read()
    if meta.get("kind") == "ComputationGraph":
        net = ComputationGraph(
            ComputationGraphConfiguration.from_json(conf_json))
    else:
        net = MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json))
    return net.init()


def _cmd_test(args) -> int:
    net = _load_model(args.model)
    it = _make_iterator(args)
    ev = net.evaluate(it)
    print(ev.stats())
    return 0


def _cmd_predict(args) -> int:
    from deeplearning4j_tpu.datasets.records import (
        CSVRecordReader,
        SVMLightRecordReader,
    )

    if bool(args.model) == bool(args.server):
        raise SystemExit("predict needs exactly one of --model (load "
                         "in-process) or --server URL (a running `serve` "
                         "instance)")
    # prediction input has no label column: every CSV value is a feature
    # (svmlight rows still carry a label field; it is ignored)
    if args.format == "svmlight":
        if args.num_features <= 0:
            raise SystemExit("--num-features is required for svmlight input")
        feats = [f for _, f in SVMLightRecordReader(args.input,
                                                    args.num_features)]
    else:
        feats = [np.asarray([float(v) for v in rec], np.float32)
                 for rec in CSVRecordReader(args.input)]
    x = np.stack(feats)
    if args.server:
        preds = _predict_via_server(args, x)
    else:
        net = _load_model(args.model)
        rows = []
        for s in range(0, len(x), args.batch):
            rows.append(np.asarray(net.output(x[s:s + args.batch])))
        preds = np.concatenate(rows)
    with open(args.output, "w") as f:
        for row in preds:
            f.write(",".join(f"{v:.8g}" for v in np.atleast_1d(row)) + "\n")
    print(f"wrote {len(preds)} predictions to {args.output}")
    return 0


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    # the tokens behind this parse — what a --multiprocess plan re-emits
    args._raw_argv = list(sys.argv[1:] if argv is None else argv)
    return {"train": _cmd_train, "test": _cmd_test,
            "predict": _cmd_predict, "serve": _cmd_serve,
            "reshard": _cmd_reshard, "plan": _cmd_plan,
            "coordinator": _cmd_coordinator}[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
