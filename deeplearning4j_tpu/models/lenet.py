"""LeNet-5 on MNIST (BASELINE.json config 1) via the sequential builder API —
the minimum end-to-end slice model (SURVEY.md §7 step 4)."""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import (
    ConvolutionLayer,
    DenseLayer,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    SubsamplingLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def lenet5(seed: int = 12345, learning_rate: float = 1e-3,
           updater: str = Updater.ADAM, dtype: str = "float32") -> MultiLayerNetwork:
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(updater)
        .weight_init("xavier")
        .dtype(dtype)
        .list()
        .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5), stride=(1, 1),
                                activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5), stride=(1, 1),
                                activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=500, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax", loss_function="mcxent"))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build()
    )
    return MultiLayerNetwork(conf)
