"""6-layer Transformer LM (BASELINE.json config 5 / north star) via the DAG
builder API — the structural successor of the reference's ComputationGraph
wiring (SURVEY.md §3.2: "attention blocks = new vertex/layer types in the
DAG"). Pre-norm blocks:

  x → Embedding → +PosEnc → [LN → MHSA → +res → LN → FF(gelu) → FF → +res]×L
    → LN → RnnOutput(softmax, mcxent over vocab)

Designed MXU-first: one fused QKV matmul per block, head_dim >= 64 by
default (the 128-wide MXU wastes 3/4 of its lanes at head_dim 32 — measured
2.4x step-time difference on v5e), bf16-ready via the config dtype policy,
remat-able via .remat(True) for long sequences. Attention uses the fused
Pallas flash kernel for long sequences (ops/flash_attention.py) and XLA's
fused dense softmax below MIN_FLASH_SEQ.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    ElementWiseVertexConf,
    EmbeddingLayer,
    InputType,
    LayerNormalization,
    NeuralNetConfiguration,
    RnnOutputLayer,
    SelfAttentionLayer,
    Updater,
)
from deeplearning4j_tpu.nn.conf.layers import PositionalEncodingLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph


def _build_lm(vocab_size, d_model, n_heads, n_layers, max_length, dropout,
              seed, learning_rate, dtype, remat, ff_builder,
              seq_parallel_axis="",
              attention_dropout=None) -> ComputationGraph:
    """Shared pre-norm LM skeleton; `ff_builder(g, name, input_name)` adds
    the per-block feed-forward sublayer(s) and returns the output name —
    the dense and MoE variants differ only there."""
    g = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(Updater.ADAM)
        .weight_init("xavier")
        .dtype(dtype)
        .remat(remat)
        .graph_builder()
        .add_inputs("tokens")
    )
    g.add_layer("embed", EmbeddingLayer(n_in=vocab_size, n_out=d_model,
                                        activation="identity", has_bias=False),
                "tokens")
    g.add_layer("posenc", PositionalEncodingLayer(
        max_length=max_length, n_features=d_model,
        seq_parallel_axis=seq_parallel_axis), "embed")
    prev = "posenc"
    for i in range(n_layers):
        b = f"blk{i}"
        g.add_layer(f"{b}_ln1", LayerNormalization(n_in=d_model, n_out=d_model),
                    prev)
        # attention dropout rides every fused path since r6 — including
        # ring attention under sequence parallelism (the in-kernel keep
        # mask hashes GLOBAL sequence coordinates, so each shard drops
        # exactly its window of the single-chip mask)
        g.add_layer(f"{b}_attn", SelfAttentionLayer(
            n_in=d_model, n_out=d_model, n_heads=n_heads, causal=True,
            dropout=dropout,
            attention_dropout=(dropout if attention_dropout is None
                               else attention_dropout),
            activation="identity",
            seq_parallel_axis=seq_parallel_axis), f"{b}_ln1")
        g.add_vertex(f"{b}_res1", ElementWiseVertexConf(op="add"),
                     prev, f"{b}_attn")
        g.add_layer(f"{b}_ln2", LayerNormalization(n_in=d_model, n_out=d_model),
                    f"{b}_res1")
        ff_out = ff_builder(g, b, f"{b}_ln2")
        g.add_vertex(f"{b}_res2", ElementWiseVertexConf(op="add"),
                     f"{b}_res1", ff_out)
        prev = f"{b}_res2"
    g.add_layer("ln_f", LayerNormalization(n_in=d_model, n_out=d_model), prev)
    g.add_layer("out", RnnOutputLayer(n_in=d_model, n_out=vocab_size,
                                      activation="softmax",
                                      loss_function="mcxent"), "ln_f")
    g.set_outputs("out")
    g.set_input_types(tokens=InputType.recurrent(1))
    return ComputationGraph(g.build())


def transformer_lm(vocab_size: int = 10000, d_model: int = 256,
                   n_heads: int = 4, n_layers: int = 6, d_ff: int = 1024,
                   max_length: int = 512, dropout: float = 0.0,
                   seed: int = 12345, learning_rate: float = 3e-4,
                   dtype: str = "float32", remat: bool = False,
                   seq_parallel_axis: str = "",
                   attention_dropout: float = None) -> ComputationGraph:
    """seq_parallel_axis: name of a mesh axis to shard TIME over — builds
    an SP-ready config for parallel/sequence_parallel.py (ring attention +
    position-offset encodings inside shard_map). attention_dropout
    overrides the attention-weight dropout independently of the
    input/FF `dropout` (None: follow it)."""
    def ff(g, b, src):
        g.add_layer(f"{b}_ff1", DenseLayer(n_in=d_model, n_out=d_ff,
                                           activation="gelu", dropout=dropout),
                    src)
        g.add_layer(f"{b}_ff2", DenseLayer(n_in=d_ff, n_out=d_model,
                                           activation="identity"), f"{b}_ff1")
        return f"{b}_ff2"

    return _build_lm(vocab_size, d_model, n_heads, n_layers, max_length,
                     dropout, seed, learning_rate, dtype, remat, ff,
                     seq_parallel_axis=seq_parallel_axis,
                     attention_dropout=attention_dropout)


def transformer_moe_lm(vocab_size: int = 10000, d_model: int = 256,
                       n_heads: int = 4, n_layers: int = 6,
                       n_experts: int = 8, top_k: int = 2,
                       d_expert_hidden: int = 512, max_length: int = 512,
                       dropout: float = 0.0, seed: int = 12345,
                       learning_rate: float = 3e-4, dtype: str = "float32",
                       remat: bool = False, routing: str = "routed",
                       capacity_factor: float = 1.25) -> ComputationGraph:
    """Mixture-of-Experts LM: each block's dense FF replaced by a top-k
    gated expert FFN (nn/layers/moe.py; dropout applies to the expert
    input like the dense variant's first FF layer). Experts shard over a
    mesh 'expert' axis for EP execution; routing="routed" (default) uses
    capacity-factor token dispatch, "dense" the compute-all oracle."""
    from deeplearning4j_tpu.nn.layers.moe import MixtureOfExpertsLayer

    def ff(g, b, src):
        g.add_layer(f"{b}_moe", MixtureOfExpertsLayer(
            n_in=d_model, n_out=d_model, n_experts=n_experts, top_k=top_k,
            d_hidden=d_expert_hidden, activation="gelu", dropout=dropout,
            routing=routing, capacity_factor=capacity_factor),
            src)
        return f"{b}_moe"

    return _build_lm(vocab_size, d_model, n_heads, n_layers, max_length,
                     dropout, seed, learning_rate, dtype, remat, ff)


def transformer_flops_per_token(vocab_size, d_model, n_layers, d_ff, seq_len,
                                attention_factor=1.0):
    """Analytic forward+backward FLOPs per token for MFU accounting
    (backward ≈ 2x forward). The attention quadratic term is counted on
    the FULL [T, T] matrix (the dense-accounted convention most MFU
    quotes use); `attention_factor` scales it — see
    transformer_flops_per_token_executed."""
    per_layer = (
        4 * 2 * d_model * d_model  # qkv + out proj: 4 [d,d] matmuls, 2dd each
        + 2 * 2 * d_model * d_ff  # two FF matmuls
        + attention_factor * 2 * 2 * seq_len * d_model  # qk^T and attn@v
    )
    fwd = n_layers * per_layer + 2 * d_model * vocab_size  # + LM head
    return int(3 * fwd)  # fwd + bwd(2x)


def causal_attention_factor(seq_len: int) -> float:
    """Executed fraction of the dense [T, T] attention matrix under a
    causal mask: T(T+1)/2 visible (query, key) pairs out of T*T —
    (T+1)/(2T), approaching 1/2 from above as T grows. The exact pair
    count, not the 0.5 approximation (VERDICT r5 #4 asked for the
    honest number; at T=512 the two differ by ~0.1% of the attention
    term, at 32k by ~0.003%)."""
    return (seq_len + 1) / (2.0 * seq_len)


def transformer_flops_per_token_executed(vocab_size, d_model, n_layers,
                                         d_ff, seq_len, causal=True):
    """FLOPs per token counting only work the kernels EXECUTE (VERDICT
    r5 #4): the causal flash kernels iterate key blocks to the diagonal
    (ops/flash_attention.py `hi = qi*block_q//block_k + 1`) and the
    chunked loop skips above-diagonal tile pairs outright, so the dense
    convention credits ~2x the attention work that runs. The attention
    term is counted at exactly T(T+1)/2 causal pairs
    (`causal_attention_factor`). At seq 512 the dense convention
    inflates MFU ~12%; at seq 32k attention dominates and the inflation
    approaches 2x — `mfu_executed` derived from this is the number
    comparable to the hardware's causal-attention roofline. (The
    executed diagonal tiles' masked upper halves slightly over-count
    the skip, <= one block's worth — the kernels run marginally MORE
    than this count, so the executed MFU is conservative.)"""
    return transformer_flops_per_token(
        vocab_size, d_model, n_layers, d_ff, seq_len,
        attention_factor=causal_attention_factor(seq_len) if causal
        else 1.0)


def transformer_moe_flops_per_token(vocab_size, d_model, n_layers,
                                    n_experts, top_k, d_expert_hidden,
                                    seq_len):
    """Analytic fwd+bwd FLOPs per token for the MoE LM: the dense FF term
    becomes top_k expert FFNs + the router matmul. USEFUL flops only —
    capacity-buffer zero padding is the implementation's overhead, not
    model compute, so the MFU derived from this is honest about it."""
    per_layer = (
        4 * 2 * d_model * d_model
        + top_k * 2 * 2 * d_model * d_expert_hidden  # k routed expert FFNs
        + 2 * d_model * n_experts                    # router logits
        + 2 * 2 * seq_len * d_model
    )
    fwd = n_layers * per_layer + 2 * d_model * vocab_size
    return 3 * fwd


