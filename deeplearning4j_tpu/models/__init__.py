"""Model zoo: the BASELINE.json benchmark configs expressed in the builder
API (LeNet-5/MNIST, VGG-16/CIFAR-10, ResNet-20 DP, 6-layer Transformer LM)."""

from deeplearning4j_tpu.models.lenet import lenet5  # noqa: F401
from deeplearning4j_tpu.models.vgg import vgg16  # noqa: F401
from deeplearning4j_tpu.models.resnet import resnet20  # noqa: F401
from deeplearning4j_tpu.models.transformer import transformer_lm  # noqa: F401
