"""ResNet-20 for CIFAR-10 (BASELINE.json config 4: the data-parallel
benchmark model) via the DAG API — residual adds are ElementWiseVertex(add),
the structural feature the reference's ComputationGraph provides
(nn/conf/graph/ElementWiseVertex)."""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    ElementWiseVertexConf,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    SubsamplingLayer,
    Updater,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph


def resnet20(num_classes: int = 10, seed: int = 12345,
             learning_rate: float = 1e-3, dtype: str = "float32") -> ComputationGraph:
    g = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(Updater.ADAM)
        .weight_init("relu")
        .dtype(dtype)
        .graph_builder()
        .add_inputs("input")
    )
    g.add_layer("conv0", ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                          convolution_mode="same",
                                          activation="identity"), "input")
    g.add_layer("bn0", BatchNormalization(activation="relu"), "conv0")
    prev = "bn0"
    widths = [16, 16, 16, 32, 32, 32, 64, 64, 64]
    for i, w in enumerate(widths):
        stride = 2 if i in (3, 6) else 1  # downsample at stage boundaries
        base = f"b{i}"
        g.add_layer(f"{base}_conv1", ConvolutionLayer(
            n_out=w, kernel_size=(3, 3), stride=(stride, stride),
            convolution_mode="same", activation="identity"), prev)
        g.add_layer(f"{base}_bn1", BatchNormalization(activation="relu"),
                    f"{base}_conv1")
        g.add_layer(f"{base}_conv2", ConvolutionLayer(
            n_out=w, kernel_size=(3, 3), convolution_mode="same",
            activation="identity"), f"{base}_bn1")
        g.add_layer(f"{base}_bn2", BatchNormalization(activation="identity"),
                    f"{base}_conv2")
        shortcut = prev
        if stride != 1 or i == 0:
            # 1x1 projection shortcut when shape changes
            g.add_layer(f"{base}_proj", ConvolutionLayer(
                n_out=w, kernel_size=(1, 1), stride=(stride, stride),
                convolution_mode="same", activation="identity"), prev)
            shortcut = f"{base}_proj"
        g.add_vertex(f"{base}_add", ElementWiseVertexConf(op="add"),
                     f"{base}_bn2", shortcut)
        g.add_layer(f"{base}_relu", ActivationLayer(activation="relu"),
                    f"{base}_add")
        prev = f"{base}_relu"
    # global average pool via an 8x8 AVG subsampling (input 32x32 → 8x8 here)
    g.add_layer("gap", SubsamplingLayer(pooling_type="avg", kernel_size=(8, 8),
                                        stride=(8, 8)), prev)
    g.add_layer("fc", DenseLayer(n_out=64, activation="relu"), "gap")
    g.add_layer("out", OutputLayer(n_out=num_classes, activation="softmax",
                                   loss_function="mcxent"), "fc")
    g.set_outputs("out")
    g.set_input_types(input=InputType.convolutional(32, 32, 3))
    return ComputationGraph(g.build())
