"""VGG-16 on CIFAR-10 (BASELINE.json config 2) via the DAG builder API —
exercises conv/BN/pooling op coverage on the ComputationGraph container."""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    SubsamplingLayer,
    Updater,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph

_CFG = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
        512, 512, 512, "M"]


def vgg16(num_classes: int = 10, image_size: int = 32, seed: int = 12345,
          learning_rate: float = 1e-3, batch_norm: bool = True,
          dtype: str = "float32") -> ComputationGraph:
    g = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(learning_rate)
        .updater(Updater.ADAM)
        .weight_init("relu")
        .dtype(dtype)
        .graph_builder()
        .add_inputs("input")
    )
    prev = "input"
    i = 0
    for v in _CFG:
        if v == "M":
            name = f"pool{i}"
            g.add_layer(name, SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
                        prev)
        else:
            name = f"conv{i}"
            g.add_layer(name, ConvolutionLayer(
                n_out=v, kernel_size=(3, 3), stride=(1, 1),
                convolution_mode="same", activation="relu"), prev)
            if batch_norm:
                bn = f"bn{i}"
                g.add_layer(bn, BatchNormalization(), name)
                name = bn
        prev = name
        i += 1
    g.add_layer("fc1", DenseLayer(n_out=512, activation="relu"), prev)
    g.add_layer("out", OutputLayer(n_out=num_classes, activation="softmax",
                                   loss_function="mcxent"), "fc1")
    g.set_outputs("out")
    g.set_input_types(input=InputType.convolutional(image_size, image_size, 3))
    return ComputationGraph(g.build())
