"""Exact t-SNE, on-device (reference: plot/Tsne.java — calculate():72,
per-iteration gains/momentum update :88-151, binary-search x2p():238).

TPU-first design: the entire iteration — Student-t affinities over all
pairs, gradient, gains, momentum — is one jitted step over [N, 2] arrays;
the host loop only counts iterations and flips the early-exaggeration /
momentum-switch scalars, which enter the step as traced args so one compiled
program serves all phases. The perplexity binary search (x2p) is a
vectorised fori_loop: every row's beta search step runs in lockstep on
device instead of the reference's per-row Java loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _x2p(x, perplexity, iters=50):
    """Conditional gaussian affinities P(j|i) with per-row variance found by
    binary search on entropy (Tsne.java x2p:238). Vectorised: all rows
    search concurrently; a fixed 50 bisection steps halves the bracket to
    well below the reference's 1e-5 tolerance, with no data-dependent exit
    to break the jit."""
    n = x.shape[0]
    sum_x = jnp.sum(x * x, axis=1)
    d2 = jnp.maximum(sum_x[:, None] + sum_x[None, :] - 2.0 * x @ x.T, 0.0)
    log_u = jnp.log(perplexity)
    eye = jnp.eye(n, dtype=bool)

    def entropy_and_p(beta):
        logits = -d2 * beta[:, None]
        logits = jnp.where(eye, -jnp.inf, logits)
        p = jax.nn.softmax(logits, axis=1)
        # Shannon entropy H = -sum p log p (natural log, as the reference)
        h = -jnp.sum(jnp.where(p > 0, p * jnp.log(p + 1e-30), 0.0), axis=1)
        return h, p

    def body(_, carry):
        beta, lo, hi = carry
        h, _ = entropy_and_p(beta)
        too_high = h > log_u          # entropy too high → beta too small
        new_lo = jnp.where(too_high, beta, lo)
        new_hi = jnp.where(too_high, hi, beta)
        new_beta = jnp.where(
            too_high,
            jnp.where(jnp.isinf(new_hi), beta * 2.0, (beta + new_hi) / 2.0),
            (new_lo + beta) / 2.0,
        )
        return new_beta, new_lo, new_hi

    beta0 = jnp.ones((n,))
    lo0 = jnp.zeros((n,))
    hi0 = jnp.full((n,), jnp.inf)
    beta, _, _ = jax.lax.fori_loop(0, iters, body, (beta0, lo0, hi0))
    _, p = entropy_and_p(beta)
    return p


@jax.jit
def _tsne_step(y, iy, gains, p, p_report, momentum, min_gain, learning_rate):
    """One t-SNE gradient step with the reference's gains/momentum scheme
    (Tsne.java:124-151). `p` drives the gradient (may be early-exaggerated);
    `p_report` is the true P so reported KL is comparable across the lying
    phase boundary."""
    n = y.shape[0]
    sum_y = jnp.sum(y * y, axis=1)
    num = 1.0 / (1.0 + sum_y[:, None] + sum_y[None, :] - 2.0 * y @ y.T)
    num = num * (1.0 - jnp.eye(n))
    q = jnp.maximum(num / jnp.sum(num), 1e-12)
    pq = (p - q) * num                                   # [N,N]
    dy = 4.0 * (jnp.diag(jnp.sum(pq, axis=1)) - pq) @ y   # KL gradient
    # gains: shrink where gradient keeps the velocity's sign, grow otherwise
    same_sign = jnp.sign(dy) == jnp.sign(iy)
    gains = jnp.where(same_sign, gains * 0.8, gains + 0.2)
    gains = jnp.maximum(gains, min_gain)
    iy = momentum * iy - learning_rate * (gains * dy)
    y = y + iy
    y = y - jnp.mean(y, axis=0, keepdims=True)
    kl = jnp.sum(jnp.where(p_report > 0, p_report * jnp.log(p_report / q), 0.0))
    return y, iy, gains, kl


class Tsne:
    """Exact t-SNE (plot/Tsne.java builder surface: maxIter, perplexity,
    learningRate, stopLyingIteration, momentum switch at iter 20)."""

    def __init__(self, max_iter: int = 1000, perplexity: float = 30.0,
                 learning_rate: float = 500.0, initial_momentum: float = 0.5,
                 final_momentum: float = 0.8, momentum_switch: int = 20,
                 stop_lying_iteration: int = 250, exaggeration: float = 4.0,
                 min_gain: float = 0.01, seed: int = 0):
        self.max_iter = max_iter
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.momentum_switch = momentum_switch
        self.stop_lying_iteration = stop_lying_iteration
        self.exaggeration = exaggeration
        self.min_gain = min_gain
        self.seed = seed
        self.kl_history: list[float] = []

    def calculate(self, x, target_dimensions: int = 2,
                  perplexity: float | None = None) -> np.ndarray:
        """Embed x [N, D] → [N, target_dimensions] (Tsne.calculate:72)."""
        x = jnp.asarray(np.asarray(x, dtype=np.float32))
        n = x.shape[0]
        perp = self.perplexity if perplexity is None else perplexity
        p = _x2p(x, perp)
        p = (p + p.T) / (2.0 * n)                 # symmetrise + normalise
        p = jnp.maximum(p, 1e-12)

        key = jax.random.PRNGKey(self.seed)
        y = jax.random.normal(key, (n, target_dimensions)) * 1e-4
        iy = jnp.zeros_like(y)
        gains = jnp.ones_like(y)

        self.kl_history = []
        for i in range(self.max_iter):
            momentum = (self.initial_momentum if i < self.momentum_switch
                        else self.final_momentum)
            lying = i < self.stop_lying_iteration
            p_eff = p * self.exaggeration if lying else p
            y, iy, gains, kl = _tsne_step(
                y, iy, gains, p_eff, p, momentum, self.min_gain,
                self.learning_rate)
            if (i + 1) % 50 == 0:
                self.kl_history.append(float(kl))
        return np.asarray(y)

    # reference alias (Tsne.plot → calculate)
    def fit_transform(self, x, target_dimensions: int = 2) -> np.ndarray:
        return self.calculate(x, target_dimensions)
