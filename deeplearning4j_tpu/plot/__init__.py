"""Embedding visualisation (reference: deeplearning4j-core
`org/deeplearning4j/plot/` — Tsne.java, BarnesHutTsne.java).

Exact t-SNE runs fully on device as a jitted update loop (all-pairs
affinities are dense matmul-shaped work the MXU eats); Barnes-Hut t-SNE uses
the host-side SpTree for its O(N log N) force approximation, matching the
reference's split between Tsne and BarnesHutTsne.
"""

from .tsne import Tsne
from .barnes_hut_tsne import BarnesHutTsne

__all__ = ["Tsne", "BarnesHutTsne"]
