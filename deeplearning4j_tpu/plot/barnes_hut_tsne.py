"""Barnes-Hut t-SNE (reference: plot/BarnesHutTsne.java — implements Model;
sparse input affinities via k-NN + per-row beta search, SpTree-accelerated
gradient :310, fit():435-474).

Host-side by design: Barnes-Hut's pruned tree traversal is irregular,
data-dependent control flow that XLA cannot tile — the same reason the
reference keeps it on the CPU heap. The O(N·u) k-NN affinity construction is
vectorised NumPy; use the exact `Tsne` class when N is small enough to
prefer the all-pairs on-device path.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..clustering.sptree import SpTree


def _knn_affinities(x: np.ndarray, perplexity: float, k: int,
                    tol: float = 1e-5, iters: int = 50):
    """Sparse conditional affinities over each row's k nearest neighbours
    (BarnesHutTsne.computeGaussianPerplexity). Returns CSR (rows, cols,
    vals).

    k-NN runs in row blocks with argpartition so peak memory is
    O(block * N), never a full dense [N, N] matrix — the whole point of the
    Barnes-Hut path is N too large for the exact all-pairs code.
    """
    n = x.shape[0]
    sum_x = np.sum(x * x, axis=1)
    block = max(1, min(n, (1 << 26) // max(n, 1)))       # ~512MB f64 cap
    nbr = np.empty((n, k), dtype=np.int64)
    nd2 = np.empty((n, k), dtype=np.float64)
    for s in range(0, n, block):
        e = min(s + block, n)
        d2 = np.maximum(sum_x[s:e, None] + sum_x[None, :]
                        - 2.0 * x[s:e] @ x.T, 0.0)       # [b, N]
        d2[np.arange(e - s), np.arange(s, e)] = np.inf
        part = np.argpartition(d2, k - 1, axis=1)[:, :k]
        pd2 = np.take_along_axis(d2, part, axis=1)
        order = np.argsort(pd2, axis=1)
        nbr[s:e] = np.take_along_axis(part, order, axis=1)
        nd2[s:e] = np.take_along_axis(pd2, order, axis=1)

    log_u = np.log(perplexity)
    beta = np.ones(n)
    lo = np.zeros(n)
    hi = np.full(n, np.inf)
    for _ in range(iters):
        logits = -nd2 * beta[:, None]
        logits -= logits.max(axis=1, keepdims=True)
        e = np.exp(logits)
        p = e / e.sum(axis=1, keepdims=True)
        h = -np.sum(np.where(p > 0, p * np.log(p + 1e-30), 0.0), axis=1)
        diff = h - log_u
        if np.all(np.abs(diff) < tol):
            break
        too_high = diff > 0
        lo = np.where(too_high, beta, lo)
        hi = np.where(too_high, hi, beta)
        beta = np.where(
            too_high,
            np.where(np.isinf(hi), beta * 2.0, (beta + hi) / 2.0),
            (lo + beta) / 2.0,
        )
    logits = -nd2 * beta[:, None]
    logits -= logits.max(axis=1, keepdims=True)
    e = np.exp(logits)
    p = e / e.sum(axis=1, keepdims=True)

    rows = np.arange(0, n * k + 1, k)
    return rows, nbr.reshape(-1), p.reshape(-1)


def _symmetrize_csr(rows, cols, vals, n):
    """P = (P + Pᵀ) / (2N) on the sparse structure
    (BarnesHutTsne symmetrized affinity). Edges are bucketed per row so the
    whole pass is O(N·k), not a global-dict scan per row."""
    per_row: list[dict] = [{} for _ in range(n)]
    for i in range(n):
        for idx in range(rows[i], rows[i + 1]):
            j = int(cols[idx])
            v = float(vals[idx])
            per_row[i][j] = per_row[i].get(j, 0.0) + v
            per_row[j][i] = per_row[j].get(i, 0.0) + v
    total = 2.0 * n
    out_rows = [0]
    out_cols: list[int] = []
    out_vals: list[float] = []
    for i in range(n):
        for j in sorted(per_row[i]):
            out_cols.append(j)
            out_vals.append(per_row[i][j] / total)
        out_rows.append(len(out_cols))
    return (np.asarray(out_rows), np.asarray(out_cols),
            np.asarray(out_vals, dtype=np.float64))


class BarnesHutTsne:
    """θ-approximate t-SNE (plot/BarnesHutTsne.java: theta default 0.5,
    fit():435; gradient():310 = edge forces − non-edge forces / sumQ)."""

    def __init__(self, max_iter: int = 1000, perplexity: float = 30.0,
                 theta: float = 0.5, learning_rate: float = 200.0,
                 initial_momentum: float = 0.5, final_momentum: float = 0.8,
                 momentum_switch: int = 250,
                 stop_lying_iteration: int = 250, exaggeration: float = 12.0,
                 min_gain: float = 0.01, seed: int = 0):
        self.max_iter = max_iter
        self.perplexity = perplexity
        self.theta = theta
        self.learning_rate = learning_rate
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.momentum_switch = momentum_switch
        self.stop_lying_iteration = stop_lying_iteration
        self.exaggeration = exaggeration
        self.min_gain = min_gain
        self.seed = seed
        self.y: Optional[np.ndarray] = None
        self.kl_divergences: list[float] = []

    def _gradient(self, y, rows, cols, vals):
        tree = SpTree(y)
        pos_f = tree.compute_edge_forces(rows, cols, vals)
        neg_f = np.zeros_like(y)
        sum_q = 0.0
        for i in range(len(y)):
            f = np.zeros(y.shape[1])
            sum_q += tree.compute_non_edge_forces(i, self.theta, f)
            neg_f[i] = f
        return pos_f - neg_f / max(sum_q, 1e-12)

    def fit(self, x, target_dimensions: int = 2) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        n = x.shape[0]
        k = min(int(3 * self.perplexity), n - 1)
        rows, cols, vals = _knn_affinities(x, self.perplexity, k)
        rows, cols, vals = _symmetrize_csr(rows, cols, vals, n)

        rng = np.random.default_rng(self.seed)
        y = rng.normal(scale=1e-4, size=(n, target_dimensions))
        iy = np.zeros_like(y)
        gains = np.ones_like(y)

        self.kl_divergences = []
        for i in range(self.max_iter):
            lying = i < self.stop_lying_iteration
            v = vals * self.exaggeration if lying else vals
            dy = self._gradient(y, rows, cols, v)
            momentum = (self.initial_momentum if i < self.momentum_switch
                        else self.final_momentum)
            same_sign = np.sign(dy) == np.sign(iy)
            gains = np.where(same_sign, gains * 0.8, gains + 0.2)
            gains = np.maximum(gains, self.min_gain)
            iy = momentum * iy - self.learning_rate * (gains * dy)
            y = y + iy
            y -= y.mean(axis=0, keepdims=True)
        self.y = y
        return y

    # reference naming (BarnesHutTsne implements Model → getData)
    def get_data(self) -> Optional[np.ndarray]:
        return self.y
