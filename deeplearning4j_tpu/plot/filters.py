"""Filter-weight visualization (reference plot/PlotFilters.java and
plot/iterationlistener/PlotFiltersIterationListener.java).

Tiles learned filters into one image grid (the Krizhevsky-style weight
plot): 2D input [n_filters, n_pixels] (e.g. a transposed dense/RBM W) or
4D input [n_filters, h, w, channels] (this framework's NHWC conv kernels
reshaped filter-major). Vectorized numpy — the reference's per-tile
put/get loop becomes one reshape/transpose."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

_EPS = 1e-12


def scale(arr: np.ndarray) -> np.ndarray:
    """Min-max scale to [0, 1] (reference PlotFilters.scale)."""
    arr = arr - arr.min()
    return arr / (arr.max() + _EPS)


class PlotFilters:
    def __init__(self, input_array: np.ndarray,
                 tile_shape: Sequence[int],
                 tile_spacing: Sequence[int] = (0, 0),
                 image_shape: Optional[Sequence[int]] = None,
                 scale_rows_to_interval: bool = True,
                 output_pixels: bool = True):
        self.input = np.asarray(input_array)
        self.tile_shape = tuple(tile_shape)
        self.tile_spacing = tuple(tile_spacing)
        if image_shape is None:
            if self.input.ndim < 3:
                raise ValueError(
                    "image_shape required for 2D input (rows are flat)")
            image_shape = self.input.shape[1:3]
        self.image_shape = tuple(image_shape)
        self.scale_rows_to_interval = scale_rows_to_interval
        self.output_pixels = output_pixels
        self._plot: Optional[np.ndarray] = None

    def _tiles(self) -> np.ndarray:
        """[n, h, w] stack of per-filter images."""
        x = self.input
        h, w = self.image_shape
        if x.ndim == 2:
            tiles = x.reshape(-1, h, w)
        elif x.ndim == 4:
            # NHWC filters: average channels for the grayscale grid
            tiles = x.mean(axis=-1).reshape(-1, h, w)
        elif x.ndim == 3:
            tiles = x.reshape(-1, h, w)
        else:
            raise ValueError(f"unsupported input rank {x.ndim}")
        return tiles.astype(np.float64)

    def plot(self) -> np.ndarray:
        th, tw = self.tile_shape
        hs, ws = self.tile_spacing
        h, w = self.image_shape
        out_shape = ((h + hs) * th - hs, (w + ws) * tw - ws)
        out = np.zeros(out_shape, np.float64)
        tiles = self._tiles()
        for idx in range(min(len(tiles), th * tw)):
            r, c = divmod(idx, tw)
            img = tiles[idx]
            if self.scale_rows_to_interval:
                img = scale(img)
            if self.output_pixels:
                img = img * 255.0
            out[r * (h + hs):r * (h + hs) + h,
                c * (w + ws):c * (w + ws) + w] = img
        self._plot = out
        return out

    def get_plot(self) -> np.ndarray:
        if self._plot is None:
            raise RuntimeError("call plot() first")
        return self._plot


class PlotFiltersIterationListener:
    """Renders a layer's weights every `frequency` iterations (reference
    plot/iterationlistener/PlotFiltersIterationListener.java). The latest
    grid is kept on the listener and optionally written as .npy so any
    host tool (or the UI standalone page) can display it."""

    def __init__(self, layer_name: str, tile_shape: Tuple[int, int] = (10, 10),
                 image_shape: Optional[Tuple[int, int]] = None,
                 frequency: int = 10, output_path: Optional[str] = None):
        self.layer_name = layer_name
        self.tile_shape = tile_shape
        self.image_shape = image_shape
        self.frequency = max(1, frequency)
        self.output_path = output_path
        self.last_plot: Optional[np.ndarray] = None
        self.invoked = 0

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency:
            return
        params = model.params.get(self.layer_name)
        if not params or "W" not in params:
            return
        W = np.asarray(params["W"], np.float32)
        if W.ndim == 4:  # conv HWIO -> filter-major [O, H, W, I]
            W = np.transpose(W, (3, 0, 1, 2))
            image_shape = self.image_shape or W.shape[1:3]
            filters = W
        else:  # dense [n_in, n_out] -> rows are filters
            filters = W.T
            image_shape = self.image_shape
            if image_shape is None:
                side = int(np.sqrt(filters.shape[1]))
                image_shape = (side, filters.shape[1] // side)
                filters = filters[:, :image_shape[0] * image_shape[1]]
        pf = PlotFilters(filters, self.tile_shape, (1, 1), image_shape)
        self.last_plot = pf.plot()
        self.invoked += 1
        if self.output_path:
            np.save(self.output_path, self.last_plot)
