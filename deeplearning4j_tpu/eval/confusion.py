"""Confusion matrix (reference eval/ConfusionMatrix.java)."""

from __future__ import annotations

import numpy as np


class ConfusionMatrix:
    def __init__(self, classes):
        self.classes = list(classes)
        n = len(self.classes)
        self.matrix = np.zeros((n, n), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def add_matrix(self, other: "ConfusionMatrix"):
        self.matrix += other.matrix

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def get_actual_total(self, actual: int) -> int:
        return int(self.matrix[actual].sum())

    def get_predicted_total(self, predicted: int) -> int:
        return int(self.matrix[:, predicted].sum())

    def to_csv(self) -> str:
        header = "," + ",".join(str(c) for c in self.classes)
        rows = [header]
        for i, c in enumerate(self.classes):
            rows.append(str(c) + "," + ",".join(str(v) for v in self.matrix[i]))
        return "\n".join(rows)

    def __str__(self):
        return self.to_csv()
