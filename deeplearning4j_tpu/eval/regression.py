"""Regression evaluation (reference eval/RegressionEvaluation.java:
MSE/MAE/RMSE/relative squared error/R^2 per output column)."""

from __future__ import annotations

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: int | None = None, column_names=None):
        self.column_names = column_names
        self._n = n_columns
        self._sum_sq = None
        self._sum_abs = None
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._count = 0

    def _ensure(self, n):
        if self._sum_sq is None:
            self._n = n
            self._sum_sq = np.zeros(n)
            self._sum_abs = np.zeros(n)
            self._sum_label = np.zeros(n)
            self._sum_label_sq = np.zeros(n)
            self._sum_pred = np.zeros(n)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                m = np.asarray(mask).astype(bool).reshape(-1)
                labels, predictions = labels[m], predictions[m]
        self._ensure(labels.shape[-1])
        err = labels - predictions
        self._sum_sq += (err**2).sum(axis=0)
        self._sum_abs += np.abs(err).sum(axis=0)
        self._sum_label += labels.sum(axis=0)
        self._sum_label_sq += (labels**2).sum(axis=0)
        self._sum_pred += predictions.sum(axis=0)
        self._count += labels.shape[0]

    def mean_squared_error(self, col: int) -> float:
        return float(self._sum_sq[col] / self._count)

    def mean_absolute_error(self, col: int) -> float:
        return float(self._sum_abs[col] / self._count)

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int) -> float:
        mean = self._sum_label[col] / self._count
        ss_tot = self._sum_label_sq[col] - self._count * mean**2
        return float(1.0 - self._sum_sq[col] / max(ss_tot, 1e-12))

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self._sum_sq / self._count))

    def stats(self) -> str:
        lines = ["column,MSE,MAE,RMSE,R^2"]
        for c in range(self._n):
            name = self.column_names[c] if self.column_names else str(c)
            lines.append(
                f"{name},{self.mean_squared_error(c):.6f},"
                f"{self.mean_absolute_error(c):.6f},"
                f"{self.root_mean_squared_error(c):.6f},{self.r_squared(c):.6f}"
            )
        return "\n".join(lines)
