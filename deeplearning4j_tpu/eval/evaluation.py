"""Classification evaluation (reference eval/Evaluation.java: eval:111
argmax compare, evalTimeSeries:189-221 with masks, stats():294 —
accuracy/precision/recall/f1 + confusion matrix).
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.eval.confusion import ConfusionMatrix


class Evaluation:
    def __init__(self, n_classes: int | None = None, labels=None,
                 top_n: int = 1):
        self.label_names = labels
        self._n = n_classes
        self.confusion: ConfusionMatrix | None = None
        if n_classes:
            self.confusion = ConfusionMatrix(range(n_classes))
        self.examples = 0
        # top-N accuracy tracking (reference Evaluation(int topN) ctor:
        # an example counts as top-N correct when the true class is among
        # the N highest-probability predictions)
        self.top_n = max(1, int(top_n))
        self.top_n_correct = 0

    def _ensure(self, n):
        if self.confusion is None:
            self._n = n
            self.confusion = ConfusionMatrix(range(n))

    def eval(self, labels, predictions, mask=None):
        """labels/predictions: [batch, C] one-hot/probabilities, or
        [batch, time, C] time series (reference evalTimeSeries) with
        optional [batch, time] mask."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions, dtype=np.float32)
        if labels.ndim == 3:
            if mask is None:
                labels = labels.reshape(-1, labels.shape[-1])
                predictions = predictions.reshape(-1, predictions.shape[-1])
            else:
                m = np.asarray(mask).astype(bool).reshape(-1)
                labels = labels.reshape(-1, labels.shape[-1])[m]
                predictions = predictions.reshape(-1, predictions.shape[-1])[m]
        self._ensure(labels.shape[-1])
        actual = labels.argmax(axis=-1)
        pred = predictions.argmax(axis=-1)
        np.add.at(self.confusion.matrix, (actual, pred), 1)
        self.examples += len(actual)
        if self.top_n > 1 and len(actual):
            k = min(self.top_n, predictions.shape[-1])
            top = np.argpartition(predictions, -k, axis=-1)[:, -k:]
            self.top_n_correct += int((top == actual[:, None]).any(axis=-1).sum())
        else:
            self.top_n_correct += int((pred == actual).sum())

    def merge(self, other: "Evaluation"):
        """Merge partial evaluations (reference Evaluation.merge — used by
        distributed eval reduce)."""
        if other.confusion is None:
            return self
        if other.top_n != self.top_n:
            raise ValueError(
                f"cannot merge Evaluation(top_n={other.top_n}) into "
                f"Evaluation(top_n={self.top_n}) — counts are incompatible")
        self._ensure(other.confusion.matrix.shape[0])
        self.confusion.add_matrix(other.confusion)
        self.examples += other.examples
        self.top_n_correct += other.top_n_correct
        return self

    # ----------------------------------------------------------- metrics
    def _tp(self, c):
        return self.confusion.get_count(c, c)

    def true_positives(self):
        return {c: self._tp(c) for c in range(self._n)}

    def accuracy(self) -> float:
        if self.examples == 0:
            return 0.0
        return float(np.trace(self.confusion.matrix)) / self.examples

    def top_n_accuracy(self) -> float:
        """Fraction of examples whose true class is in the top-N predictions
        (reference Evaluation.topNAccuracy)."""
        if self.examples == 0:
            return 0.0
        return self.top_n_correct / self.examples

    def precision(self, c: int | None = None) -> float:
        if c is not None:
            denom = self.confusion.get_predicted_total(c)
            return self._tp(c) / denom if denom else 0.0
        # macro average over classes that were predicted at least once —
        # never-predicted classes are excluded, matching the warning stats()
        # prints (reference Evaluation.java:312-318)
        vals = [self.precision(i) for i in range(self._n)
                if self.confusion.get_predicted_total(i) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, c: int | None = None) -> float:
        if c is not None:
            denom = self.confusion.get_actual_total(c)
            return self._tp(c) / denom if denom else 0.0
        vals = [self.recall(i) for i in range(self._n)
                if self.confusion.get_actual_total(i) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, c: int | None = None) -> float:
        p, r = self.precision(c), self.recall(c)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def false_positive_rate(self, c: int) -> float:
        fp = self.confusion.get_predicted_total(c) - self._tp(c)
        neg = self.examples - self.confusion.get_actual_total(c)
        return fp / neg if neg else 0.0

    def false_negative_rate(self, c: int) -> float:
        fn = self.confusion.get_actual_total(c) - self._tp(c)
        pos = self.confusion.get_actual_total(c)
        return fn / pos if pos else 0.0

    def stats(self) -> str:
        """Summary string (reference stats():294, incl. the never-predicted
        class warnings :312-318)."""
        lines = ["==========================Scores=========================="]
        warnings = []
        for c in range(self._n or 0):
            if (self.confusion.get_predicted_total(c) == 0
                    and self.confusion.get_actual_total(c) > 0):
                warnings.append(
                    f"Warning: class {c} was never predicted by the model. "
                    f"This class was excluded from average precision")
        lines.extend(warnings)
        lines.append(f" Accuracy:  {self.accuracy():.4f}")
        lines.append(f" Precision: {self.precision():.4f}")
        lines.append(f" Recall:    {self.recall():.4f}")
        lines.append(f" F1 Score:  {self.f1():.4f}")
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: "
                         f"{self.top_n_accuracy():.4f}")
        lines.append("===========================================================")
        if self.confusion is not None and (self._n or 0) <= 20:
            lines.append("Confusion matrix:")
            lines.append(self.confusion.to_csv())
        return "\n".join(lines)
