"""KD-tree (reference: clustering/kdtree/{KDTree, HyperRect}.java).

Host-side structure: axis-cycling binary tree supporting insert, delete,
nearest-neighbour and range (hyper-rectangle) queries. Used by the reference
for spatial lookups; kept in NumPy — pointer-chasing tree walks are host
work, not TPU work.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class HyperRect:
    """Axis-aligned box with per-dim [lower, upper] intervals
    (kdtree/HyperRect.java)."""

    def __init__(self, lower: np.ndarray, upper: np.ndarray):
        self.lower = np.asarray(lower, dtype=np.float64)
        self.upper = np.asarray(upper, dtype=np.float64)

    @staticmethod
    def infinite(dims: int) -> "HyperRect":
        return HyperRect(np.full(dims, -np.inf), np.full(dims, np.inf))

    def contains(self, point: np.ndarray) -> bool:
        return bool(np.all(point >= self.lower) and np.all(point <= self.upper))

    def min_distance(self, point: np.ndarray) -> float:
        """Distance from point to the nearest face of the box (0 if inside)."""
        clipped = np.clip(point, self.lower, self.upper)
        return float(np.linalg.norm(point - clipped))

    def get_lower(self, point: np.ndarray, dim: int) -> "HyperRect":
        upper = self.upper.copy()
        upper[dim] = point[dim]
        return HyperRect(self.lower.copy(), upper)

    def get_upper(self, point: np.ndarray, dim: int) -> "HyperRect":
        lower = self.lower.copy()
        lower[dim] = point[dim]
        return HyperRect(lower, self.upper.copy())


class _Node:
    __slots__ = ("point", "left", "right")

    def __init__(self, point: np.ndarray):
        self.point = point
        self.left: Optional[_Node] = None
        self.right: Optional[_Node] = None


class KDTree:
    """Axis-cycling kd-tree (kdtree/KDTree.java: insert, delete, nn, knn)."""

    def __init__(self, dims: int):
        self.dims = int(dims)
        self.root: Optional[_Node] = None
        self.size = 0

    def insert(self, point) -> None:
        point = np.asarray(point, dtype=np.float64)
        if point.shape != (self.dims,):
            raise ValueError(f"expected {self.dims}-d point")
        self.size += 1
        if self.root is None:
            self.root = _Node(point)
            return
        node, depth = self.root, 0
        while True:
            dim = depth % self.dims
            if point[dim] < node.point[dim]:
                if node.left is None:
                    node.left = _Node(point)
                    return
                node = node.left
            else:
                if node.right is None:
                    node.right = _Node(point)
                    return
                node = node.right
            depth += 1

    def delete(self, point) -> bool:
        """Remove one node holding `point`. Rebuilds the subtree rooted at
        the removed node from its surviving points (median-split, so the
        rebuilt subtree is balanced) — simpler and more robust than the
        classic find-min replacement dance, and the reference's delete is a
        rarely-hot path."""
        point = np.asarray(point, dtype=np.float64)
        parent: Optional[_Node] = None
        node, depth, from_left = self.root, 0, False
        while node is not None and not np.array_equal(node.point, point):
            parent = node
            dim = depth % self.dims
            from_left = point[dim] < node.point[dim]
            node = node.left if from_left else node.right
            depth += 1
        if node is None:
            return False
        # collect the subtree's points minus the deleted node, iteratively
        pts: List[np.ndarray] = []
        stack = [c for c in (node.left, node.right) if c is not None]
        while stack:
            cur = stack.pop()
            pts.append(cur.point)
            stack.extend(c for c in (cur.left, cur.right) if c is not None)
        rebuilt = self._build_balanced(pts, depth)
        if parent is None:
            self.root = rebuilt
        elif from_left:
            parent.left = rebuilt
        else:
            parent.right = rebuilt
        self.size -= 1
        return True

    def _build_balanced(self, pts: List[np.ndarray], depth: int) -> Optional[_Node]:
        if not pts:
            return None
        dim = depth % self.dims
        pts = sorted(pts, key=lambda p: p[dim])
        mid = len(pts) // 2
        # descent invariant: strictly-less goes left, >= goes right — shift
        # the split to the first duplicate so no equal value lands left
        while mid > 0 and pts[mid - 1][dim] == pts[mid][dim]:
            mid -= 1
        node = _Node(pts[mid])
        node.left = self._build_balanced(pts[:mid], depth + 1)
        node.right = self._build_balanced(pts[mid + 1:], depth + 1)
        return node

    def nn(self, point) -> Tuple[float, Optional[np.ndarray]]:
        """Nearest neighbour: (distance, point)."""
        res = self.knn(point, 1)
        return res[0] if res else (np.inf, None)

    def knn(self, point, k: int) -> List[Tuple[float, np.ndarray]]:
        """k nearest neighbours as (distance, point), nearest first."""
        point = np.asarray(point, dtype=np.float64)
        heap: List[Tuple[float, int, np.ndarray]] = []  # max-heap via -dist
        counter = 0
        # Explicit stack instead of recursion: unbalanced inserts (sorted
        # input) can make the tree O(N) deep, which would blow the Python
        # recursion limit. Entries are (node, depth, is_far_child, parent
        # plane distance); far children re-check the prune bound at pop time
        # because tau may have tightened since they were pushed.
        stack: List[Tuple[_Node, int, bool, float]] = [(self.root, 0, False, 0.0)] if self.root else []
        while stack:
            node, depth, is_far, plane_dist = stack.pop()
            if is_far and len(heap) == k and plane_dist >= -heap[0][0]:
                continue
            d = float(np.linalg.norm(node.point - point))
            if len(heap) < k:
                heapq.heappush(heap, (-d, counter, node.point))
                counter += 1
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, counter, node.point))
                counter += 1
            dim = depth % self.dims
            diff = point[dim] - node.point[dim]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            # push far first so near is explored first (LIFO)
            if far is not None:
                stack.append((far, depth + 1, True, abs(diff)))
            if near is not None:
                stack.append((near, depth + 1, False, 0.0))
        out = [(-negd, pt) for negd, _, pt in heap]
        out.sort(key=lambda t: t[0])
        return out

    def range(self, rect: HyperRect) -> List[np.ndarray]:
        """All points inside the hyper-rectangle."""
        out: List[np.ndarray] = []
        stack: List[Tuple[_Node, int]] = [(self.root, 0)] if self.root else []
        while stack:
            node, depth = stack.pop()
            if rect.contains(node.point):
                out.append(node.point)
            dim = depth % self.dims
            if node.left is not None and rect.lower[dim] < node.point[dim]:
                stack.append((node.left, depth + 1))
            if node.right is not None and rect.upper[dim] >= node.point[dim]:
                stack.append((node.right, depth + 1))
        return out
