"""Clustering + spatial-index algorithms (reference: deeplearning4j-core
`org/deeplearning4j/clustering/` — kmeans, kdtree, vptree, quadtree, sptree).

TPU-first split: KMeans assignment/update steps are jitted XLA computations
(distances as one big matmul on the MXU); the spatial trees are host-side
pointer structures used for nearest-neighbour queries and Barnes-Hut t-SNE —
irregular tree walks don't map to the TPU and stay in NumPy, exactly the role
they play in the reference (UI nearest-neighbors, BarnesHutTsne gradients).
"""

from .cluster import Cluster, ClusterSet, Point, PointClassification
from .kmeans import KMeansClustering
from .kdtree import KDTree, HyperRect
from .vptree import VPTree
from .quadtree import QuadTree
from .sptree import SpTree

__all__ = [
    "Cluster",
    "ClusterSet",
    "Point",
    "PointClassification",
    "KMeansClustering",
    "KDTree",
    "HyperRect",
    "VPTree",
    "QuadTree",
    "SpTree",
]
