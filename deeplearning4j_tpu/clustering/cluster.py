"""Cluster model objects (reference: clustering/cluster/{Point, Cluster,
ClusterSet, PointClassification}.java).

Thin host-side containers over NumPy arrays. The heavy math (assignment,
center updates) lives in `kmeans.py` as jitted batch ops; these classes are
the user-facing result/aggregate view the reference exposes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np


@dataclass
class Point:
    """A single point with an optional id/label (cluster/Point.java)."""

    array: np.ndarray
    id: Optional[str] = None
    label: Optional[str] = None

    @staticmethod
    def to_points(matrix: np.ndarray) -> List["Point"]:
        return [Point(np.asarray(row), id=str(i)) for i, row in enumerate(matrix)]


@dataclass
class PointClassification:
    """Result of classifying one point into a ClusterSet
    (cluster/PointClassification.java)."""

    cluster: "Cluster"
    distance: float
    new_location: bool


@dataclass
class Cluster:
    """One cluster: a center plus its member points (cluster/Cluster.java)."""

    center: np.ndarray
    points: List[Point] = field(default_factory=list)
    id: Optional[str] = None
    label: Optional[str] = None

    def add_point(self, point: Point) -> None:
        self.points.append(point)

    def remove_points(self) -> None:
        self.points = []

    def distance_to_center(self, point: Point) -> float:
        return float(np.linalg.norm(point.array - self.center))

    def is_empty(self) -> bool:
        return not self.points


class ClusterSet:
    """A set of clusters + assignment API (cluster/ClusterSet.java).

    `classify_point` returns the nearest cluster; `classify_points` does the
    batch variant in one vectorised distance computation.
    """

    def __init__(self, clusters: Optional[Sequence[Cluster]] = None):
        self.clusters: List[Cluster] = list(clusters or [])

    @property
    def centers(self) -> np.ndarray:
        return np.stack([c.center for c in self.clusters])

    def add_cluster(self, cluster: Cluster) -> None:
        self.clusters.append(cluster)

    def cluster_count(self) -> int:
        return len(self.clusters)

    def get_cluster(self, idx: int) -> Cluster:
        return self.clusters[idx]

    def remove_points(self) -> None:
        for c in self.clusters:
            c.remove_points()

    def classify_point(self, point: Point, move: bool = True) -> PointClassification:
        centers = self.centers
        d = np.linalg.norm(centers - point.array[None, :], axis=1)
        idx = int(np.argmin(d))
        cluster = self.clusters[idx]
        previously = any(p is point for p in cluster.points)
        if move and not previously:
            # move semantics: a point belongs to exactly one cluster
            for other in self.clusters:
                other.points = [p for p in other.points if p is not point]
            cluster.add_point(point)
        return PointClassification(cluster, float(d[idx]), not previously)

    def classify_points(self, points: Sequence[Point], move: bool = True) -> List[PointClassification]:
        """Batch classify: one [N, K] distance computation, then the same
        move semantics as classify_point."""
        if not points:
            return []
        centers = self.centers
        pts = np.stack([p.array for p in points])
        d = np.linalg.norm(pts[:, None, :] - centers[None, :, :], axis=2)
        idxs = np.argmin(d, axis=1)
        out = []
        moving: List[Point] = []
        for p, di, idx in zip(points, d, idxs):
            cluster = self.clusters[int(idx)]
            previously = any(q is p for q in cluster.points)
            if move and not previously:
                moving.append(p)
            out.append(PointClassification(cluster, float(di[idx]), not previously))
        if moving:
            # strip all moving points in ONE pass per cluster, then append
            # each to its target — the per-point variant would rebuild every
            # cluster list N times
            moving_ids = {id(p) for p in moving}
            for c in self.clusters:
                c.points = [q for q in c.points if id(q) not in moving_ids]
            for p, idx in zip(points, idxs):
                if id(p) in moving_ids:
                    self.clusters[int(idx)].add_point(p)
        return out

    def inertia(self) -> float:
        """Sum of squared member→center distances (distortion cost)."""
        total = 0.0
        for c in self.clusters:
            if c.points:
                pts = np.stack([p.array for p in c.points])
                total += float(((pts - c.center[None, :]) ** 2).sum())
        return total
