"""Quad-tree — the 2-D space-partitioning tree (reference: clustering/
quadtree/{QuadTree, Cell}.java).

The reference maintains QuadTree separately from SpTree with the same
Barnes-Hut role specialised to 2-D (the t-SNE output dimensionality). Here it
wraps SpTree with a 2-D check plus the quadrant-named accessors the 2-D API
exposes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .sptree import SpTree


class QuadTree(SpTree):
    """2-D Barnes-Hut tree (quadtree/QuadTree.java)."""

    def __init__(self, data: np.ndarray):
        data = np.asarray(data, dtype=np.float64)
        if data.ndim != 2 or data.shape[1] != 2:
            raise ValueError("QuadTree requires [N, 2] data")
        super().__init__(data)

    # quadrant-named child accessors (QuadTree.java north-west etc.);
    # child index bit d set ⇔ on the + side of dim d.
    @property
    def south_west(self) -> Optional["SpTree"]:
        return self.children[0b00] if not self.is_leaf else None

    @property
    def south_east(self) -> Optional["SpTree"]:
        return self.children[0b01] if not self.is_leaf else None

    @property
    def north_west(self) -> Optional["SpTree"]:
        return self.children[0b10] if not self.is_leaf else None

    @property
    def north_east(self) -> Optional["SpTree"]:
        return self.children[0b11] if not self.is_leaf else None
