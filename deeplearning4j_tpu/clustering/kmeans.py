"""KMeans clustering, TPU-first (reference: clustering/kmeans/
KMeansClustering.java + algorithm/BaseClusteringAlgorithm.java with its
strategy/condition machinery: FixedClusterCountStrategy,
FixedIterationCountCondition, VarianceVariationCondition).

The reference iterates point-by-point with per-cluster Java collections; here
one Lloyd iteration is a single jitted XLA computation: the N×K distance
matrix is formed via ‖x‖² + ‖c‖² − 2·X·Cᵀ (one MXU matmul), assignment is an
argmin, and the center update is an unsorted segment-sum — all fused by XLA.
The convergence conditions run on host between steps.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .cluster import Cluster, ClusterSet, Point


@partial(jax.jit, static_argnames=("k",))
def _lloyd_step(points, centers, k):
    """One Lloyd iteration. points [N,D], centers [K,D] → (new_centers,
    assignments, distortion)."""
    # Pairwise squared distances via the gram-trick: one [N,D]x[D,K] matmul.
    x2 = jnp.sum(points * points, axis=1, keepdims=True)          # [N,1]
    c2 = jnp.sum(centers * centers, axis=1)[None, :]              # [1,K]
    d2 = x2 + c2 - 2.0 * points @ centers.T                       # [N,K]
    assign = jnp.argmin(d2, axis=1)                               # [N]
    best = jnp.min(d2, axis=1)
    distortion = jnp.sum(jnp.maximum(best, 0.0))

    sums = jax.ops.segment_sum(points, assign, num_segments=k)    # [K,D]
    counts = jax.ops.segment_sum(jnp.ones((points.shape[0],)), assign,
                                 num_segments=k)                  # [K]
    # Empty clusters keep their previous center (reference keeps the cluster
    # alive rather than dropping it).
    new_centers = jnp.where(counts[:, None] > 0,
                            sums / jnp.maximum(counts, 1.0)[:, None],
                            centers)
    return new_centers, assign, distortion


class KMeansClustering:
    """Fixed-cluster-count KMeans (kmeans/KMeansClustering.java:setup —
    `KMeansClustering.setup(clusterCount, maxIterations, distanceFunction)`).

    Termination mirrors the reference's two ClusteringAlgorithmConditions:
    a FixedIterationCountCondition (`max_iterations`) and a
    VarianceVariationCondition (`variation_tolerance` on the relative
    distortion change).
    """

    def __init__(self, cluster_count: int, max_iterations: int = 100,
                 variation_tolerance: float = 1e-4, seed: int = 0,
                 init: str = "k-means++"):
        if cluster_count < 1:
            raise ValueError("cluster_count must be >= 1")
        self.k = int(cluster_count)
        self.max_iterations = int(max_iterations)
        self.variation_tolerance = float(variation_tolerance)
        self.seed = seed
        self.init = init
        self.iteration_count = 0
        self.distortion_history: list[float] = []

    @staticmethod
    def setup(cluster_count: int, max_iterations: int = 100,
              distance_function: str = "euclidean", seed: int = 0) -> "KMeansClustering":
        """Reference-parity factory (KMeansClustering.java `setup`). Only the
        euclidean distance maps to the gram-trick matmul; it is the only
        metric the reference's kmeans uses in practice."""
        if distance_function not in ("euclidean", "sqeuclidean"):
            raise ValueError(f"unsupported distance: {distance_function}")
        return KMeansClustering(cluster_count, max_iterations, seed=seed)

    def _init_centers(self, pts: jnp.ndarray) -> jnp.ndarray:
        n = pts.shape[0]
        rng = np.random.default_rng(self.seed)
        if self.init == "random" or self.k == 1:
            idx = rng.choice(n, size=self.k, replace=False)
            return pts[np.asarray(idx)]
        # k-means++ seeding: sample proportional to distance-to-nearest.
        # Runs on host with a running min — one [N, D] distance per step —
        # instead of a jitted kernel whose growing centers shape would force
        # k-1 XLA recompiles.
        np_pts = np.asarray(pts, dtype=np.float64)
        chosen = [int(rng.integers(n))]
        d2 = np.sum((np_pts - np_pts[chosen[0]][None, :]) ** 2, axis=1)
        for _ in range(1, self.k):
            total = d2.sum()
            if total <= 0:
                remaining = [i for i in range(n) if i not in chosen]
                chosen.append(int(rng.choice(remaining)))
            else:
                chosen.append(int(rng.choice(n, p=d2 / total)))
            d2 = np.minimum(
                d2, np.sum((np_pts - np_pts[chosen[-1]][None, :]) ** 2, axis=1))
        return pts[np.asarray(chosen)]

    def apply_to(self, points) -> ClusterSet:
        """Run Lloyd iterations to convergence; returns a populated
        ClusterSet (BaseClusteringAlgorithm.applyTo)."""
        if isinstance(points, (list, tuple)) and points and isinstance(points[0], Point):
            matrix = np.stack([p.array for p in points]).astype(np.float32)
            point_objs = list(points)
        else:
            matrix = np.asarray(points, dtype=np.float32)
            point_objs = Point.to_points(matrix)
        if matrix.ndim != 2:
            raise ValueError("points must be [N, D]")
        if matrix.shape[0] < self.k:
            raise ValueError(f"need >= {self.k} points, got {matrix.shape[0]}")

        pts = jnp.asarray(matrix)
        centers = self._init_centers(pts)
        self.distortion_history = []
        prev = None
        for i in range(self.max_iterations):
            centers, _, distortion = _lloyd_step(pts, centers, self.k)
            distortion = float(distortion)
            self.distortion_history.append(distortion)
            self.iteration_count = i + 1
            if prev is not None:
                denom = max(prev, 1e-12)
                if abs(prev - distortion) / denom < self.variation_tolerance:
                    break
            prev = distortion
        # final assignment against the FINAL centers — the in-loop assign is
        # computed against the pre-update centers and would leave memberships
        # inconsistent with the returned centers
        _, assign, _ = _lloyd_step(pts, centers, self.k)

        centers_np = np.asarray(centers)
        assign_np = np.asarray(assign)
        clusters = [Cluster(center=centers_np[j], id=str(j)) for j in range(self.k)]
        for pi, ci in enumerate(assign_np):
            clusters[int(ci)].add_point(point_objs[pi])
        return ClusterSet(clusters)
