"""Vantage-point tree (reference: clustering/vptree/VPTree.java — backs the
UI nearest-neighbors view and WordVectors.wordsNearest TreeModelUtils).

Host-side metric tree for exact k-NN in general metric spaces. Built once
over an [N, D] matrix (optionally with string labels, the word2vec use-case),
then queried with `search`. Distances within a node are computed vectorised
over NumPy; the tree walk itself is host logic.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Sequence, Tuple

import numpy as np


def _distances(metric: str, items: np.ndarray, point: np.ndarray) -> np.ndarray:
    if metric == "euclidean":
        return np.linalg.norm(items - point[None, :], axis=1)
    if metric == "cosine":
        # sqrt(2·(1−cos)) = euclidean distance between the normalised
        # vectors: a true metric (1−cos violates the triangle inequality and
        # would invalidate the VP prune bounds), monotone in cosine
        # similarity so rankings match cosine nearest-neighbour queries.
        denom = (np.linalg.norm(items, axis=1) * np.linalg.norm(point) + 1e-12)
        cos = np.clip((items @ point) / denom, -1.0, 1.0)
        return np.sqrt(np.maximum(2.0 * (1.0 - cos), 0.0))
    raise ValueError(f"unknown metric {metric}")


class _VPNode:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index: int):
        self.index = index
        self.threshold = 0.0
        self.inside: Optional[_VPNode] = None
        self.outside: Optional[_VPNode] = None


class VPTree:
    """VP-tree over row vectors (VPTree.java: `new VPTree(items)`,
    `search(target, k, results, distances)`)."""

    def __init__(self, items: np.ndarray,
                 labels: Optional[Sequence[str]] = None,
                 metric: str = "euclidean", seed: int = 0):
        self.items = np.asarray(items, dtype=np.float64)
        if self.items.ndim != 2:
            raise ValueError("items must be [N, D]")
        self.labels = list(labels) if labels is not None else None
        if self.labels is not None and len(self.labels) != len(self.items):
            raise ValueError("labels length mismatch")
        self.metric = metric
        self._rng = np.random.default_rng(seed)
        self.root = self._build(list(range(len(self.items))))

    def _build(self, idxs: List[int]) -> Optional[_VPNode]:
        if not idxs:
            return None
        vp = idxs[int(self._rng.integers(len(idxs)))]
        rest = [i for i in idxs if i != vp]
        node = _VPNode(vp)
        if not rest:
            return node
        d = _distances(self.metric, self.items[rest], self.items[vp])
        # Split at the median *rank*, not the median value: ties (duplicate
        # rows are common in embedding matrices) would otherwise all land on
        # one side and stall the recursion. Rank-splitting guarantees both
        # halves shrink, so depth is O(log N). Correctness holds because
        # inside ⊆ {d <= threshold} and outside ⊆ {d >= threshold}.
        order = np.argsort(d, kind="stable")
        mid = len(rest) // 2
        node.threshold = float(d[order[mid]]) if mid < len(rest) else float(d[order[-1]])
        inside = [rest[i] for i in order[:mid]]
        outside = [rest[i] for i in order[mid:]]
        node.inside = self._build(inside)
        node.outside = self._build(outside)
        return node

    def search(self, target, k: int) -> List[Tuple[float, int]]:
        """k nearest as (distance, row-index), nearest first."""
        target = np.asarray(target, dtype=np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap via negated distance
        tau = [np.inf]

        def visit(node: Optional[_VPNode]) -> None:
            if node is None:
                return
            d = float(_distances(self.metric, self.items[node.index][None, :],
                                 target)[0])
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.index))
                tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d < node.threshold:
                visit(node.inside)
                if d + tau[0] >= node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        out = [(-negd, idx) for negd, idx in heap]
        out.sort(key=lambda t: t[0])
        return out

    def words_nearest(self, target, k: int) -> List[str]:
        """Label view of `search` (TreeModelUtils.wordsNearest)."""
        if self.labels is None:
            raise ValueError("tree built without labels")
        return [self.labels[i] for _, i in self.search(target, k)]
