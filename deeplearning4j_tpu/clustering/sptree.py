"""Space-partitioning tree for Barnes-Hut t-SNE (reference: clustering/
sptree/{SpTree, Cell, DataPoint}.java — computeNonEdgeForces /
computeEdgeForces feed plot/BarnesHutTsne.java:310).

An n-dimensional tree with 2^d children per cell, storing center-of-mass and
cumulative size per subtree. Host-side: Barnes-Hut's data-dependent pruned
traversal is irregular host work; the O(N·logN) force sums it produces are
small and feed the t-SNE update step.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class Cell:
    """Axis-aligned cell: center `corner` + half-width `width` per dim
    (sptree/Cell.java)."""

    __slots__ = ("corner", "width")

    def __init__(self, corner: np.ndarray, width: np.ndarray):
        self.corner = corner
        self.width = width

    def contains(self, point: np.ndarray) -> bool:
        return bool(np.all(np.abs(point - self.corner) <= self.width + 1e-12))


class SpTree:
    """Barnes-Hut space-partitioning tree (sptree/SpTree.java).

    Build over data [N, D]; query with `compute_non_edge_forces` (repulsive
    term, theta-pruned) and `compute_edge_forces` (attractive term over the
    sparse P matrix).
    """

    QT_NODE_CAPACITY = 1  # leaf capacity, as in the reference

    def __init__(self, data: np.ndarray, cell: Optional[Cell] = None,
                 indices: Optional[List[int]] = None):
        data = np.asarray(data, dtype=np.float64)
        self.data = data
        self.dims = data.shape[1]
        self.n_children = 2 ** self.dims
        if cell is None:
            mins = data.min(axis=0)
            maxs = data.max(axis=0)
            center = (mins + maxs) / 2.0
            width = (maxs - mins) / 2.0 + 1e-5
            cell = Cell(center, width)
        self.cell = cell
        self.center_of_mass = np.zeros(self.dims)
        self.cum_size = 0
        self.point_index: Optional[int] = None  # leaf payload
        self.children: List[Optional[SpTree]] = [None] * self.n_children
        self.is_leaf = True
        for i in (indices if indices is not None else range(len(data))):
            self.insert(int(i))

    def insert(self, index: int) -> bool:
        point = self.data[index]
        if not self.cell.contains(point):
            return False
        self.cum_size += 1
        mult1 = (self.cum_size - 1) / self.cum_size
        self.center_of_mass = self.center_of_mass * mult1 + point / self.cum_size

        if self.is_leaf and self.point_index is None:
            self.point_index = index
            return True
        # duplicate point: just accounted for in center-of-mass/cum_size
        if self.point_index is not None and np.allclose(point, self.data[self.point_index]):
            return True
        if self.is_leaf:
            self._subdivide()
        for child in self.children:
            if child is not None and child.insert(index):
                return True
        return False  # pragma: no cover - cell geometry guarantees insertion

    def _subdivide(self) -> None:
        half = self.cell.width / 2.0
        for c in range(self.n_children):
            offset = np.array([(1 if (c >> d) & 1 else -1) for d in range(self.dims)],
                              dtype=np.float64)
            corner = self.cell.corner + offset * half
            self.children[c] = SpTree(self.data, Cell(corner, half.copy()),
                                      indices=[])
        moved = self.point_index
        # this leaf may hold duplicates (cum_size counts them; the insert
        # that triggered subdivision already bumped cum_size for the NEW
        # point, which insert() will place afterwards) — re-insert the moved
        # point once per absorbed copy so no mass is dropped
        multiplicity = self.cum_size - 1
        self.point_index = None
        self.is_leaf = False
        if moved is not None:
            for _ in range(max(1, multiplicity)):
                for child in self.children:
                    if child.insert(moved):
                        break

    def compute_non_edge_forces(self, point_index: int, theta: float,
                                neg_f: np.ndarray) -> float:
        """Accumulate repulsive forces on `neg_f` [D]; returns this subtree's
        contribution to sum_Q (SpTree.computeNonEdgeForces)."""
        if self.cum_size == 0:
            return 0.0
        if self.is_leaf and self.point_index == point_index and self.cum_size == 1:
            return 0.0
        point = self.data[point_index]
        diff = point - self.center_of_mass
        d2 = float(diff @ diff)
        max_width = float(self.cell.width.max() * 2.0)
        # Barnes-Hut criterion: treat cell as one body if compact enough
        if self.is_leaf or (max_width * max_width) < (theta * theta) * d2:
            if self.is_leaf and (
                    self.point_index == point_index
                    or np.allclose(self.data[self.point_index], point)):
                # leaf holding (a duplicate of) the query point: exclude only
                # the query point itself. Remaining collapsed copies still
                # count toward sum_Q (cum_size-1 bodies at d2=0 → q=1, zero
                # net force) — the reference only short-circuits on size==1.
                return float(self.cum_size - 1)
            q = 1.0 / (1.0 + d2)
            mult = self.cum_size * q
            sum_q = mult
            neg_f += mult * q * diff
            return sum_q
        sum_q = 0.0
        for child in self.children:
            if child is not None:
                sum_q += child.compute_non_edge_forces(point_index, theta, neg_f)
        return sum_q

    def compute_edge_forces(self, rows: np.ndarray, cols: np.ndarray,
                            vals: np.ndarray) -> np.ndarray:
        """Attractive forces for all points given CSR-style (rows, cols,
        vals) of the symmetrised P matrix (SpTree.computeEdgeForces).
        Vectorised over all edges. Returns pos_f [N, D]."""
        n = len(self.data)
        pos_f = np.zeros_like(self.data)
        for i in range(n):
            start, end = rows[i], rows[i + 1]
            if start == end:
                continue
            js = cols[start:end]
            diff = self.data[i][None, :] - self.data[js]       # [E, D]
            d2 = 1.0 + np.sum(diff * diff, axis=1)             # [E]
            w = (vals[start:end] / d2)[:, None]
            pos_f[i] = np.sum(w * diff, axis=0)
        return pos_f

    def depth(self) -> int:
        if self.is_leaf:
            return 1
        return 1 + max((c.depth() for c in self.children if c is not None),
                       default=0)
