"""Zero-downtime fleet operations: live weight hot-swap, replica
self-healing, and telemetry-driven autoscaling (ISSUE 13).

Every pillar this composes already exists — any-mesh checkpoint restore
(reshard/), the continuous-batching engine (serving/engine.py), elastic
fault machinery (distributed/faults.py), and request-level telemetry —
but until this module a serving fleet could not pick up a new
checkpoint, replace a dead replica, or change size without dropping
traffic. Three operations, all off the request path (the SparkNet
train-to-serve story, arXiv:1511.06051, done with the reshard planner
of arXiv:2112.01075):

* **Live weight hot-swap** — `hot_swap` restores a checkpoint step
  through the reshard-aware `restore_for_serving` into a SHADOW net (a
  double-buffered param slot the replicas never read), validates it
  against the currently-served set (tree structure, per-leaf
  shape/dtype, device placement), and publishes it through the
  `WeightStore`: one atomic reference flip. A replica reads
  `store.current` exactly ONCE per batch, so every in-flight and queued
  request completes against a coherent param set — generation N or
  N+1, never a mix — and telemetry `request` events carry the
  generation each batch served (`weight_gen`), making the flip visible
  and the zero-failed-requests property assertable from the JSONL
  alone. A restore that fails validation (shape mismatch, truncated
  checkpoint, wrong conf) raises `WeightSwapError` with the OLD weights
  still serving; both outcomes leave a typed `weight_swap` event
  (step, restore_ms, generation, ok). `CheckpointWatcher` polls a
  checkpoint directory and hot-swaps each newly committed step — the
  training-fleet-publishes / server-follows loop.

* **Replica self-healing** — `ReplicaFaultInjector` carries
  `distributed/faults.py` replica-scoped specs (`r0:kill@batch3`,
  `r1:hang@batch2`, `r0:kill@decode5`) into the engine's worker
  threads; `FleetSupervisor.poll` detects a death from the thread's
  liveness or heartbeat staleness, reaps it (fails the in-flight batch
  loudly, drains its queued batches back to the batcher), and respawns
  it after a `RespawnBackoff` delay — re-running warmup before
  re-admission, which compiles NOTHING because the jit executables
  survive a thread death in-process, so the trace counter stays frozen
  (the chaos replay's zero-retrace gate).

* **Telemetry-driven autoscaling** — `autoscale_decision` is a pure
  function of (queue depth, recent p99, replica count, clock,
  hysteresis state); the supervisor samples the engine's batcher and
  the recorder's ring buffer, emits a typed `autoscale` event per poll
  (the occupancy headline's source), and grows/drains replicas through
  `engine.add_replica()` / `engine.retire_replica()` — scale-down
  drains: a retiring replica finishes its queued work before its
  thread exits.

Every decision surface (swap validation, supervisor reap/respawn,
autoscale hysteresis, backoff) is a pure function or takes an
injectable clock, so tier-1 drives the whole state machine with fake
clocks and zero sleeps. jax imports stay inside functions: the module
is importable under the graftlint AST stubs.

This module is the BLESSED param publish/flip path (graftlint G021):
assigning a serving worker's live params directly, or calling
`resume_from` on an engine's net anywhere else in serving/, bypasses
the double buffer, the validation, and the telemetry record.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

from deeplearning4j_tpu.distributed.faults import FaultSchedule


class WeightSwapError(RuntimeError):
    """A hot-swap restore was rejected (shape/placement mismatch,
    truncated checkpoint, no committed step); the old weights are still
    serving — rejection never interrupts traffic."""


class ReplicaKilled(RuntimeError):
    """An injected replica death (`rN:kill@...`). A thread cannot be
    SIGKILLed: the engine fails the in-flight batch loudly and lets the
    worker thread exit; the supervisor requeues + respawns."""


# ------------------------------------------------------------ weight store

@dataclass(frozen=True)
class WeightSet:
    """One immutable published param set. Frozen: a replica that read
    this set serves ALL of it — the flip can never hand out a mix."""

    generation: int
    step: int
    params: Any
    state: Any


class WeightStore:
    """The double buffer behind live hot-swap.

    `current` is a single attribute read (atomic under the GIL) of an
    immutable `WeightSet`; `publish` builds the standby set COMPLETELY
    before the one-reference flip, so a reader observes either the old
    or the new generation, never a partial write — and the old set
    object stays intact for batches that already grabbed it. Publishers
    serialize on a lock; readers never lock."""

    def __init__(self, params, state, step: int = 0):
        self._current = WeightSet(0, int(step), params, state)
        self._lock = threading.Lock()
        self.last_swap_ts: Optional[float] = None

    @property
    def current(self) -> WeightSet:
        return self._current

    @property
    def generation(self) -> int:
        return self._current.generation

    @property
    def step(self) -> int:
        return self._current.step

    def publish(self, params, state, step: int) -> WeightSet:
        """Flip to a new generation. The standby `WeightSet` is fully
        constructed BEFORE the assignment — the assignment IS the swap."""
        with self._lock:
            new = WeightSet(self._current.generation + 1, int(step),
                            params, state)
            self._current = new
            self.last_swap_ts = time.time()
            return new

    def describe(self) -> dict:
        return {"generation": self.generation, "step": self.step,
                "last_swap_ts": self.last_swap_ts}


def validate_swap(current_params, candidate_params) -> None:
    """The pre-flip gate: the candidate tree must match the served tree
    in structure and per-leaf shape/dtype, and every candidate leaf must
    live on this process's own devices (a leaf resharded onto a remote
    mesh would fail mid-forward, after the flip — too late). Raises
    `WeightSwapError` naming the first offending leaf."""
    import jax

    cur_leaves, cur_def = jax.tree.flatten(current_params)
    new_leaves, new_def = jax.tree.flatten(candidate_params)
    if cur_def != new_def:
        raise WeightSwapError(
            f"param tree structure mismatch: serving {cur_def} vs "
            f"candidate {new_def}")
    local = set(jax.local_devices())
    for i, (a, b) in enumerate(zip(cur_leaves, new_leaves)):
        if getattr(a, "shape", None) != getattr(b, "shape", None) or \
                str(getattr(a, "dtype", "")) != str(getattr(b, "dtype", "")):
            raise WeightSwapError(
                f"leaf {i} mismatch: serving "
                f"{getattr(a, 'shape', None)}/{getattr(a, 'dtype', None)} "
                f"vs candidate "
                f"{getattr(b, 'shape', None)}/{getattr(b, 'dtype', None)}")
        devs = getattr(getattr(b, "sharding", None), "device_set", None)
        if devs is not None and not set(devs) <= local:
            raise WeightSwapError(
                f"leaf {i} is placed on non-local devices "
                f"{set(devs) - local} — the restore must target this "
                "serving process's own mesh")


# -------------------------------------------------------- restore + swap

def restore_for_serving(net, checkpoint_dir: str, step=None) -> int:
    """The blessed serving restore: reshard-aware `resume_from` onto
    this process's OWN one-device data mesh (the checkpoint may have
    been written by any training fleet shape — reshard/ plans the
    placement and orbax reads only the needed slices). Engines call
    this at startup; `hot_swap` calls it against a shadow net. Returns
    the restored step (0 = cold start)."""
    import jax

    from deeplearning4j_tpu.parallel.mesh import make_mesh

    return int(net.resume_from(
        checkpoint_dir, step=step,
        target_mesh=make_mesh({"data": 1}, devices=jax.local_devices())))


def _shadow_net(net):
    """A fresh net with the same configuration — the double-buffered
    restore target. Its params are the standby slot; the serving net's
    own params are never touched."""
    clone = getattr(net, "clone", None)
    if callable(clone):
        return clone()
    import copy

    shadow = type(net)(copy.deepcopy(net.conf))
    shadow.init()
    return shadow


def latest_step(checkpoint_dir: str) -> Optional[int]:
    """Newest fully-committed step under a ShardedCheckpointer layout
    (meta.json is written last, so a step without one is mid-write).
    Pure stdlib — the watcher polls this without importing orbax."""
    try:
        entries = os.listdir(checkpoint_dir)
    except OSError:
        return None
    steps = []
    for d in entries:
        if d.startswith("step_") and os.path.exists(
                os.path.join(checkpoint_dir, d, "meta.json")):
            try:
                steps.append(int(d.split("_", 1)[1]))
            except ValueError:
                pass
    return max(steps) if steps else None


def validate_checkpoint_shapes(current_params, checkpoint_dir: str,
                               step: int) -> None:
    """The PRE-restore gate: the checkpoint's RECORDED array metadata
    (orbax, written at save time) must match the served param tree
    leaf-for-leaf in structure, shape, and dtype. This must happen
    before any read: the reshard-aware restore path loads only the
    slices a target template asks for, so a wrong-architecture
    checkpoint would otherwise partially load into correctly-SHAPED
    garbage that a post-restore check cannot see. An unreadable /
    truncated step fails the same gate (rejection is the safe
    direction — the old weights keep serving)."""
    import jax
    import orbax.checkpoint as ocp

    model_dir = os.path.join(checkpoint_dir, f"step_{step}", "model")
    try:
        meta = ocp.StandardCheckpointer().metadata(model_dir)
    except Exception as exc:
        raise WeightSwapError(
            f"checkpoint step {step} is unreadable (truncated or "
            f"corrupt): {exc}") from exc
    recorded = meta.get("params") if isinstance(meta, dict) else None
    if recorded is None:
        raise WeightSwapError(
            f"checkpoint step {step} records no params tree")
    cur_leaves, cur_def = jax.tree.flatten(current_params)
    rec_leaves, rec_def = jax.tree.flatten(recorded)
    if cur_def != rec_def:
        raise WeightSwapError(
            f"checkpoint param tree structure mismatch: serving "
            f"{cur_def} vs checkpoint {rec_def}")
    for i, (a, b) in enumerate(zip(cur_leaves, rec_leaves)):
        a_shape = tuple(getattr(a, "shape", ()) or ())
        b_shape = tuple(getattr(b, "shape", ()) or ())
        if a_shape != b_shape or \
                str(getattr(a, "dtype", "")) != str(getattr(b, "dtype",
                                                            "")):
            raise WeightSwapError(
                f"checkpoint leaf {i} mismatch: serving "
                f"{a_shape}/{getattr(a, 'dtype', None)} vs checkpoint "
                f"{b_shape}/{getattr(b, 'dtype', None)} — wrong "
                "architecture for this engine")


def hot_swap(engine, checkpoint_dir: str, step=None) -> dict:
    """Restore `step` (default: latest) into a shadow net OFF the
    request path, validate, and atomically flip every replica onto the
    new generation. Emits the typed `weight_swap` event either way; on
    any failure the old weights keep serving and `WeightSwapError`
    raises with the cause."""
    rec = engine.recorder
    t0 = time.perf_counter()
    try:
        if getattr(engine, "_workers", None):
            raise WeightSwapError(
                "generation engines hot-swap by rolling replica "
                "restart, not a live flip: an in-flight generation's "
                "KV cache binds it to the weights that wrote it")
        target = step if step is not None else latest_step(checkpoint_dir)
        if target is None:
            raise WeightSwapError(
                f"no committed checkpoint under {checkpoint_dir}")
        served = engine.weights.current.params
        validate_checkpoint_shapes(served, checkpoint_dir, target)
        shadow = _shadow_net(engine.net)
        restored = restore_for_serving(shadow, checkpoint_dir,
                                       step=target)
        validate_swap(served, shadow.params)
        new = engine.weights.publish(shadow.params, shadow.state, restored)
    except Exception as exc:
        restore_ms = round(1000.0 * (time.perf_counter() - t0), 3)
        rec.error("weight_swap", exc=exc)
        rec.event("weight_swap", ok=False, step=step,
                  restore_ms=restore_ms,
                  generation=engine.weights.generation,
                  error=f"{type(exc).__name__}: {exc}")
        if isinstance(exc, WeightSwapError):
            raise
        raise WeightSwapError(f"hot swap failed, old weights still "
                              f"serving: {exc}") from exc
    restore_ms = round(1000.0 * (time.perf_counter() - t0), 3)
    rec.event("weight_swap", ok=True, step=new.step,
              restore_ms=restore_ms, generation=new.generation)
    return {"step": new.step, "generation": new.generation,
            "restore_ms": restore_ms}


class CheckpointWatcher:
    """Follow a training fleet's checkpoint directory: each newly
    committed step hot-swaps into the engine. A step whose restore is
    REJECTED is remembered (never retried in a hot loop) and the old
    weights keep serving. `poll_once` is the testable unit; `start`
    wraps it in a daemon thread for live use."""

    def __init__(self, engine, checkpoint_dir: str, *,
                 interval_s: float = 0.5):
        self.engine = engine
        self.checkpoint_dir = checkpoint_dir
        self.interval_s = float(interval_s)
        self.seen_step = int(engine.weights.step)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> Optional[dict]:
        """One watch tick: swap the newest unseen committed step, if
        any. Returns the swap record, a rejection record (`ok: False`),
        or None when nothing is new."""
        step = latest_step(self.checkpoint_dir)
        if step is None or step <= self.seen_step:
            return None
        self.seen_step = step  # even a rejected step is not re-tried
        try:
            out = hot_swap(self.engine, self.checkpoint_dir, step=step)
        except WeightSwapError as exc:
            return {"ok": False, "step": step, "error": str(exc)}
        out["ok"] = True
        return out

    def start(self) -> "CheckpointWatcher":
        def loop():
            while not self._stop.wait(self.interval_s):
                self.poll_once()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-ckpt-watch")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


# ------------------------------------------------------- fault injection

class ReplicaFaultInjector:
    """The serving half of the fault harness: replica-scoped specs from
    `distributed/faults.py` fire inside the worker thread that owns the
    unit counter. One-shot per fault (a respawned replica restarts its
    batch counter; the same spec must not re-kill it forever). The
    `fault` telemetry event lands BEFORE the fault acts — same contract
    as the process-scoped runtime."""

    def __init__(self, schedule, recorder=None):
        if not isinstance(schedule, FaultSchedule):
            schedule = FaultSchedule.parse(schedule)
        self.faults = [f for f in schedule if f.scope == "replica"]
        self.recorder = recorder
        self._fired: set = set()
        self._lock = threading.Lock()

    def _rec(self):
        if self.recorder is not None:
            return self.recorder
        from deeplearning4j_tpu.telemetry import get_default

        return get_default()

    def check(self, replica_index: int, unit: str, count: int) -> None:
        """Fire any scheduled fault for (replica, unit, count). kill
        raises `ReplicaKilled`; hang parks this thread forever (the
        supervisor's heartbeat bound reaps it)."""
        for f in self.faults:
            if (f.process_id != replica_index or f.unit != unit
                    or f.step != count):
                continue
            with self._lock:
                if f in self._fired:
                    continue
                self._fired.add(f)
            self._rec().fault(f"replica-{f.kind}", replica=replica_index,
                              spec=f.spec(), unit=unit, count=count,
                              fired=True)
            if f.kind == "kill":
                raise ReplicaKilled(f.spec())
            if f.kind == "hang":
                threading.Event().wait()  # forever; reaped by heartbeat


# ------------------------------------------------------- respawn backoff

class RespawnBackoff:
    """Exponential respawn delay with a deterministic, CAPPED jitter: a
    replica that keeps dying (a poisoned warmup, a bad weight set) must
    not be respawned in a tight loop, and a fleet of supervisors must
    not respawn in lockstep. Seeded stdlib Random — the same seed
    always produces the same delays (fake-clock testable)."""

    def __init__(self, base_s: float = 0.05, factor: float = 2.0,
                 cap_s: float = 2.0, jitter_frac: float = 0.2,
                 seed: int = 0):
        if not 0.0 <= jitter_frac <= 1.0:
            raise ValueError(f"jitter_frac must be in [0, 1], got "
                             f"{jitter_frac}")
        self.base_s = float(base_s)
        self.factor = float(factor)
        self.cap_s = float(cap_s)
        self.jitter_frac = float(jitter_frac)
        self._rng = random.Random(seed)
        self.attempt = 0

    def next(self) -> float:
        """Delay before the next respawn attempt: min(base * factor^k,
        cap) plus jitter in [0, jitter_frac * delay] — the jitter is
        capped BY the capped delay, so the total never exceeds
        cap_s * (1 + jitter_frac)."""
        delay = min(self.base_s * (self.factor ** self.attempt),
                    self.cap_s)
        self.attempt += 1
        return delay + self._rng.uniform(0.0, self.jitter_frac * delay)

    def reset(self) -> None:
        """A replica that served again cleanly earns a fresh ladder."""
        self.attempt = 0


# ------------------------------------------------------------ autoscaling

@dataclass(frozen=True)
class AutoscalePolicy:
    """The hysteresis knobs. Scale UP when queue depth or recent p99
    crosses its high-water mark (a burst is building faster than the
    fleet drains it); scale DOWN only when BOTH are under the low-water
    marks (either signal still hot holds the fleet). Separate
    cooldowns: growing is cheap and urgent, draining is neither."""

    min_replicas: int = 1
    max_replicas: int = 4
    up_queue_depth: int = 8
    down_queue_depth: int = 1
    up_p99_ms: float = float("inf")
    down_p99_ms: float = float("inf")
    cooldown_up_s: float = 0.25
    cooldown_down_s: float = 2.0
    # HBM headroom floor (0 disables). In-process replicas SHARE the
    # device, so low headroom vetoes growth (a new replica's KV pages
    # would land on an already-tight HBM) and, past the floor, drains
    # one replica to free pages — the memory analogue of the latency
    # signal, fed from the telemetry `memory` events.
    min_headroom: float = 0.0


@dataclass
class AutoscaleState:
    """The supervisor's per-fleet hysteresis memory."""

    last_up_t: float = float("-inf")
    last_down_t: float = float("-inf")


def autoscale_decision(policy: AutoscalePolicy, state: AutoscaleState, *,
                       queue_depth: int, p99_ms: float, n_replicas: int,
                       now: float, headroom: Optional[float] = None) -> int:
    """The pure scale decision: +1 (grow), -1 (drain one), or 0. Mutates
    only `state` (the hysteresis marks) — fake-clock testable. A
    scale-up also arms the DOWN cooldown so a burst's tail can't
    immediately drain what its head grew. `headroom` (fraction of HBM
    left, None = no signal) gates against `policy.min_headroom`: a
    breach vetoes growth and drains one replica on the usual DOWN
    cooldown — memory pressure outranks latency pressure."""
    breached = (policy.min_headroom > 0 and headroom is not None
                and headroom < policy.min_headroom)
    if breached:
        if n_replicas > policy.min_replicas \
                and now - state.last_down_t >= policy.cooldown_down_s:
            state.last_down_t = now
            return -1
        return 0
    over = (queue_depth >= policy.up_queue_depth
            or p99_ms >= policy.up_p99_ms)
    if over and n_replicas < policy.max_replicas \
            and now - state.last_up_t >= policy.cooldown_up_s:
        state.last_up_t = now
        state.last_down_t = now
        return 1
    under = (queue_depth <= policy.down_queue_depth
             and p99_ms <= policy.down_p99_ms)
    if under and n_replicas > policy.min_replicas \
            and now - state.last_down_t >= policy.cooldown_down_s \
            and now - state.last_up_t >= policy.cooldown_down_s:
        state.last_down_t = now
        return -1
    return 0


def recent_p99_ms(recorder, n: int = 64) -> float:
    """p99 of the last `n` successful `request` events' `total_s` in the
    recorder's in-memory ring — the supervisor's latency signal (0.0
    when no requests have completed yet)."""
    lat = [1000.0 * float(ev["total_s"]) for ev in recorder.events
           if ev.get("event") == "request" and ev.get("ok")
           and "total_s" in ev][-n:]
    if not lat:
        return 0.0
    lat.sort()
    k = min(len(lat) - 1, max(0, int(round(0.99 * (len(lat) - 1)))))
    return lat[k]


def recent_headroom(recorder) -> Optional[float]:
    """Min per-device HBM headroom (1 - bytes_in_use/bytes_limit) from
    the LATEST `memory` event in the recorder's in-memory ring — the
    supervisor's memory signal, same shape as recent_p99_ms. None when
    no memory event carries device limits (off-TPU, or sampling off):
    no signal, not "plenty of room"."""
    for ev in reversed(recorder.events):
        if ev.get("event") != "memory":
            continue
        ratios = []
        for row in (ev.get("devices") or {}).values():
            limit = float(row.get("bytes_limit", 0) or 0)
            if limit > 0:
                ratios.append(
                    1.0 - float(row.get("bytes_in_use", 0)) / limit)
        return min(ratios) if ratios else None
    return None


# ------------------------------------------------------------- supervisor

class FleetSupervisor:
    """The per-engine operations loop: replica self-healing plus
    (optionally) telemetry-driven autoscaling.

    `poll(now)` is the whole state machine — injectable clock, no
    internal sleeps — and `run_in_thread` wraps it for live fleets.
    Each tick:

    1. **Detect** — a worker is dead when its thread has exited without
       draining (the kill path marks itself dead) or when it holds a
       batch past `death_after_s` of heartbeat silence (the hang path:
       a wedged thread cannot report its own death).
    2. **Reap** — `engine.fleet_reap` fails the in-flight batch loudly
       (its requests get `request` events with `ok: false` — the
       BOUNDED failure set) and drains queued batches back to the
       batcher FIFO, where live replicas pick them up.
    3. **Respawn** — after the backoff delay, `engine.fleet_respawn`
       re-runs warmup on the same jit wrappers (zero compiles: the
       executables survive a thread death) and re-admits the replica;
       a `replica-respawn` fault event carries `respawn_ms`.
    4. **Autoscale** — when a policy is set: sample queue depth, the
       recorder ring's recent p99, and the latest `memory` event's HBM
       headroom (recent_headroom — the memory analogue of the
       straggler signal), apply `autoscale_decision`, and grow/drain
       through the engine; every tick emits a typed `autoscale` event
       (the occupancy bench row's only source) carrying the headroom
       it acted on.
    """

    def __init__(self, engine, *, policy: Optional[AutoscalePolicy] = None,
                 death_after_s: float = 2.0,
                 backoff: Optional[RespawnBackoff] = None,
                 clock=time.monotonic, recorder=None):
        self.engine = engine
        self.policy = policy
        self.death_after_s = float(death_after_s)
        self.backoff = backoff or RespawnBackoff()
        self._clock = clock
        self.recorder = recorder if recorder is not None else engine.recorder
        self.scale_state = AutoscaleState()
        self._respawn_due: dict = {}  # worker -> due time
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- tick
    def _is_dead(self, w, now: float) -> bool:
        if not w.alive:
            return True  # marked itself dead (the kill path)
        thread = getattr(w, "_thread", None)
        if thread is not None and not thread.is_alive() \
                and w.lifecycle == "serving":
            return True  # exited without draining
        if getattr(w, "current_batch", None) is not None \
                and now - w.last_beat > self.death_after_s:
            return True  # wedged mid-batch: heartbeat went stale
        return False

    def poll(self, now: Optional[float] = None) -> dict:
        now = self._clock() if now is None else now
        actions = {"reaped": [], "respawned": [], "scale": 0}
        for w in self.engine.fleet_workers():
            if w.lifecycle in ("draining", "retired"):
                continue  # scale-down drain is not a death
            if w in self._respawn_due:
                continue
            if w.lifecycle == "dead" or self._is_dead(w, now):
                requeued = self.engine.fleet_reap(
                    w, reason="heartbeat-stale" if w.alive else "died")
                delay = self.backoff.next()
                self._respawn_due[w] = now + delay
                self.recorder.fault(
                    "replica-dead", replica=w.index, requeued=requeued,
                    respawn_in_s=round(delay, 4))
                actions["reaped"].append(w.index)
        for w, due in list(self._respawn_due.items()):
            if now < due:
                continue
            del self._respawn_due[w]
            t0 = time.perf_counter()
            self.engine.fleet_respawn(w)
            respawn_ms = round(1000.0 * (time.perf_counter() - t0), 3)
            self.backoff.reset()
            self.recorder.fault("replica-respawn", replica=w.index,
                                respawn_ms=respawn_ms)
            actions["respawned"].append(w.index)
        if self.policy is not None:
            snap = self.engine.fleet_snapshot()
            p99 = recent_p99_ms(self.recorder)
            headroom = recent_headroom(self.recorder)
            d = autoscale_decision(
                self.policy, self.scale_state,
                queue_depth=snap["queue_depth"], p99_ms=p99,
                n_replicas=snap["n_replicas"], now=now,
                headroom=headroom)
            if d > 0:
                self.engine.add_replica()
            elif d < 0:
                self.engine.retire_replica()
            actions["scale"] = d
            fields = {}
            if headroom is not None:
                fields["headroom"] = round(headroom, 4)
            self.recorder.event(
                "autoscale", n_serving=snap["n_serving"] + max(0, d),
                n_replicas=snap["n_replicas"] + d,
                queue_depth=snap["queue_depth"],
                p99_ms=round(p99, 3), action=d,
                max_replicas=self.policy.max_replicas, **fields)
        return actions

    # ------------------------------------------------------------- live
    def run_in_thread(self, interval_s: float = 0.05) -> "FleetSupervisor":
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.poll()
                except Exception as exc:  # keep supervising; log loudly
                    self.recorder.error("fleet-supervisor", exc=exc)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-supervisor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


__all__ = [
    "AutoscalePolicy",
    "AutoscaleState",
    "CheckpointWatcher",
    "FleetSupervisor",
    "ReplicaFaultInjector",
    "ReplicaKilled",
    "RespawnBackoff",
    "WeightSet",
    "WeightStore",
    "WeightSwapError",
    "autoscale_decision",
    "hot_swap",
    "latest_step",
    "recent_p99_ms",
    "restore_for_serving",
    "validate_swap",
]
