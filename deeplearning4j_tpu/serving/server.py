"""The serving front door — stdlib ThreadingHTTPServer over an
InferenceEngine (same lifecycle idiom as ui/server.py).

Endpoints (all JSON):

    POST /predict       {"features": [...], "mask": [...]?, "id": "..."?}
                        -> {"id", "output", "prediction", "timing"}
                        Each request rides the continuous batcher: it
                        coalesces with concurrent requests into a bucket
                        batch (serving/batcher.py) and returns when its
                        batch completes. 400 on malformed input or a
                        prompt longer than the lattice max; 500 when the
                        batch's forward worker died (the error string
                        names the cause); 503 while draining.
    GET  /healthz       {"status", "replicas", "lattice", "served", ...}
    GET  /stats         the engine's full counter dict
    POST /drain         begin graceful drain (stop admitting; pending
                        batches flush); the server keeps answering GETs

Run with ``ServingServer(engine, port=0).start()``; ``.url`` gives the
bound address. ``stop()`` drains the engine then closes the listener.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

# per-request wait bound inside the HTTP handler: far above any sane
# max-wait + forward time; a hit means the engine lost the batch
REQUEST_TIMEOUT_S = 60.0


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-serve/1.0"

    def log_message(self, fmt, *args):  # quiet, like ui/server.py
        pass

    @property
    def serving(self) -> "ServingServer":
        return self.server.serving_server

    def _json(self, obj, code: int = 200) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        route = self.path.rstrip("/")
        engine = self.serving.engine
        if route in ("", "/healthz"):
            stats = engine.stats()
            stats["status"] = ("draining" if self.serving.draining
                              else "serving")
            self._json(stats)
            return
        if route == "/stats":
            self._json(engine.stats())
            return
        self._json({"error": f"unknown path {self.path}"}, 404)

    def do_POST(self):  # noqa: N802
        route = self.path.rstrip("/")
        if route == "/drain":
            self.serving.begin_drain()
            self._json({"status": "draining"})
            return
        if route != "/predict":
            self._json({"error": f"unknown path {self.path}"}, 404)
            return
        if self.serving.draining:
            self._json({"error": "draining; not admitting requests"}, 503)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            features = np.asarray(payload["features"])
            mask = payload.get("mask")
        except (KeyError, ValueError, TypeError) as exc:
            self._json({"error": f"bad request body: {exc!r}"}, 400)
            return
        engine = self.serving.engine
        try:
            req = engine.submit(features, mask=mask,
                                request_id=payload.get("id"))
        except (ValueError, RuntimeError) as exc:
            # lattice rejection (prompt longer than the max seq bucket)
            # or a drain race — the client's error, not a retrace
            self._json({"error": str(exc)}, 400)
            return
        if not req.wait(REQUEST_TIMEOUT_S):
            self._json({"id": req.request_id, "error": "timed out"}, 504)
            return
        if req.error is not None:
            self._json({"id": req.request_id, "error": req.error}, 500)
            return
        out = np.asarray(req.result)
        self._json({
            "id": req.request_id,
            "output": out.tolist(),
            "prediction": _argmax_last(out),
            "timing": {
                "queue_s": round(req.t_assembled - req.t_enqueue, 6),
                "total_s": round(req.t_done - req.t_enqueue, 6),
            },
        })


def _argmax_last(out: np.ndarray):
    """Class index/indices over the last axis — the `predict` view of
    the raw output ([V] -> int, [T, V] -> [T] ints)."""
    if out.ndim == 0:
        return float(out)
    am = np.argmax(out, axis=-1)
    return int(am) if am.ndim == 0 else am.tolist()


class ServingServer:
    """Facade owning the HTTP listener; the engine is constructed by the
    caller (CLI `serve` or a test) so its lattice/replica/checkpoint
    config stays explicit."""

    def __init__(self, engine, port: int = 0, host: str = "127.0.0.1"):
        self.engine = engine
        self.draining = False
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.serving_server = self
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ServingServer":
        self.engine.start()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()
        return self

    def begin_drain(self) -> None:
        """Stop admitting /predict requests; the engine flushes what it
        already accepted (POST /drain, and the first phase of stop())."""
        self.draining = True

    def stop(self, drain_timeout: float = 30.0) -> None:
        """Graceful shutdown: drain the engine (every admitted request
        completes or fails loudly), then close the listener."""
        self.begin_drain()
        self.engine.drain(drain_timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
