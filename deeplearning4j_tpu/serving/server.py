"""The serving front door — stdlib ThreadingHTTPServer over an
InferenceEngine (same lifecycle idiom as ui/server.py).

Endpoints (all JSON):

    POST /predict       {"features": [...], "mask": [...]?, "id": "..."?}
                        -> {"id", "output", "prediction", "timing"}
                        Each request rides the continuous batcher: it
                        coalesces with concurrent requests into a bucket
                        batch (serving/batcher.py) and returns when its
                        batch completes. 400 on malformed input or a
                        prompt longer than the lattice max; 500 when the
                        batch's forward worker died (the error string
                        names the cause); 503 while draining.
    POST /generate      {"tokens": [...], "max_new_tokens": N?, "id"?}
                        -> STREAMING NDJSON (one {"token": t, "i": k}
                        line per generated token as it decodes, then a
                        {"done": true, "tokens": [...], "timing": ...}
                        summary line; close-delimited body). Requires a
                        GenerationEngine (serving/engine.py). 400 on
                        malformed/oversized prompts, 503 while draining
                        or when the KV-cache page pool and pending
                        queue are saturated (kvcache.py — exhaustion
                        queues or refuses, never crashes), 404 when the
                        engine has no generation path.
    POST /embed         {"ids": [...], "id": "..."?}
                        -> {"id", "vectors", "timing"} — embedding-table
                        row lookup (padded up to the engine's bucket
                        lattice; the ep-sharded gather path when the
                        engine serves a live sharded table). Requires an
                        EmbeddingServingEngine (embedding/serving.py);
                        404 otherwise, 400 on out-of-range ids or a
                        batch over the lattice max, 503 while draining.
    POST /search        {"vector": [...] | "vectors": [[...]], "k": N?,
                        "id"?} -> {"id", "ids", "scores", "timing"} —
                        ANN top-k over the device-resident partition-
                        then-refine index (embedding/ann.py), nearest
                        first by cosine. `k` must be on the engine's
                        warmed k-grid (a foreign k would retrace); same
                        404/400/503 envelope as /embed.
    GET  /metrics       Prometheus text exposition (version 0.0.4),
                        backed by the pure-stdlib rolling-histogram
                        registry (telemetry/metrics.py): request
                        latency histogram + live p50/p99 (fed straight
                        off the telemetry `request` event stream, no
                        log parse on the scrape path), queue depth,
                        KV page-pool occupancy (raw pages + fill
                        ratio), speculative-decode acceptance gauges
                        (accepted tokens/step, draft acceptance rate)
                        when the engine decodes speculatively,
                        published weight generation/step, per-replica
                        liveness and heartbeat age, and the memory/MFU
                        surface: `serving_hbm_live_bytes`,
                        `serving_hbm_limit_bytes` + per-device
                        `serving_hbm_headroom_ratio` (TPU only),
                        `serving_memory_ledger_bytes{subsystem=...}`
                        (telemetry/memstat.py ledger), and
                        `serving_mfu_live` (cost-book flops over
                        measured forward time; telemetry/costbook.py)
                        — the fleet's pager surface
    GET  /healthz       {"status", "replicas", "lattice", "served", ...,
                        "fleet": [per-replica {index, state (warming/
                        serving/draining/dead/retired), alive, counters,
                        last_beat_age_s}], "weights": {generation, step,
                        last_swap_ts}} — the fleet-operations view
                        (serving/fleet.py): current weight generation,
                        last hot-swap timestamp, replica lifecycles
    GET  /stats         the engine's full counter dict (same fleet rows)
    POST /drain         begin graceful drain (stop admitting; pending
                        batches flush); the server keeps answering GETs

Every 503 (draining, KV-cache saturation) carries a ``Retry-After``
header: the condition is transient — a drained server's traffic moves
to its replacement, a saturated pool frees as requests complete.

Run with ``ServingServer(engine, port=0).start()``; ``.url`` gives the
bound address. ``stop()`` drains the engine then closes the listener.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

# per-request wait bound inside the HTTP handler: far above any sane
# max-wait + forward time; a hit means the engine lost the batch
REQUEST_TIMEOUT_S = 60.0

# Retry-After seconds on every 503 (drain / saturation): drains flush in
# well under this, and a retrying client that waits it out lands on the
# replacement fleet member
RETRY_AFTER_S = 5


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-serve/1.0"

    def log_message(self, fmt, *args):  # quiet, like ui/server.py
        pass

    @property
    def serving(self) -> "ServingServer":
        return self.server.serving_server

    def _json(self, obj, code: int = 200, headers=()) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if code == 503:
            # a draining / saturated fleet is a transient condition: tell
            # well-behaved clients when to come back (RFC 9110 §10.2.3)
            self.send_header("Retry-After", str(RETRY_AFTER_S))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        route = self.path.rstrip("/")
        engine = self.serving.engine
        if route in ("", "/healthz"):
            stats = engine.stats()
            stats["status"] = ("draining" if self.serving.draining
                              else "serving")
            self._json(stats)
            return
        if route == "/stats":
            self._json(engine.stats())
            return
        if route == "/metrics":
            body = self.serving.metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", _metrics_mod().CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self._json({"error": f"unknown path {self.path}"}, 404)

    def do_POST(self):  # noqa: N802
        route = self.path.rstrip("/")
        if route == "/drain":
            self.serving.begin_drain()
            self._json({"status": "draining"})
            return
        if route == "/generate":
            self._generate()
            return
        if route in ("/embed", "/search"):
            self._embedding(route)
            return
        if route != "/predict":
            self._json({"error": f"unknown path {self.path}"}, 404)
            return
        if self.serving.draining:
            self._json({"error": "draining; not admitting requests"}, 503)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            features = np.asarray(payload["features"])
            mask = payload.get("mask")
        except (KeyError, ValueError, TypeError) as exc:
            self._json({"error": f"bad request body: {exc!r}"}, 400)
            return
        engine = self.serving.engine
        try:
            req = engine.submit(features, mask=mask,
                                request_id=payload.get("id"))
        except (ValueError, RuntimeError) as exc:
            # lattice rejection (prompt longer than the max seq bucket)
            # or a drain race — the client's error, not a retrace
            self._json({"error": str(exc)}, 400)
            return
        if not req.wait(REQUEST_TIMEOUT_S):
            self._json({"id": req.request_id, "error": "timed out"}, 504)
            return
        if req.error is not None:
            self._json({"id": req.request_id, "error": req.error}, 500)
            return
        out = np.asarray(req.result)
        self._json({
            "id": req.request_id,
            "output": out.tolist(),
            "prediction": _argmax_last(out),
            "timing": {
                "queue_s": round(req.t_assembled - req.t_enqueue, 6),
                "total_s": round(req.t_done - req.t_enqueue, 6),
            },
        })


    def _generate(self):
        """Streaming generation: tokens flow to the client line-by-line
        as the decode loop emits them (queue → NDJSON; the body is
        close-delimited, so plain urllib readers see each line as it
        flushes). The summary line carries the full token list and the
        TTFT/total timing so a client that only reads the tail still
        gets everything."""
        engine = self.serving.engine
        if not hasattr(engine, "submit_generate"):
            self._json({"error": "this engine does not serve "
                                 "generation (start a "
                                 "GenerationEngine)"}, 404)
            return
        if self.serving.draining:
            self._json({"error": "draining; not admitting requests"}, 503)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            tokens = np.asarray(payload["tokens"])
            max_new = payload.get("max_new_tokens")
        except (KeyError, ValueError, TypeError) as exc:
            self._json({"error": f"bad request body: {exc!r}"}, 400)
            return
        from deeplearning4j_tpu.serving.engine import QueueFullError

        try:
            req = engine.submit_generate(tokens, max_new,
                                         request_id=payload.get("id"))
        except QueueFullError as exc:
            self._json({"error": str(exc)}, 503)
            return
        except (ValueError, RuntimeError) as exc:
            code = 503 if "draining" in str(exc) else 400
            self._json({"error": str(exc)}, code)
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.end_headers()
        i = 0
        while True:
            try:
                tok = req.stream.get(timeout=REQUEST_TIMEOUT_S)
            except Exception:
                self._line({"id": req.request_id, "error": "timed out"})
                return
            if tok is None:
                break
            self._line({"token": int(tok), "i": i})
            i += 1
        summary = {"done": True, "id": req.request_id,
                   "tokens": list(req.emitted),
                   "timing": {
                       "queue_s": round(req.t_admitted - req.t_enqueue, 6),
                       "ttft_s": (round(req.t_first_token - req.t_enqueue,
                                        6) if req.t_first_token else None),
                       "total_s": round(req.t_done - req.t_enqueue, 6)}}
        if req.error is not None:
            summary["error"] = req.error
        self._line(summary)

    def _embedding(self, route: str):
        """Embedding lookups and ANN vector search, served by an
        EmbeddingServingEngine (embedding/serving.py). Gated on the
        submit methods the same way /generate gates on
        submit_generate."""
        engine = self.serving.engine
        method = "submit_embed" if route == "/embed" else "submit_search"
        if not hasattr(engine, method):
            self._json({"error": "this engine does not serve embeddings "
                                 "(start an EmbeddingServingEngine)"}, 404)
            return
        if self.serving.draining:
            self._json({"error": "draining; not admitting requests"}, 503)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if route == "/embed":
                req = engine.submit_embed(payload["ids"],
                                          request_id=payload.get("id"))
            else:
                queries = payload.get("vectors", payload.get("vector"))
                if queries is None:
                    raise KeyError("vector")
                req = engine.submit_search(queries, k=payload.get("k"),
                                           request_id=payload.get("id"))
        except (KeyError, ValueError, TypeError) as exc:
            self._json({"error": f"bad request body: {exc!r}"}, 400)
            return
        except RuntimeError as exc:
            code = 503 if "draining" in str(exc) else 400
            self._json({"error": str(exc)}, code)
            return
        if not req.wait(REQUEST_TIMEOUT_S):
            self._json({"id": req.request_id, "error": "timed out"}, 504)
            return
        if req.error is not None:
            self._json({"id": req.request_id, "error": req.error}, 500)
            return
        body = {"id": req.request_id,
                "timing": {"total_s":
                           round(req.t_done - req.t_enqueue, 6)}}
        if route == "/embed":
            body["vectors"] = np.asarray(
                req.result["vectors"]).tolist()
        else:
            body["ids"] = np.asarray(req.result["ids"]).tolist()
            body["scores"] = np.asarray(
                req.result["scores"]).tolist()
        self._json(body)

    def _line(self, obj) -> None:
        try:
            self.wfile.write((json.dumps(obj) + "\n").encode())
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-stream; the engine finishes anyway


def _metrics_mod():
    from deeplearning4j_tpu.telemetry import metrics
    return metrics


class ServingMetrics:
    """The /metrics backing store for one engine: a MetricsRegistry
    whose request-latency histograms are fed LIVE from the telemetry
    event stream (`Recorder.add_sink` — no log parse, no device sync
    on the scrape path) and whose fleet gauges (queue depth, page-pool
    occupancy, weight generation, per-replica liveness) are scraped
    from `engine.stats()` at collection time."""

    def __init__(self, engine):
        m = _metrics_mod()
        self.engine = engine
        self.registry = m.MetricsRegistry()
        self.requests = self.registry.counter(
            "serving_requests_total",
            "served requests by outcome (ok/error) and kind")
        self.latency = self.registry.histogram(
            "serving_request_latency_seconds",
            "end-to-end request latency (enqueue -> result)")
        self.queue_wait = self.registry.histogram(
            "serving_request_queue_seconds",
            "request wait before its batch cut")
        self.ttft = self.registry.histogram(
            "serving_ttft_seconds",
            "generation time-to-first-token (enqueue -> first token)")
        self.anomalies = self.registry.counter(
            "serving_anomalies_total",
            "anomaly events on the record, by kind (telemetry/trace.py)")
        self.queue_depth = self.registry.gauge(
            "serving_queue_depth", "pending requests in the batcher")
        self.replicas = self.registry.gauge(
            "serving_replicas", "replica count by lifecycle state")
        self.replica_up = self.registry.gauge(
            "serving_replica_up",
            "1 while the replica is alive and serving traffic")
        self.replica_beat_age = self.registry.gauge(
            "serving_replica_last_beat_age_seconds",
            "seconds since the replica's last heartbeat")
        self.weight_generation = self.registry.gauge(
            "serving_weight_generation",
            "published WeightStore generation (hot-swap flips bump it)")
        self.weight_step = self.registry.gauge(
            "serving_weight_step",
            "training step of the published weight set")
        self.pool_pages = self.registry.gauge(
            "serving_page_pool_pages",
            "KV-cache page pool occupancy (in_use/total/peak)")
        self.trace_count = self.registry.gauge(
            "serving_trace_count",
            "compiled-trace count (frozen after warmup: any growth "
            "mid-traffic is a retrace)")
        self.pool_occupancy = self.registry.gauge(
            "serving_page_occupancy_ratio",
            "KV-cache page pool fill fraction (pages_in_use / "
            "pages_total) per replica")
        self.spec_accepted = self.registry.gauge(
            "serving_speculative_accepted_tokens_per_step",
            "running mean tokens emitted per verify step per active "
            "slot (1.0 = the non-speculative floor)")
        self.spec_acceptance = self.registry.gauge(
            "serving_speculative_acceptance_rate",
            "fraction of offered draft tokens the verify step accepted")
        self.hbm_live = self.registry.gauge(
            "serving_hbm_live_bytes",
            "total live device bytes (jax.live_arrays) from the "
            "engine's memory sampler — the ledger's ground truth")
        self.hbm_limit = self.registry.gauge(
            "serving_hbm_limit_bytes",
            "per-device HBM capacity (backend memory_stats "
            "bytes_limit; absent off-TPU)")
        self.hbm_headroom = self.registry.gauge(
            "serving_hbm_headroom_ratio",
            "per-device 1 - bytes_in_use/bytes_limit — the "
            "autoscaler's memory signal (absent off-TPU)")
        self.ledger_bytes = self.registry.gauge(
            "serving_memory_ledger_bytes",
            "live bytes attributed per subsystem (params/opt_state/"
            "kv_pages/prefetch/activations/other)")
        self.mfu_live = self.registry.gauge(
            "serving_mfu_live",
            "model FLOPs utilization over recent forwards: cost-book "
            "flops / measured forward seconds / device peak FLOPs")
        # the embedding-engine data-movement surface: one latency
        # histogram per span kind (gather / scatter_add / ann_probe —
        # the registered recorder spans) plus a bytes-moved counter,
        # fed live off the span event stream like the request latencies
        self.embed_spans = {
            name: self.registry.histogram(
                f"serving_embedding_{name}_seconds",
                f"embedding-engine {name} span wall time")
            for name in ("gather", "scatter_add", "ann_probe")
        }
        self.embed_bytes = self.registry.counter(
            "serving_embedding_bytes_total",
            "bytes moved by embedding-engine spans, by span kind")
        # recent per-forward MFU samples, fed by on_event (cheap append);
        # the gauge publishes their mean at collection time
        from collections import deque
        self._mfu_window = deque(maxlen=64)
        self.registry.add_collector(self._collect)

    # ------------------------------------------------------- live events
    def on_event(self, ev: dict) -> None:
        """The recorder sink: request events feed the latency
        histograms on the emitting thread; anomaly events bump their
        counter. Cheap (a few float appends) and exception-contained by
        the recorder."""
        kind = ev.get("event")
        if kind == "request":
            outcome = "ok" if ev.get("ok") else "error"
            self.registry.inc(self.requests, 1.0, outcome=outcome,
                              kind=str(ev.get("kind", "predict")))
            if "total_s" in ev:
                self.registry.observe(self.latency, float(ev["total_s"]))
            if "queue_s" in ev:
                self.registry.observe(self.queue_wait,
                                      float(ev["queue_s"]))
            if "ttft_s" in ev:
                self.registry.observe(self.ttft, float(ev["ttft_s"]))
            if "forward_s" in ev and "bucket" in ev:
                self._observe_mfu(ev)
        elif kind == "anomaly":
            self.registry.inc(self.anomalies, 1.0,
                              kind=str(ev.get("kind", "unknown")))
        elif kind == "span" and ev.get("name") in self.embed_spans:
            name = ev["name"]
            if "seconds" in ev:
                self.registry.observe(self.embed_spans[name],
                                      float(ev["seconds"]))
            if ev.get("bytes"):
                self.registry.inc(self.embed_bytes, float(ev["bytes"]),
                                  span=str(name))

    def _observe_mfu(self, ev: dict) -> None:
        """Per-forward MFU sample: the warmed cost book's flops for the
        request's bucket over the measured forward wall time and the
        device's peak. Dict lookups only — no analysis on this path."""
        book = getattr(self.engine, "costbook", None)
        peak = float(getattr(self.engine, "peak_flops", 0.0) or 0.0)
        if book is None or peak <= 0.0:
            return
        flops = book.flops("forward", ev["bucket"])
        seconds = float(ev["forward_s"] or 0.0)
        if flops <= 0.0 or seconds <= 0.0:
            return
        self._mfu_window.append(book.mfu(flops, seconds, peak))

    # ---------------------------------------------------------- scraping
    def _collect(self) -> None:
        stats = self.engine.stats()
        self.queue_depth.set(stats.get("queue_depth", 0))
        self.trace_count.set(stats.get("trace_count", 0))
        weights = stats.get("weights") or {}
        self.weight_generation.set(weights.get("generation", 0))
        self.weight_step.set(weights.get("step", 0))
        states: dict = {}
        self.replica_up.clear()
        self.replica_beat_age.clear()
        for row in stats.get("fleet", []):
            states[row["state"]] = states.get(row["state"], 0) + 1
            idx = str(row.get("index", "?"))
            up = 1.0 if row.get("alive") and row.get("state") == "serving" \
                else 0.0
            self.replica_up.set(up, replica=idx)
            if "last_beat_age_s" in row:
                self.replica_beat_age.set(row["last_beat_age_s"],
                                          replica=idx)
        self.replicas.clear()
        for state, n in states.items():
            self.replicas.set(n, state=state)
        self.pool_pages.clear()
        self.pool_occupancy.clear()
        for i, pool in enumerate(stats.get("page_pools", [])):
            for field in ("pages_in_use", "pages_total", "pages_peak"):
                if field in pool:
                    self.pool_pages.set(pool[field], replica=str(i),
                                        kind=field)
            total = float(pool.get("pages_total", 0) or 0)
            if total:
                self.pool_occupancy.set(
                    float(pool.get("pages_in_use", 0)) / total,
                    replica=str(i))
        spec = stats.get("speculative") or {}
        if spec.get("enabled"):
            self.spec_accepted.set(
                float(spec.get("accepted_tokens_per_step", 0.0)))
            self.spec_acceptance.set(
                float(spec.get("draft_acceptance_rate", 0.0)))
        mem = stats.get("memory") or {}
        if mem:
            self.hbm_live.set(float(mem.get("live_array_bytes", 0)))
            self.ledger_bytes.clear()
            for subsystem, nbytes in (mem.get("ledger") or {}).items():
                self.ledger_bytes.set(float(nbytes),
                                      subsystem=str(subsystem))
            self.hbm_limit.clear()
            self.hbm_headroom.clear()
            for dev, row in (mem.get("devices") or {}).items():
                limit = float(row.get("bytes_limit", 0) or 0)
                if limit > 0:
                    self.hbm_limit.set(limit, device=str(dev))
                    self.hbm_headroom.set(
                        1.0 - float(row.get("bytes_in_use", 0)) / limit,
                        device=str(dev))
        if self._mfu_window:
            window = list(self._mfu_window)
            self.mfu_live.set(sum(window) / len(window))

    def render(self) -> str:
        return self.registry.render()


def _argmax_last(out: np.ndarray):
    """Class index/indices over the last axis — the `predict` view of
    the raw output ([V] -> int, [T, V] -> [T] ints)."""
    if out.ndim == 0:
        return float(out)
    am = np.argmax(out, axis=-1)
    return int(am) if am.ndim == 0 else am.tolist()


class ServingServer:
    """Facade owning the HTTP listener; the engine is constructed by the
    caller (CLI `serve` or a test) so its lattice/replica/checkpoint
    config stays explicit."""

    def __init__(self, engine, port: int = 0, host: str = "127.0.0.1"):
        self.engine = engine
        self.draining = False
        # the /metrics surface: live latency histograms off the
        # telemetry stream + fleet gauges scraped from engine.stats()
        self.metrics = ServingMetrics(engine)
        recorder = getattr(engine, "recorder", None)
        if recorder is not None and hasattr(recorder, "add_sink"):
            recorder.add_sink(self.metrics.on_event)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.serving_server = self
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ServingServer":
        self.engine.start()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()
        return self

    def begin_drain(self) -> None:
        """Stop admitting /predict requests; the engine flushes what it
        already accepted (POST /drain, and the first phase of stop())."""
        self.draining = True

    def stop(self, drain_timeout: float = 30.0) -> None:
        """Graceful shutdown: drain the engine (every admitted request
        completes or fails loudly), then close the listener."""
        self.begin_drain()
        self.engine.drain(drain_timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
