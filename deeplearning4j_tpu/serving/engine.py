"""Replica dispatch: jitted forward workers consuming bucket batches.

One `_Replica` = one worker thread owning its own jit wrapper of the
net's pure inference function (`net.inference_fn()` — nn/multilayer.py
and nn/graph.py). The dispatcher pulls assembled batches from the
Batcher and deals them round-robin over the replicas, so host-side
padding/assembly of the next batch overlaps the current forward (XLA
releases the GIL during execution). On the distributed runtime each
process runs its own engine behind its own port (the CLI `serve
--multiprocess` plan); the per-process telemetry suffix from
distributed/bootstrap keeps the logs attributable.

Zero-retrace accounting: every bucket shape is compiled ONCE during
`warmup` under a telemetry span named "compile"; the traced function
also bumps a host-side trace counter at trace time, so tier-1 can
assert the compile-span count AND the trace count stay frozen across a
replayed mixed-length trace (the lattice contract in
serving/buckets.py).

Failure containment (ARCHITECTURE §Serving failure modes): a worker
dying mid-batch fails THAT batch's requests (each future carries the
error, the HTTP layer returns 500, a telemetry `error` event keeps the
full traceback) and the replica keeps serving the next batch — one
poisoned input cannot take the replica down with it.

Fleet operations (ISSUE 13, serving/fleet.py): every replica reads its
params through the engine's double-buffered `WeightStore` exactly ONCE
per batch — the live hot-swap flips that reference between batches, so
each request event records the single coherent `weight_gen` it served
against. Replicas carry a lifecycle (warming → serving → draining /
dead → retired), a heartbeat, and an optional chaos injector
(replica-scoped `distributed/faults.py` specs); the `FleetSupervisor`
reaps dead/hung replicas (queued batches drain back to the batcher),
respawns them through the SAME jit wrappers (zero new traces), and the
autoscale loop grows/drains the replica set through `add_replica` /
`retire_replica` (a retiring replica finishes its queued work first).

jax imports stay inside methods: the module is importable under the
graftlint AST stubs and costs tools nothing.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from collections import deque

import numpy as np

from deeplearning4j_tpu.serving.batcher import (Batch, Batcher, DecodeSlots,
                                                GenRequest)
from deeplearning4j_tpu.serving.buckets import Bucket, BucketLattice
from deeplearning4j_tpu.serving.fleet import (ReplicaFaultInjector,
                                              ReplicaKilled, WeightStore,
                                              restore_for_serving)
from deeplearning4j_tpu.serving.kvcache import CachePlan
from deeplearning4j_tpu.serving.speculative import (NgramProposer,
                                                    accept_greedy)
from deeplearning4j_tpu.telemetry.costbook import CostBook, peak_flops
from deeplearning4j_tpu.telemetry.memstat import (MemoryLedger,
                                                  MemorySampler)


class QueueFullError(RuntimeError):
    """Generation admission refused: the page pool and the pending queue
    are both full — the front door's graceful 503, never a crash."""


class _Replica:
    """One forward worker: its own jit wrapper (own compile cache), its
    own batch queue, its own trace counter. Params come from the
    engine's double-buffered `WeightStore` — read ONCE per batch, so a
    hot-swap flip lands between batches, never inside one. Lifecycle
    (`warming`/`serving`/`draining`/`dead`/`retired`), heartbeat, and
    the chaos injector are what `serving/fleet.FleetSupervisor`
    supervises."""

    def __init__(self, index: int, net, recorder, weights: WeightStore,
                 faults: ReplicaFaultInjector | None = None):
        import jax

        self.index = index
        self.net = net
        self.recorder = recorder
        self.weights = weights
        self.faults = faults
        self.queue: queue.Queue = queue.Queue()
        # guards the stats counters below: they are `+=`-mutated on the
        # worker thread and read by describe()/stats() on the control
        # plane — bare read-modify-write loses updates (G025)
        self._mu = threading.Lock()
        self.trace_count = 0
        self.served = 0
        self.failed = 0
        self.batches_run = 0
        self.alive = True
        self.lifecycle = "warming"
        self.last_beat = 0.0
        self.current_batch: Batch | None = None
        self._seen_shapes: set = set()
        fwd = net.inference_fn()

        def counted(params, state, x, mask=None):
            # runs at TRACE time only: the retrace tell the zero-retrace
            # gate asserts on (one bump per compiled bucket shape)
            with self._mu:
                self.trace_count += 1
            return fwd(params, state, x, mask)

        self._jit = jax.jit(counted)
        self._thread: threading.Thread | None = None

    # ----------------------------------------------------------- forward
    def _shape_key(self, feats: np.ndarray, mask) -> tuple:
        return (feats.shape, str(feats.dtype), mask is not None)

    def fail_batch(self, batch: Batch, exc_or_msg, *, clock,
                   weight_gen: int | None = None) -> None:
        """Fail every request of one batch loudly (worker death, reaped
        hang, drain with no live replica) — each future carries the
        error, telemetry keeps the record."""
        with self._mu:
            self.failed += batch.n_real
        if isinstance(exc_or_msg, BaseException):
            self.recorder.error(f"replica:{self.index}", exc=exc_or_msg)
            err = "".join(traceback.format_exception_only(
                type(exc_or_msg), exc_or_msg)).strip()
        else:
            err = str(exc_or_msg)
            self.recorder.error(f"replica:{self.index}", error=err)
        t_done = clock()
        for r in batch.requests:
            r.error = err
            r.t_done = t_done
            self._request_event(r, batch, None, ok=False, error=err,
                               weight_gen=weight_gen)
            r.done.set()

    def run_batch(self, batch: Batch, *, clock, sequence: bool) -> None:
        # the cross-thread correlation handoff: the batcher rooted this
        # batch's trace (queue -> batch_assemble on the dispatcher
        # thread); everything this replica thread emits for it —
        # forward, nested compile, the per-request events — joins that
        # tree (telemetry/recorder.py; warmup batches carry no trace
        # and the context is a no-op)
        with self.recorder.trace(batch.trace_id,
                                 parent_id=batch.parent_span):
            self._run_batch(batch, clock=clock, sequence=sequence)

    def _run_batch(self, batch: Batch, *, clock, sequence: bool) -> None:
        rec = self.recorder
        self.current_batch = batch
        self.last_beat = clock()
        with self._mu:
            self.batches_run += 1
        # the ONE read of the published weight set this batch serves
        # against — the hot-swap flip is atomic relative to it
        ws = self.weights.current
        key = self._shape_key(batch.features, batch.mask)
        first = key not in self._seen_shapes
        t0 = time.perf_counter()
        try:
            with rec.span("forward", bucket=list(batch.bucket.key()),
                          replica=self.index, n_real=batch.n_real):
                if self.faults is not None:
                    self.faults.check(self.index, "batch",
                                      self.batches_run)
                if first:
                    # the first execution of a bucket shape includes its
                    # compile — span-named so the warmed compile count is
                    # reconstructable from telemetry alone
                    with rec.span("compile",
                                  bucket=list(batch.bucket.key()),
                                  replica=self.index):
                        y = self._jit(ws.params, ws.state,
                                      batch.features, batch.mask)
                        rows = np.asarray(y)  # batch-boundary fetch
                    self._seen_shapes.add(key)
                else:
                    y = self._jit(ws.params, ws.state,
                                  batch.features, batch.mask)
                    rows = np.asarray(y)  # batch-boundary fetch
        except ReplicaKilled as exc:
            # injected replica death: the in-flight batch fails (the
            # BOUNDED failure set), the thread dies; the supervisor
            # requeues this replica's queue and respawns it. Death is
            # marked BEFORE the futures complete so a waiter that saw
            # the failure also sees the dead replica.
            self.current_batch = None
            self.alive = False
            self.lifecycle = "dead"
            self.fail_batch(batch, exc, clock=clock,
                            weight_gen=ws.generation)
            raise
        except Exception as exc:  # worker dying mid-batch: contain it
            self.fail_batch(batch, exc, clock=clock,
                            weight_gen=ws.generation)
            self.current_batch = None
            return
        forward_s = time.perf_counter() - t0
        t_done = clock()
        for i, r in enumerate(batch.requests):
            out = rows[i]
            if sequence:
                out = out[:r.length]  # drop time padding
            r.result = out
            r.t_done = t_done
            with self._mu:
                self.served += 1
            self._request_event(r, batch, forward_s, ok=True,
                               weight_gen=ws.generation)
            r.done.set()
        self.current_batch = None
        self.last_beat = clock()

    def _request_event(self, r, batch: Batch, forward_s, *, ok,
                       error: str | None = None,
                       weight_gen: int | None = None) -> None:
        """The per-request telemetry record — the ONLY source the
        traffic-replay bench reads latency from (serving/replay.py
        reconstructs p50/p99/QPS from these events alone). `weight_gen`
        names the published weight generation the batch served against
        — the hot-swap flip's visibility in the request stream."""
        fields = dict(
            ok=ok, bucket=list(batch.bucket.key()),
            replica=self.index, n_real=batch.n_real,
            queue_s=round(r.t_assembled - r.t_enqueue, 6),
            batch_assemble_s=round(batch.assemble_seconds, 6),
            total_s=round(r.t_done - r.t_enqueue, 6))
        if weight_gen is None:
            weight_gen = self.weights.generation
        fields["weight_gen"] = weight_gen
        if forward_s is not None:
            fields["forward_s"] = round(forward_s, 6)
        if batch.bucket.seq is not None:
            fields["seq_len"] = r.length
            fields["padded_seq"] = batch.bucket.seq
        if error:
            fields["error"] = error
        self.recorder.request(r.request_id, **fields)

    # ---------------------------------------------------------- lifecycle
    def start(self, clock, sequence: bool) -> None:
        self.last_beat = clock()

        def loop():
            while True:
                batch = self.queue.get()
                if batch is None:
                    if self.lifecycle != "dead":
                        self.lifecycle = "retired"
                    return
                try:
                    self.run_batch(batch, clock=clock, sequence=sequence)
                except ReplicaKilled:
                    return  # dead: the supervisor requeues + respawns

        self.lifecycle = "serving"
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"serve-replica-{self.index}")
        self._thread.start()

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def describe(self, now: float | None = None) -> dict:
        """One /healthz row: lifecycle, counters, heartbeat age."""
        with self._mu:
            out = {"index": self.index, "state": self.lifecycle,
                   "alive": self.alive, "served": self.served,
                   "failed": self.failed,
                   "batches_run": self.batches_run}
        if now is not None:
            out["last_beat_age_s"] = round(max(0.0, now - self.last_beat),
                                           3)
        return out


class InferenceEngine:
    """The serving core: Batcher in front, round-robin replicas behind.

    `net` is shared by every replica (params are immutable device
    arrays; each replica jits its own wrapper). `checkpoint` resumes the
    net from an Orbax host-checkpoint directory before any compile —
    the PR 6 portable-restore seed: a checkpoint saved by a training
    fleet restores into this single serving process."""

    def __init__(self, net, lattice: BucketLattice | None = None, *,
                 replicas: int = 1, max_wait_ms: float = 5.0,
                 sequence: bool = False, checkpoint: str | None = None,
                 faults=None, recorder=None):
        if recorder is None:
            from deeplearning4j_tpu.telemetry import get_default

            recorder = get_default()
        self.recorder = recorder
        self.sequence = sequence
        if net.params is None:
            net.init()
        self.restored_step = 0
        if checkpoint is not None:
            # any-mesh checkpoint restore through the blessed fleet
            # path: the checkpoint may have been written by a 2x4
            # training fleet; the portable resharding engine (reshard/)
            # plans its placement onto this serving process's own
            # one-device mesh and orbax reads only the slices it needs
            self.restored_step = restore_for_serving(net, checkpoint)
        self.net = net
        # the double-buffered published weight set every replica reads
        # from — live hot-swap (serving/fleet.hot_swap) flips it
        self.weights = WeightStore(net.params, net.state,
                                   step=self.restored_step)
        # the memory-observability spine: the ledger attributes live
        # bytes (the weight-store read tracks hot-swaps), the sampler
        # emits `memory` events at warmup and on the stats tick, the
        # costbook harvests XLA cost/memory analyses at warmup
        ledger = MemoryLedger()
        ledger.register("params", lambda: self.weights.current.params)
        self.memsampler = MemorySampler(recorder, ledger)
        self.costbook = CostBook(recorder)
        self.peak_flops = 0.0  # set at warmup from the device kind
        self.lattice = lattice or BucketLattice()
        self.batcher = Batcher(self.lattice, max_wait_ms,
                               sequence=sequence, recorder=recorder)
        self._clock = self.batcher._clock
        self._faults = None
        if faults is not None:
            self._faults = (faults if isinstance(faults,
                                                 ReplicaFaultInjector)
                            else ReplicaFaultInjector(faults, recorder))
        self._rcv = threading.Condition()
        self._next_index = 0
        self._replicas = [self._new_replica()
                          for _ in range(max(1, int(replicas)))]
        self._rr = 0
        self._dispatcher: threading.Thread | None = None
        self._started = False
        self._draining = False
        self._feature_template: np.ndarray | None = None
        recorder.meta(role="serving-engine", replicas=len(self._replicas),
                      sequence=sequence, lattice=self.lattice.describe(),
                      restored_step=self.restored_step)

    def _new_replica(self) -> _Replica:
        r = _Replica(self._next_index, self.net, self.recorder,
                     self.weights, faults=self._faults)
        self._next_index += 1
        return r

    # ------------------------------------------------------------- warmup
    def warmup(self, example_features) -> int:
        """Compile every lattice bucket on every replica once, BEFORE
        traffic. `example_features` is one request-shaped array (its
        trailing dims + dtype define the bucket shapes). Returns the
        number of (replica, bucket) compiles performed; after this the
        compile-span count and trace count are frozen — a mixed-length
        replay must add zero."""
        ex = np.asarray(example_features)
        self._feature_template = ex
        compiles = sum(self._warm_replica(r) for r in self._replicas)
        if compiles:
            # one post-warmup snapshot: every serving run's telemetry
            # carries at least one `memory` event, and the MFU gauge
            # gets its device-peak denominator
            import jax

            self.peak_flops = peak_flops(
                getattr(jax.devices()[0], "device_kind", ""))
            self.memsampler.sample("warmup", peak_flops=self.peak_flops)
        return compiles

    def _warm_replica(self, replica: _Replica) -> int:
        """Compile every lattice bucket this replica has not yet seen
        (warmup, add_replica, and the supervisor's respawn-re-warm all
        route here; a respawn compiles NOTHING — the jit executables
        survive a thread death in-process)."""
        ex = self._feature_template
        if ex is None:
            return 0
        replica.lifecycle = ("warming" if replica.lifecycle != "serving"
                             else replica.lifecycle)
        tail = ex.shape[1:] if self.sequence else ex.shape
        ws = self.weights.current
        compiles = 0
        for bucket in self.lattice.shapes():
            feats, mask = self._zeros_for(bucket, tail, ex.dtype)
            batch = Batch(bucket, feats, mask, [])
            key = replica._shape_key(feats, mask)
            if key in replica._seen_shapes:
                continue
            with self.recorder.span("compile",
                                    bucket=list(bucket.key()),
                                    replica=replica.index,
                                    warmup=True):
                y = replica._jit(ws.params, ws.state,
                                 batch.features, batch.mask)
                np.asarray(y)  # batch-boundary fetch
            replica._seen_shapes.add(key)
            compiles += 1
            # cost-book harvest rides the warmup compile: lower() after
            # the warm call is a jaxpr-cache hit (no re-trace — the
            # frozen trace counters stay frozen), and the analyses come
            # from the AOT executable XLA already built
            self.costbook.record("forward", list(bucket.key()),
                                 replica._jit,
                                 (ws.params, ws.state, batch.features,
                                  batch.mask))
        return compiles

    def _zeros_for(self, bucket: Bucket, tail: tuple, dtype):
        if self.sequence:
            feats = np.zeros((bucket.batch, bucket.seq) + tail, dtype)
            mask = np.ones((bucket.batch, bucket.seq), np.float32)
            return feats, mask
        return np.zeros((bucket.batch,) + tail, dtype), None

    # ------------------------------------------------------------ serving
    def start(self) -> "InferenceEngine":
        if self._started:
            return self
        self._started = True
        for r in self._replicas:
            r.start(self._clock, self.sequence)

        def dispatch():
            while True:
                batch = self.batcher.next_batch()
                if batch is None:
                    break  # draining and empty
                if not self._dispatch_batch(batch):
                    # draining with zero live replicas left
                    self._replicas[0].fail_batch(
                        batch, "no live replica during drain",
                        clock=self._clock)
            with self._rcv:
                targets = list(self._replicas)
            for r in targets:
                r.queue.put(None)

        self._dispatcher = threading.Thread(target=dispatch, daemon=True,
                                            name="serve-dispatch")
        self._dispatcher.start()
        return self

    def _dispatch_batch(self, batch: Batch) -> bool:
        """Round-robin one batch over LIVE replicas only — dead,
        draining, and retired workers never receive new batches. The
        pick AND the queue put happen under the replica lock, so a
        concurrent retire's drain sentinel can never slip between them
        and strand the batch behind it. Blocks (condition-notified by
        respawn/add) while no replica is servable; returns False only
        when the engine is draining and no replica will come back."""
        with self._rcv:
            while True:
                serving = [r for r in self._replicas
                           if r.alive and r.lifecycle == "serving"]
                if serving:
                    replica = serving[self._rr % len(serving)]
                    self._rr += 1
                    replica.queue.put(batch)
                    return True
                if self._draining:
                    return False
                self._rcv.wait(timeout=0.05)

    def submit(self, features, mask=None, request_id=None):
        features = np.asarray(features)
        if self._feature_template is not None:
            # the lattice freezes dtype as much as shape: a JSON round
            # trip arrives float64/int64 and would miss every warmed
            # cache entry (one silent retrace per bucket) — cast to the
            # warmup template's dtype at the door
            features = features.astype(self._feature_template.dtype,
                                       copy=False)
        return self.batcher.submit(features, mask=mask,
                                   request_id=request_id)

    def predict(self, features, mask=None, timeout: float = 30.0):
        """Synchronous convenience: submit + wait. Raises on a failed
        batch (the worker-death path) or timeout."""
        req = self.submit(features, mask=mask)
        if not req.wait(timeout):
            raise TimeoutError(f"request {req.request_id} timed out "
                               f"after {timeout}s")
        if req.error is not None:
            raise RuntimeError(f"request {req.request_id} failed: "
                               f"{req.error}")
        return req.result

    # ---------------------------------------------------- fleet lifecycle
    # The FleetSupervisor's contract surface (serving/fleet.py): reap a
    # dead/hung worker, respawn it, grow/drain the replica set.

    def fleet_workers(self) -> list:
        with self._rcv:
            return list(self._replicas)

    def fleet_snapshot(self) -> dict:
        """The autoscale loop's engine-side signals."""
        with self._rcv:
            n_serving = sum(1 for r in self._replicas
                            if r.alive and r.lifecycle == "serving")
            n_replicas = sum(1 for r in self._replicas
                             if r.alive and r.lifecycle
                             in ("warming", "serving"))
        return {"queue_depth": self.batcher.depth,
                "n_serving": n_serving, "n_replicas": n_replicas}

    def fleet_reap(self, replica: _Replica, reason: str = "died") -> int:
        """Take a dead/hung replica out of dispatch: fail its in-flight
        batch (the hang case — a wedged thread can never complete it;
        the kill path already failed its own), then drain its QUEUED
        batches back to the batcher FIFO head, where live replicas pick
        them up. Returns the requeued request count."""
        with self._rcv:
            replica.alive = False
            replica.lifecycle = "dead"
        inflight = replica.current_batch
        if inflight is not None:
            replica.current_batch = None
            replica.fail_batch(inflight, f"replica {replica.index} "
                                         f"reaped ({reason})",
                               clock=self._clock)
        requeued = []
        while True:
            try:
                b = replica.queue.get_nowait()
            except queue.Empty:
                break
            if b is not None:
                requeued.extend(b.requests)
        if requeued:
            self.batcher.requeue(requeued)
        return len(requeued)

    def fleet_respawn(self, replica: _Replica) -> _Replica:
        """Bring a reaped replica back: fresh queue + thread over the
        SAME jit wrappers (compiled executables survive a thread death
        in-process), warmup re-run before re-admission — it compiles
        nothing, so the trace counter stays frozen — then re-admit to
        dispatch."""
        replica.queue = queue.Queue()
        replica.batches_run = 0
        replica.current_batch = None
        replica.alive = True
        replica.lifecycle = "warming"
        self._warm_replica(replica)
        replica.start(self._clock, self.sequence)
        with self._rcv:
            self._rcv.notify_all()
        return replica

    def add_replica(self) -> _Replica:
        """Scale UP one replica: build, warm every lattice bucket
        (warmup-flagged compiles — the zero-retrace accounting is
        unchanged), start, admit to dispatch."""
        with self._rcv:
            replica = self._new_replica()
            self._replicas.append(replica)
        self._warm_replica(replica)
        if self._started:
            replica.start(self._clock, self.sequence)
        with self._rcv:
            self._rcv.notify_all()
        return replica

    def retire_replica(self) -> _Replica | None:
        """Scale DOWN one replica, gracefully: the newest serving
        replica stops receiving batches (lifecycle `draining`),
        finishes everything already in its queue, and its thread exits
        — queued work is never dropped. The last live replica is never
        retired."""
        with self._rcv:
            serving = [r for r in self._replicas
                       if r.alive and r.lifecycle == "serving"]
            if len(serving) <= 1:
                return None
            replica = serving[-1]
            replica.lifecycle = "draining"
            # the sentinel lands under the same lock the dispatcher
            # picks+puts under: no batch can follow it into the queue
            replica.queue.put(None)
        return replica

    # -------------------------------------------------------------- drain
    def drain(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: refuse new requests, flush every pending
        batch through the replicas, join the threads. Every admitted
        request completes (or fails loudly) before this returns."""
        self._draining = True
        with self._rcv:
            self._rcv.notify_all()
        self.batcher.close()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout)
        for r in self.fleet_workers():
            if r.lifecycle == "dead":
                continue  # a wedged thread never joins; it is a daemon
            r.join(timeout)
        self.recorder.event("span", name="drain", ok=True, seconds=0.0,
                            served=self.served, failed=self.failed)

    # -------------------------------------------------------------- stats
    @property
    def trace_count(self) -> int:
        return sum(r.trace_count for r in self._replicas)

    @property
    def served(self) -> int:
        return sum(r.served for r in self._replicas)

    @property
    def failed(self) -> int:
        return sum(r.failed for r in self._replicas)

    def stats(self) -> dict:
        now = self._clock()
        with self._rcv:
            fleet = [r.describe(now) for r in self._replicas]
        # the stats tick is a blessed batch boundary: rate-limited, so
        # a tight scrape loop cannot turn /stats into a live-array walk
        self.memsampler.maybe_sample("stats_tick")
        return {
            "replicas": len(fleet),
            "served": self.served,
            "failed": self.failed,
            "queue_depth": self.batcher.depth,
            "trace_count": self.trace_count,
            "restored_step": self.restored_step,
            "lattice": self.lattice.describe(),
            "sequence": self.sequence,
            "fleet": fleet,
            "weights": self.weights.describe(),
            "memory": self.memsampler.last,
            "peak_flops": self.peak_flops,
        }


# --------------------------------------------------------------- generation

class _GenWorker:
    """One generation replica: its own KV-cache allocation, page pool,
    decode-slot state machine, and jit wrappers (own compile cache, own
    trace counter) for the prefill and decode steps.

    The loop interleaves chunked prefills into the running decode batch:
    each iteration admits what the pool allows, runs at most ONE prompt
    chunk (so a long prefill never starves decoding slots), then one
    decode step over all slots. The decode step's shape is FIXED —
    [n_slots] tokens and positions against the [n_slots, capacity]
    cache — so it compiles exactly once; inactive rows decode a dummy
    token whose K/V write is routed to the scratch position
    (capacity - 1), which any real tenant overwrites before it can ever
    be attended (a token's own K/V lands at its position in the same
    step that reads it).

    SPECULATIVE MODE (speculative_k >= 2): the decode step is replaced
    by a fixed-shape VERIFY step over [n_slots, k] token windows
    (nn/decode.make_verify_fn). Each active slot's window is its true
    last token followed by k-1 host-side n-gram drafts
    (serving/speculative.NgramProposer); the greedy acceptance mask
    (`accept_greedy`) turns the k verify rows into 1..k emitted tokens
    — each one a model argmax given exactly its prefix, so the emitted
    stream is bit-identical to non-speculative greedy. The zero-retrace
    contract is untouched: ONE verify shape compiles at warmup (instead
    of the decode shape — only the step actually used is warmed), the
    DecodeSlots machine is unchanged, and a rejected draft's cache
    pages stay reserved by the up-front admission reservation (released
    on the same completion/failure path as ever; its stale K/V is
    invisible under key_limit until the next window overwrites it).

    kv_dtype="int8" swaps every cache entry for the quantized paged
    form ({"k","k_scale","v","v_scale"}) through the same three step
    fns — shapes still lattice/page-grid points, ~4x less HBM/slot."""

    def __init__(self, index: int, net, lattice: BucketLattice,
                 plan: CachePlan, prefill_chunk: int, max_queue: int,
                 recorder, weights: WeightStore | None = None,
                 faults: ReplicaFaultInjector | None = None,
                 speculative_k: int = 0, costbook: CostBook | None = None):
        import jax
        import jax.numpy as jnp

        self.index = index
        self.net = net
        self.lattice = lattice
        self.plan = plan
        self.prefill_chunk = prefill_chunk
        self.max_queue = max_queue
        self.recorder = recorder
        self.costbook = costbook or CostBook(recorder)
        self.weights = weights or WeightStore(net.params, net.state)
        self.faults = faults
        self.pool = plan.make_pool()
        self.slots = DecodeSlots(plan.n_slots)
        self.kv_dtype = plan.kv_dtype
        self.speculative_k = int(speculative_k)
        self.cache = net.init_kv_cache(plan.n_slots, plan.capacity,
                                       plan.kv_dtype, plan.page_size)
        # guards the stats counters below (worker-thread `+=` vs
        # describe()/stats() reads on the control plane — G025); never
        # held across a jit call or a queue wait, so it orders freely
        # against `_cv`
        self._mu = threading.Lock()
        self.trace_count = 0
        self.served = 0
        self.failed = 0
        self.tokens_out = 0
        self.decode_steps_run = 0
        self.verify_steps_run = 0
        self.slot_steps = 0  # (active slot, verify step) pairs
        self.accepted_tokens = 0
        self.drafted_tokens = 0
        self.draft_overhead_s = 0.0
        self.proposer = NgramProposer()
        self.alive = True
        self.lifecycle = "warming"
        self.last_beat = 0.0
        self.current_batch = None  # the active row set mid-step
        self._seen_shapes: set = set()
        self.pending: deque[GenRequest] = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._thread: threading.Thread | None = None

        prefill_raw = net.prefill_fn(plan.kv_dtype, plan.page_size)
        step_raw = net.incremental_decode_fn(plan.kv_dtype,
                                             plan.page_size)

        def counted_prefill(params, state, cache, padded_tokens,
                            bucket_kmask, rows, start, last_idx):
            with self._mu:  # trace-time bump: the retrace tell
                self.trace_count += 1
            probs, cache = prefill_raw(params, state, cache,
                                       padded_tokens, bucket_kmask,
                                       rows, start, last_idx)
            return jnp.argmax(probs, axis=-1).astype(jnp.int32), cache

        def counted_step(params, state, cache, padded_tokens, pos):
            with self._mu:
                self.trace_count += 1
            probs, cache = step_raw(params, state, cache, padded_tokens,
                                    pos)
            return jnp.argmax(probs, axis=-1).astype(jnp.int32), cache

        self._prefill_jit = jax.jit(counted_prefill)
        self._decode_jit = jax.jit(counted_step)
        self._verify_jit = None
        if self.speculative_k >= 2:
            verify_raw = net.verify_decode_fn(plan.kv_dtype,
                                              plan.page_size)

            def counted_verify(params, state, cache, padded_windows,
                               pos):
                with self._mu:
                    self.trace_count += 1
                probs, cache = verify_raw(params, state, cache,
                                          padded_windows, pos)
                # [B, k] argmax rows: the acceptance mask's input —
                # k verification verdicts for one batch-boundary fetch
                return (jnp.argmax(probs, axis=-1).astype(jnp.int32),
                        cache)

            self._verify_jit = jax.jit(counted_verify)

    # ---------------------------------------------------------- planning
    def chunk_buckets(self) -> list:
        """The prefill shapes this worker ever compiles (the lattice
        owns the set — buckets.prefill_buckets)."""
        return self.lattice.prefill_buckets(self.prefill_chunk)

    def _next_chunk_len(self, remaining: int) -> int:
        """Bucket-shaped length of the next prompt chunk: full chunks
        while more than a chunk remains, the bucketed remainder last."""
        if remaining >= self.prefill_chunk:
            return self.prefill_chunk
        return self.lattice.seq_bucket(remaining)

    # ------------------------------------------------------------ warmup
    def warmup(self, clock) -> int:
        """Compile every (prefill-bucket) shape plus the decode step
        once, before traffic. After this the trace counter is frozen —
        a mixed prompt/output-length replay must add zero."""
        compiles = 0
        ws = self.weights.current
        rows = np.zeros(1, np.int32)
        start = np.zeros(1, np.int32)
        for Tb in self.chunk_buckets():
            key = ("prefill", Tb)
            if key in self._seen_shapes:
                continue
            with self.recorder.span("compile", kind="prefill",
                                    bucket=[1, Tb], replica=self.index,
                                    warmup=True):
                tok, cache = self._prefill_jit(
                    ws.params, ws.state, self.cache,
                    np.zeros((1, Tb), np.int32),
                    np.zeros((1, Tb), np.float32), rows, start,
                    np.asarray([Tb - 1], np.int32))
                np.asarray(tok)  # batch-boundary fetch
                self.cache = cache
            self._seen_shapes.add(key)
            compiles += 1
            # warmup-time cost harvest: lower() is a jaxpr-cache hit
            # (no trace-counter bump), the analyses are XLA's own
            self.costbook.record("prefill", [1, Tb], self._prefill_jit,
                                 (ws.params, ws.state, self.cache,
                                  np.zeros((1, Tb), np.int32),
                                  np.zeros((1, Tb), np.float32), rows,
                                  start, np.asarray([Tb - 1], np.int32)))
        # only the step this worker actually runs is warmed: the decode
        # shape in plain mode, the [B, k] verify shape in speculative
        # mode — either way ONE step compile, and the trace counter is
        # frozen after it
        if self._verify_jit is not None:
            if "verify" not in self._seen_shapes:
                B, K = self.plan.n_slots, self.speculative_k
                scratch = np.full(B, self.plan.capacity - 1, np.int32)
                with self.recorder.span("compile", kind="verify",
                                        shape=[B, K, self.plan.capacity],
                                        replica=self.index, warmup=True):
                    tok, cache = self._verify_jit(
                        ws.params, ws.state, self.cache,
                        np.zeros((B, K), np.int32), scratch)
                    np.asarray(tok)  # batch-boundary fetch
                    self.cache = cache
                self._seen_shapes.add("verify")
                compiles += 1
                self.costbook.record(
                    "verify", [B, K, self.plan.capacity],
                    self._verify_jit,
                    (ws.params, ws.state, self.cache,
                     np.zeros((B, K), np.int32), scratch))
        elif "decode" not in self._seen_shapes:
            B = self.plan.n_slots
            scratch = np.full(B, self.plan.capacity - 1, np.int32)
            with self.recorder.span("compile", kind="decode",
                                    shape=[B, self.plan.capacity],
                                    replica=self.index, warmup=True):
                tok, cache = self._decode_jit(
                    ws.params, ws.state, self.cache,
                    np.zeros(B, np.int32), scratch)
                np.asarray(tok)  # batch-boundary fetch
                self.cache = cache
            self._seen_shapes.add("decode")
            compiles += 1
            self.costbook.record("decode", [B, self.plan.capacity],
                                 self._decode_jit,
                                 (ws.params, ws.state, self.cache,
                                  np.zeros(B, np.int32), scratch))
        return compiles

    # --------------------------------------------------------- admission
    def submit(self, req: GenRequest) -> None:
        pages = self.plan.request_pages(
            self.lattice.seq_bucket(req.prompt_len), req.max_new_tokens)
        if pages > self.pool.n_pages:
            raise ValueError(
                f"request needs {pages} cache pages but the replica "
                f"pool holds {self.pool.n_pages} — prompt + "
                "max_new_tokens exceed the cache geometry")
        with self._cv:
            if self._closed:
                raise RuntimeError("engine is draining; request refused")
            if len(self.pending) >= self.max_queue:
                raise QueueFullError(
                    "generation queue full (page pool saturated and "
                    f"{self.max_queue} requests already waiting) — "
                    "retry later")
            self.pending.append(req)
            self._cv.notify_all()

    def _admit(self, clock) -> None:
        with self._cv:
            while self.pending:
                idx = self.slots.free_index()
                if idx is None:
                    return
                req = self.pending[0]
                pages = self.plan.request_pages(
                    self.lattice.seq_bucket(req.prompt_len),
                    req.max_new_tokens)
                if not self.pool.try_reserve(pages):
                    return  # pool exhausted: stays queued, not dropped
                self.pending.popleft()
                req.t_admitted = clock()
                self.slots.admit(idx, req, pages)
                self.recorder.event("page_pool", replica=self.index,
                                    **self.pool.describe())

    # ----------------------------------------------------------- compute
    def _run_prefill_chunk_bucketed(self, slot_idx: int, clock) -> None:
        """One bucket-shaped prompt chunk for one slot, under the
        request's trace context — its prefill_chunk spans (and any
        nested compile) correlate to the request id the final `request`
        event carries, so a generation's prefill tree reconstructs from
        the JSONL alone."""
        req = self.slots.slots[slot_idx].request
        with self.recorder.trace(req.request_id):
            self._prefill_chunk_inner(slot_idx, clock)

    def _prefill_chunk_inner(self, slot_idx: int, clock) -> None:
        """The chunk itself. The argument
        names and the enclosing span keep the G017/G019 contract
        visible: the jit sees only padded bucket arrays, and the only
        host fetch is the one batch-boundary np.asarray of the
        next-token id."""
        slot = self.slots.slots[slot_idx]
        req = slot.request
        L = req.prompt_len
        Tc = self._next_chunk_len(L - slot.start)
        n_real = min(Tc, L - slot.start)
        padded_tokens = np.zeros((1, Tc), np.int32)
        padded_tokens[0, :n_real] = req.tokens[slot.start:slot.start
                                               + n_real]
        bucket_kmask = np.zeros((1, Tc), np.float32)
        bucket_kmask[0, :n_real] = 1.0
        final = slot.start + n_real >= L
        key = ("prefill", Tc)
        first = key not in self._seen_shapes
        ws = self.weights.current
        try:
            with self.recorder.span("prefill_chunk", bucket=[1, Tc],
                                    start=slot.start, replica=self.index,
                                    final=final):
                args = (ws.params, ws.state, self.cache,
                        padded_tokens, bucket_kmask,
                        np.asarray([slot_idx], np.int32),
                        np.asarray([slot.start], np.int32),
                        np.asarray([n_real - 1], np.int32))
                if first:
                    with self.recorder.span("compile", kind="prefill",
                                            bucket=[1, Tc],
                                            replica=self.index):
                        tok, cache = self._prefill_jit(*args)
                        toks = np.asarray(tok)  # batch-boundary fetch
                    self._seen_shapes.add(key)
                else:
                    tok, cache = self._prefill_jit(*args)
                    toks = np.asarray(tok)  # batch-boundary fetch
        except Exception as exc:
            self._fail_slot(slot_idx, exc, clock)
            return
        self.cache = cache
        slot.start += n_real
        if final:
            # the prompt's last forward row IS the first generated
            # token: TTFT is this chunk's completion
            slot.pos = L
            slot.last_token = int(toks[0])
            now = clock()
            req.emit(slot.last_token, now)
            with self._mu:
                self.tokens_out += 1
            self._maybe_complete(slot_idx, clock)

    def _decode_batch_step(self, active: list, clock) -> None:
        """One fixed-shape decode step over every slot row; `active`
        names the rows whose outputs are real. One np.asarray for the
        whole [n_slots] next-token vector — the batch-boundary fetch —
        then host-side distribution to the slots."""
        B = self.plan.n_slots
        padded_tokens = np.zeros(B, np.int32)
        pos = np.full(B, self.plan.capacity - 1, np.int32)  # scratch
        for i in active:
            slot = self.slots.slots[i]
            padded_tokens[i] = slot.last_token
            pos[i] = slot.pos
        ws = self.weights.current
        with self._mu:
            self.decode_steps_run += 1
        self.current_batch = list(active)
        try:
            with self.recorder.span("decode_step", replica=self.index,
                                    n_active=len(active)):
                if self.faults is not None:
                    self.faults.check(self.index, "decode",
                                      self.decode_steps_run)
                tok, cache = self._decode_jit(
                    ws.params, ws.state, self.cache,
                    padded_tokens, pos)
                toks = np.asarray(tok)  # batch-boundary fetch
        except ReplicaKilled as exc:
            # injected mid-decode death: every active slot fails (pages
            # released by _fail_slot), the thread dies; the supervisor
            # respawns — pending requests stay queued with the worker.
            # Death is marked BEFORE the futures complete so a waiter
            # that saw the failure also sees the dead worker.
            self.current_batch = None
            self.alive = False
            self.lifecycle = "dead"
            for i in active:
                self._fail_slot(i, exc, clock)
            raise
        except Exception as exc:
            for i in active:
                self._fail_slot(i, exc, clock)
            self.current_batch = None
            return
        self.current_batch = None
        self.cache = cache
        now = clock()
        for i in active:
            slot = self.slots.slots[i]
            slot.pos += 1
            slot.last_token = int(toks[i])
            slot.request.emit(slot.last_token, now)
            with self._mu:
                self.tokens_out += 1
            self._maybe_complete(i, clock)

    def _speculative_batch_step(self, active: list, clock) -> None:
        """One fixed-shape VERIFY step over every slot row: each active
        row's window is [last_token, d_1..d_{k-1}] (host-side n-gram
        drafts), inactive rows ride the scratch position like the plain
        decode step. ONE np.asarray fetches the whole [n_slots, k]
        argmax matrix; the greedy acceptance mask then emits 1..k
        tokens per slot — every accepted draft is a decode step that
        never ran. Draft proposal cost is metered host-side
        (`draft_overhead_us`) and the per-step `draft` telemetry event
        is what the replay's accepted_tokens_per_step headline
        reconstructs from."""
        B, K = self.plan.n_slots, self.speculative_k
        padded_windows = np.zeros((B, K), np.int32)
        pos = np.full(B, self.plan.capacity - 1, np.int32)  # scratch
        t_draft = time.perf_counter()
        drafts: dict = {}
        for i in active:
            slot = self.slots.slots[i]
            req = slot.request
            d = self.proposer.propose(
                list(req.tokens) + list(req.emitted), K - 1)
            drafts[i] = d
            padded_windows[i, 0] = slot.last_token
            padded_windows[i, 1:] = d
            pos[i] = slot.pos
        draft_s = time.perf_counter() - t_draft
        ws = self.weights.current
        with self._mu:
            self.decode_steps_run += 1
            self.verify_steps_run += 1
        self.current_batch = list(active)
        try:
            with self.recorder.span("verify_step", replica=self.index,
                                    n_active=len(active), k=K):
                if self.faults is not None:
                    self.faults.check(self.index, "decode",
                                      self.decode_steps_run)
                tok, cache = self._verify_jit(
                    ws.params, ws.state, self.cache,
                    padded_windows, pos)
                toks = np.asarray(tok)  # [B, k] batch-boundary fetch
        except ReplicaKilled as exc:
            # same containment contract as the plain decode step
            self.current_batch = None
            self.alive = False
            self.lifecycle = "dead"
            for i in active:
                self._fail_slot(i, exc, clock)
            raise
        except Exception as exc:
            for i in active:
                self._fail_slot(i, exc, clock)
            self.current_batch = None
            return
        self.current_batch = None
        self.cache = cache
        now = clock()
        step_emitted = 0
        step_accepted = 0
        for i in active:
            slot = self.slots.slots[i]
            req = slot.request
            budget = req.max_new_tokens - len(req.emitted)
            _n_acc, emitted = accept_greedy(drafts[i], toks[i])
            take = min(len(emitted), budget)
            for t in emitted[:take]:
                req.emit(int(t), now)
                with self._mu:
                    self.tokens_out += 1
            slot.pos += take
            slot.last_token = int(emitted[take - 1])
            step_emitted += take
            step_accepted += take - 1  # drafts accepted (bonus aside)
            self._maybe_complete(i, clock)
        with self._mu:
            self.accepted_tokens += step_emitted
            self.drafted_tokens += (K - 1) * len(active)
            self.slot_steps += len(active)
            self.draft_overhead_s += draft_s
        self.recorder.event("draft", replica=self.index, k=K,
                            n_active=len(active), emitted=step_emitted,
                            accepted=step_accepted,
                            drafted=(K - 1) * len(active),
                            overhead_us=round(draft_s * 1e6, 2))

    # -------------------------------------------------------- lifecycle
    def _maybe_complete(self, slot_idx: int, clock) -> None:
        slot = self.slots.slots[slot_idx]
        req = slot.request
        if len(req.emitted) < req.max_new_tokens:
            return
        self.pool.release(self.slots.release(slot_idx))
        self.recorder.event("page_pool", replica=self.index,
                            **self.pool.describe())
        req.finish(clock())
        with self._mu:
            self.served += 1
        self._request_event(req, ok=True)

    def _fail_slot(self, slot_idx: int, exc: Exception, clock) -> None:
        """Mid-decode death containment: the slot's request fails
        loudly, its PAGES ARE RELEASED, and the worker keeps serving —
        mirror of the predict replica's worker-death contract."""
        slot = self.slots.slots[slot_idx]
        req = slot.request
        self.pool.release(self.slots.release(slot_idx))
        self.recorder.event("page_pool", replica=self.index,
                            **self.pool.describe())
        self.recorder.error(f"gen-replica:{self.index}", exc=exc)
        err = "".join(traceback.format_exception_only(type(exc),
                                                      exc)).strip()
        req.finish(clock(), error=err)
        with self._mu:
            self.failed += 1
        self._request_event(req, ok=False, error=err)

    def _request_event(self, req: GenRequest, *, ok,
                       error: str | None = None) -> None:
        fields = dict(
            ok=ok, kind="generate", replica=self.index,
            # the generation trace key: the prefill_chunk spans carry
            # the same id, so the request's tree joins by trace_id even
            # though completion happens on the decode path
            trace_id=req.request_id,
            prompt_len=req.prompt_len,
            prompt_bucket=self.lattice.seq_bucket(req.prompt_len),
            new_tokens=len(req.emitted),
            queue_s=round(req.t_admitted - req.t_enqueue, 6),
            total_s=round(req.t_done - req.t_enqueue, 6))
        if req.t_first_token:
            fields["ttft_s"] = round(req.t_first_token - req.t_enqueue, 6)
        if error:
            fields["error"] = error
        self.recorder.request(req.request_id, **fields)

    def start(self, clock) -> None:
        self.last_beat = clock()

        def loop():
            while True:
                self.last_beat = clock()
                self._admit(clock)
                progressed = False
                try:
                    pi = self.slots.next_prefill()
                    if pi is not None:
                        self._run_prefill_chunk_bucketed(pi, clock)
                        progressed = True
                    active = self.slots.decoding()
                    if active:
                        if self._verify_jit is not None:
                            self._speculative_batch_step(active, clock)
                        else:
                            self._decode_batch_step(active, clock)
                        progressed = True
                except ReplicaKilled:
                    return  # dead: the fleet supervisor respawns
                if progressed:
                    continue
                with self._cv:
                    if self._closed and not self.pending \
                            and not self.slots.busy():
                        if self.lifecycle != "dead":
                            self.lifecycle = "retired"
                        return
                    if not self.pending or self.slots.free_index() is None:
                        self._cv.wait(timeout=0.05)

        self.lifecycle = "serving"
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"gen-replica-{self.index}")
        self._thread.start()

    def respawn(self, clock) -> None:
        """Fleet-supervisor respawn: fresh thread over the SAME jit
        wrappers and KV cache (warmup re-runs and compiles nothing —
        every shape is already seen), pending requests continue from
        the worker's own queue."""
        self.alive = True
        self.lifecycle = "warming"
        self.current_batch = None
        with self._mu:
            self.decode_steps_run = 0
        self.warmup(clock)
        self.start(clock)
        with self._cv:
            self._cv.notify_all()

    def reap(self, reason: str, clock) -> int:
        """Fail every occupied slot (pages released) — the hang case,
        where the wedged thread can never finish them. Pending requests
        stay queued for the respawned thread. Returns 0 (nothing is
        re-dispatched elsewhere: the queue IS this worker's)."""
        self.alive = False
        self.lifecycle = "dead"
        self.current_batch = None
        exc = RuntimeError(f"gen replica {self.index} reaped ({reason})")
        for i, s in enumerate(self.slots.slots):
            if s is not None:
                self._fail_slot(i, exc, clock)
        return 0

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    @property
    def depth(self) -> int:
        with self._cv:
            return len(self.pending)

    def describe(self, now: float | None = None) -> dict:
        with self._mu:
            out = {"index": self.index, "state": self.lifecycle,
                   "alive": self.alive, "served": self.served,
                   "failed": self.failed,
                   "decode_steps_run": self.decode_steps_run}
            if self.speculative_k >= 2:
                out["verify_steps_run"] = self.verify_steps_run
                out["accepted_tokens"] = self.accepted_tokens
                out["drafted_tokens"] = self.drafted_tokens
        if now is not None:
            out["last_beat_age_s"] = round(max(0.0, now - self.last_beat),
                                           3)
        return out


class GenerationEngine:
    """Autoregressive generation serving: prefill/decode split over a
    paged KV cache, continuous batching across decode slots.

    Where `InferenceEngine` answers one forward per request, this
    engine holds each admitted request in a decode SLOT: its prompt
    prefills the slot's cache rows chunk-by-chunk (interleaved into the
    running decode batch so long prompts don't stall everyone else's
    tokens), then every decode step extends all active slots by one
    token — N generated tokens cost prefill + N single-token steps, not
    N full-sequence forwards. Shapes are lattice/page-grid points only:
    warmup compiles each (replica, prefill-bucket) and the (replica,
    decode-shape) once, and the trace counters stay frozen under mixed
    traffic (tier-1 asserts it). Page accounting and the
    exhaustion-queues-not-crashes contract live in serving/kvcache.py."""

    def __init__(self, net, lattice: BucketLattice, *, slots: int = 4,
                 max_new_tokens: int = 16, page_size: int = 16,
                 pool_pages: int | None = None,
                 prefill_chunk: int | None = None, max_queue: int = 64,
                 replicas: int = 1, checkpoint: str | None = None,
                 speculative_k: int = 0, kv_dtype: str = "f32",
                 faults=None, recorder=None):
        if recorder is None:
            from deeplearning4j_tpu.telemetry import get_default

            recorder = get_default()
        self.recorder = recorder
        if lattice.seq_lens is None:
            raise ValueError("generation needs a sequence lattice "
                             "(BucketLattice with seq_lens)")
        if net.params is None:
            net.init()
        self.restored_step = 0
        if checkpoint is not None:
            # the blessed fleet restore path (any-mesh checkpoint onto
            # this process's own one-device mesh)
            self.restored_step = restore_for_serving(net, checkpoint)
        self.net = net
        self.weights = WeightStore(net.params, net.state,
                                   step=self.restored_step)
        self._faults = None
        if faults is not None:
            self._faults = (faults if isinstance(faults,
                                                 ReplicaFaultInjector)
                            else ReplicaFaultInjector(faults, recorder))
        self.lattice = lattice
        chunk = (lattice.max_seq if prefill_chunk is None
                 else int(prefill_chunk))
        lattice.prefill_buckets(chunk)  # raises on a non-lattice chunk
        self.speculative_k = int(speculative_k)
        if self.speculative_k == 1 or self.speculative_k < 0:
            raise ValueError(
                "speculative_k is 0 (off) or >= 2 (a window of the true "
                f"last token plus k-1 drafts); got {speculative_k}")
        if self.speculative_k > int(max_new_tokens):
            raise ValueError(
                f"speculative_k {speculative_k} exceeds max_new_tokens "
                f"{max_new_tokens} — a window can never be used whole")
        self.plan = CachePlan(lattice.max_seq, max_new_tokens,
                              max(1, int(slots)), page_size,
                              pool_pages=pool_pages, kv_dtype=kv_dtype)
        self._clock = time.monotonic
        self.costbook = CostBook(recorder)
        self._workers = [
            _GenWorker(i, net, lattice, self.plan, chunk, max_queue,
                       recorder, weights=self.weights,
                       faults=self._faults,
                       speculative_k=self.speculative_k,
                       costbook=self.costbook)
            for i in range(max(1, int(replicas)))]
        # ledger: published weights + every worker's paged KV cache
        ledger = MemoryLedger()
        ledger.register("params", lambda: self.weights.current.params)
        ledger.register("kv_pages",
                        lambda: [w.cache for w in self._workers])
        self.memsampler = MemorySampler(recorder, ledger)
        self.peak_flops = 0.0  # set at warmup from the device kind
        self._rr = 0
        self._started = False
        recorder.meta(role="generation-engine",
                      replicas=len(self._workers),
                      lattice=lattice.describe(),
                      cache=self.plan.describe(),
                      prefill_chunk=chunk,
                      speculative_k=self.speculative_k,
                      restored_step=self.restored_step)

    # ------------------------------------------------------------- warmup
    def warmup(self) -> int:
        """Compile every (replica, prefill-bucket) and (replica,
        decode-shape) once. Returns the compile count; after this the
        trace counters are frozen."""
        compiles = sum(w.warmup(self._clock) for w in self._workers)
        if compiles:
            import jax

            self.peak_flops = peak_flops(
                getattr(jax.devices()[0], "device_kind", ""))
            self.memsampler.sample("warmup", peak_flops=self.peak_flops)
        return compiles

    # ------------------------------------------------------------ serving
    def start(self) -> "GenerationEngine":
        if self._started:
            return self
        self._started = True
        for w in self._workers:
            w.start(self._clock)
        return self

    def submit_generate(self, tokens, max_new_tokens: int | None = None,
                        request_id: str | None = None) -> GenRequest:
        """Admit one generation request. Validates the prompt against
        the lattice (a too-long prompt is the client's 400) and the
        output budget against the cache geometry; a saturated pool +
        full queue raises QueueFullError (HTTP 503), never a crash."""
        toks = np.asarray(tokens)
        if toks.ndim != 1:
            raise ValueError(
                f"generation takes a [T] token prompt; got {toks.shape}")
        self.lattice.seq_bucket(int(toks.shape[0]))  # raises if too long
        max_new = (self.plan.max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        if not 1 <= max_new <= self.plan.max_new_tokens:
            raise ValueError(
                f"max_new_tokens must be in [1, "
                f"{self.plan.max_new_tokens}]; got {max_new}")
        from deeplearning4j_tpu.serving.batcher import _req_counter

        req = GenRequest(tokens=toks.astype(np.int32),
                         max_new_tokens=max_new,
                         request_id=request_id
                         or f"g{next(_req_counter)}",
                         t_enqueue=self._clock())
        worker = self._workers[self._rr % len(self._workers)]
        self._rr += 1
        worker.submit(req)
        return req

    def generate(self, tokens, max_new_tokens: int | None = None,
                 timeout: float = 60.0) -> list:
        """Synchronous convenience: submit + wait; returns the emitted
        token list. Raises on failure or timeout."""
        req = self.submit_generate(tokens, max_new_tokens)
        if not req.wait(timeout):
            raise TimeoutError(f"request {req.request_id} timed out "
                               f"after {timeout}s")
        if req.error is not None:
            raise RuntimeError(f"request {req.request_id} failed: "
                               f"{req.error}")
        return list(req.emitted)

    # ---------------------------------------------------- fleet lifecycle
    def fleet_workers(self) -> list:
        return list(self._workers)

    def fleet_snapshot(self) -> dict:
        n_serving = sum(1 for w in self._workers
                        if w.alive and w.lifecycle == "serving")
        return {"queue_depth": sum(w.depth for w in self._workers),
                "n_serving": n_serving, "n_replicas": n_serving}

    def fleet_reap(self, worker, reason: str = "died") -> int:
        return worker.reap(reason, self._clock)

    def fleet_respawn(self, worker) -> None:
        worker.respawn(self._clock)

    # -------------------------------------------------------------- drain
    def drain(self, timeout: float = 30.0) -> None:
        for w in self._workers:
            w.close()
        for w in self._workers:
            if w.lifecycle == "dead":
                continue  # a wedged daemon thread never joins
            w.join(timeout)
        self.recorder.event("span", name="drain", ok=True, seconds=0.0,
                            served=self.served, failed=self.failed)

    # -------------------------------------------------------------- stats
    @property
    def trace_count(self) -> int:
        return sum(w.trace_count for w in self._workers)

    @property
    def served(self) -> int:
        return sum(w.served for w in self._workers)

    @property
    def failed(self) -> int:
        return sum(w.failed for w in self._workers)

    def stats(self) -> dict:
        now = self._clock()
        pools = [w.pool.describe() for w in self._workers]
        # rate-limited memory tick — the stats path is a batch boundary
        self.memsampler.maybe_sample("stats_tick")
        return {
            "replicas": len(self._workers),
            "served": self.served,
            "failed": self.failed,
            "tokens_out": sum(w.tokens_out for w in self._workers),
            "queue_depth": sum(w.depth for w in self._workers),
            "trace_count": self.trace_count,
            "restored_step": self.restored_step,
            "lattice": self.lattice.describe(),
            "cache": self.plan.describe(),
            "page_pools": pools,
            "fleet": [w.describe(now) for w in self._workers],
            "weights": self.weights.describe(),
            "generate": True,
            "speculative": self._speculative_stats(),
            "memory": self.memsampler.last,
            "peak_flops": self.peak_flops,
        }

    def _speculative_stats(self) -> dict:
        """The /stats + /metrics acceptance surface: emitted tokens per
        verify step (the headline), draft acceptance rate, and the
        host-side proposer overhead — all zero/off when speculative
        decoding is disabled."""
        if self.speculative_k < 2:
            return {"enabled": False, "k": 0}
        steps = sum(w.verify_steps_run for w in self._workers)
        slot_steps = sum(w.slot_steps for w in self._workers)
        accepted = sum(w.accepted_tokens for w in self._workers)
        drafted = sum(w.drafted_tokens for w in self._workers)
        # tokens beyond the 1-per-slot-step a plain decode would emit
        bonus = accepted - slot_steps
        overhead = sum(w.draft_overhead_s for w in self._workers)
        return {
            "enabled": True, "k": self.speculative_k,
            "verify_steps": steps,
            "accepted_tokens_per_step": (round(accepted / slot_steps, 4)
                                         if slot_steps else 0.0),
            "draft_acceptance_rate": (round(bonus / drafted, 4)
                                      if drafted else 0.0),
            "draft_overhead_us_total": round(overhead * 1e6, 1),
        }
