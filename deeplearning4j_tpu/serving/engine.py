"""Replica dispatch: jitted forward workers consuming bucket batches.

One `_Replica` = one worker thread owning its own jit wrapper of the
net's pure inference function (`net.inference_fn()` — nn/multilayer.py
and nn/graph.py). The dispatcher pulls assembled batches from the
Batcher and deals them round-robin over the replicas, so host-side
padding/assembly of the next batch overlaps the current forward (XLA
releases the GIL during execution). On the distributed runtime each
process runs its own engine behind its own port (the CLI `serve
--multiprocess` plan); the per-process telemetry suffix from
distributed/bootstrap keeps the logs attributable.

Zero-retrace accounting: every bucket shape is compiled ONCE during
`warmup` under a telemetry span named "compile"; the traced function
also bumps a host-side trace counter at trace time, so tier-1 can
assert the compile-span count AND the trace count stay frozen across a
replayed mixed-length trace (the lattice contract in
serving/buckets.py).

Failure containment (ARCHITECTURE §Serving failure modes): a worker
dying mid-batch fails THAT batch's requests (each future carries the
error, the HTTP layer returns 500, a telemetry `error` event keeps the
full traceback) and the replica keeps serving the next batch — one
poisoned input cannot take the replica down with it.

jax imports stay inside methods: the module is importable under the
graftlint AST stubs and costs tools nothing.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback

import numpy as np

from deeplearning4j_tpu.serving.batcher import Batch, Batcher
from deeplearning4j_tpu.serving.buckets import Bucket, BucketLattice


class _Replica:
    """One forward worker: its own jit wrapper (own compile cache), its
    own batch queue, its own trace counter."""

    def __init__(self, index: int, net, recorder):
        import jax

        self.index = index
        self.net = net
        self.recorder = recorder
        self.queue: queue.Queue = queue.Queue()
        self.trace_count = 0
        self.served = 0
        self.failed = 0
        self._seen_shapes: set = set()
        fwd = net.inference_fn()

        def counted(params, state, x, mask=None):
            # runs at TRACE time only: the retrace tell the zero-retrace
            # gate asserts on (one bump per compiled bucket shape)
            self.trace_count += 1
            return fwd(params, state, x, mask)

        self._jit = jax.jit(counted)
        self._thread: threading.Thread | None = None

    # ----------------------------------------------------------- forward
    def _shape_key(self, feats: np.ndarray, mask) -> tuple:
        return (feats.shape, str(feats.dtype), mask is not None)

    def run_batch(self, batch: Batch, *, clock, sequence: bool) -> None:
        rec = self.recorder
        key = self._shape_key(batch.features, batch.mask)
        first = key not in self._seen_shapes
        t0 = time.perf_counter()
        try:
            with rec.span("forward", bucket=list(batch.bucket.key()),
                          replica=self.index, n_real=batch.n_real):
                if first:
                    # the first execution of a bucket shape includes its
                    # compile — span-named so the warmed compile count is
                    # reconstructable from telemetry alone
                    with rec.span("compile",
                                  bucket=list(batch.bucket.key()),
                                  replica=self.index):
                        y = self._jit(self.net.params, self.net.state,
                                      batch.features, batch.mask)
                        rows = np.asarray(y)  # batch-boundary fetch
                    self._seen_shapes.add(key)
                else:
                    y = self._jit(self.net.params, self.net.state,
                                  batch.features, batch.mask)
                    rows = np.asarray(y)  # batch-boundary fetch
        except Exception as exc:  # worker dying mid-batch: contain it
            self.failed += batch.n_real
            rec.error(f"replica:{self.index}", exc=exc)
            err = "".join(traceback.format_exception_only(type(exc), exc)).strip()
            t_done = clock()
            for r in batch.requests:
                r.error = err
                r.t_done = t_done
                self._request_event(r, batch, None, ok=False, error=err)
                r.done.set()
            return
        forward_s = time.perf_counter() - t0
        t_done = clock()
        for i, r in enumerate(batch.requests):
            out = rows[i]
            if sequence:
                out = out[:r.length]  # drop time padding
            r.result = out
            r.t_done = t_done
            self.served += 1
            self._request_event(r, batch, forward_s, ok=True)
            r.done.set()

    def _request_event(self, r, batch: Batch, forward_s, *, ok,
                       error: str | None = None) -> None:
        """The per-request telemetry record — the ONLY source the
        traffic-replay bench reads latency from (serving/replay.py
        reconstructs p50/p99/QPS from these events alone)."""
        fields = dict(
            ok=ok, bucket=list(batch.bucket.key()),
            replica=self.index, n_real=batch.n_real,
            queue_s=round(r.t_assembled - r.t_enqueue, 6),
            batch_assemble_s=round(batch.assemble_seconds, 6),
            total_s=round(r.t_done - r.t_enqueue, 6))
        if forward_s is not None:
            fields["forward_s"] = round(forward_s, 6)
        if batch.bucket.seq is not None:
            fields["seq_len"] = r.length
            fields["padded_seq"] = batch.bucket.seq
        if error:
            fields["error"] = error
        self.recorder.request(r.request_id, **fields)

    # ---------------------------------------------------------- lifecycle
    def start(self, clock, sequence: bool) -> None:
        def loop():
            while True:
                batch = self.queue.get()
                if batch is None:
                    return
                self.run_batch(batch, clock=clock, sequence=sequence)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"serve-replica-{self.index}")
        self._thread.start()

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


class InferenceEngine:
    """The serving core: Batcher in front, round-robin replicas behind.

    `net` is shared by every replica (params are immutable device
    arrays; each replica jits its own wrapper). `checkpoint` resumes the
    net from an Orbax host-checkpoint directory before any compile —
    the PR 6 portable-restore seed: a checkpoint saved by a training
    fleet restores into this single serving process."""

    def __init__(self, net, lattice: BucketLattice | None = None, *,
                 replicas: int = 1, max_wait_ms: float = 5.0,
                 sequence: bool = False, checkpoint: str | None = None,
                 recorder=None):
        if recorder is None:
            from deeplearning4j_tpu.telemetry import get_default

            recorder = get_default()
        self.recorder = recorder
        self.sequence = sequence
        if net.params is None:
            net.init()
        self.restored_step = 0
        if checkpoint is not None:
            # any-mesh checkpoint restore: the checkpoint may have been
            # written by a 2x4 training fleet; the portable resharding
            # engine (reshard/) plans its placement onto this serving
            # process's one-device mesh and orbax reads only the slices
            # it needs — the train-anywhere/serve-here handoff, with the
            # reshard_plan on the telemetry record
            import jax

            from deeplearning4j_tpu.parallel.mesh import make_mesh

            # this process's OWN first device: in a serving fleet
            # (serve --multiprocess) jax.devices()[0] belongs to rank 0
            # and is not addressable here
            self.restored_step = int(net.resume_from(
                checkpoint,
                target_mesh=make_mesh({"data": 1},
                                      devices=jax.local_devices())))
        self.net = net
        self.lattice = lattice or BucketLattice()
        self.batcher = Batcher(self.lattice, max_wait_ms,
                               sequence=sequence, recorder=recorder)
        self._clock = self.batcher._clock
        self._replicas = [_Replica(i, net, recorder)
                          for i in range(max(1, int(replicas)))]
        self._rr = 0
        self._dispatcher: threading.Thread | None = None
        self._started = False
        self._feature_template: np.ndarray | None = None
        recorder.meta(role="serving-engine", replicas=len(self._replicas),
                      sequence=sequence, lattice=self.lattice.describe(),
                      restored_step=self.restored_step)

    # ------------------------------------------------------------- warmup
    def warmup(self, example_features) -> int:
        """Compile every lattice bucket on every replica once, BEFORE
        traffic. `example_features` is one request-shaped array (its
        trailing dims + dtype define the bucket shapes). Returns the
        number of (replica, bucket) compiles performed; after this the
        compile-span count and trace count are frozen — a mixed-length
        replay must add zero."""
        ex = np.asarray(example_features)
        self._feature_template = ex
        tail = ex.shape[1:] if self.sequence else ex.shape
        compiles = 0
        for replica in self._replicas:
            for bucket in self.lattice.shapes():
                feats, mask = self._zeros_for(bucket, tail, ex.dtype)
                batch = Batch(bucket, feats, mask, [])
                key = replica._shape_key(feats, mask)
                if key in replica._seen_shapes:
                    continue
                with self.recorder.span("compile",
                                        bucket=list(bucket.key()),
                                        replica=replica.index,
                                        warmup=True):
                    y = replica._jit(self.net.params, self.net.state,
                                     batch.features, batch.mask)
                    np.asarray(y)  # batch-boundary fetch
                replica._seen_shapes.add(key)
                compiles += 1
        return compiles

    def _zeros_for(self, bucket: Bucket, tail: tuple, dtype):
        if self.sequence:
            feats = np.zeros((bucket.batch, bucket.seq) + tail, dtype)
            mask = np.ones((bucket.batch, bucket.seq), np.float32)
            return feats, mask
        return np.zeros((bucket.batch,) + tail, dtype), None

    # ------------------------------------------------------------ serving
    def start(self) -> "InferenceEngine":
        if self._started:
            return self
        self._started = True
        for r in self._replicas:
            r.start(self._clock, self.sequence)

        def dispatch():
            while True:
                batch = self.batcher.next_batch()
                if batch is None:
                    break  # draining and empty
                replica = self._replicas[self._rr % len(self._replicas)]
                self._rr += 1
                replica.queue.put(batch)
            for r in self._replicas:
                r.queue.put(None)

        self._dispatcher = threading.Thread(target=dispatch, daemon=True,
                                            name="serve-dispatch")
        self._dispatcher.start()
        return self

    def submit(self, features, mask=None, request_id=None):
        features = np.asarray(features)
        if self._feature_template is not None:
            # the lattice freezes dtype as much as shape: a JSON round
            # trip arrives float64/int64 and would miss every warmed
            # cache entry (one silent retrace per bucket) — cast to the
            # warmup template's dtype at the door
            features = features.astype(self._feature_template.dtype,
                                       copy=False)
        return self.batcher.submit(features, mask=mask,
                                   request_id=request_id)

    def predict(self, features, mask=None, timeout: float = 30.0):
        """Synchronous convenience: submit + wait. Raises on a failed
        batch (the worker-death path) or timeout."""
        req = self.submit(features, mask=mask)
        if not req.wait(timeout):
            raise TimeoutError(f"request {req.request_id} timed out "
                               f"after {timeout}s")
        if req.error is not None:
            raise RuntimeError(f"request {req.request_id} failed: "
                               f"{req.error}")
        return req.result

    # -------------------------------------------------------------- drain
    def drain(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: refuse new requests, flush every pending
        batch through the replicas, join the threads. Every admitted
        request completes (or fails loudly) before this returns."""
        self.batcher.close()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout)
        for r in self._replicas:
            r.join(timeout)
        self.recorder.event("span", name="drain", ok=True, seconds=0.0,
                            served=self.served, failed=self.failed)

    # -------------------------------------------------------------- stats
    @property
    def trace_count(self) -> int:
        return sum(r.trace_count for r in self._replicas)

    @property
    def served(self) -> int:
        return sum(r.served for r in self._replicas)

    @property
    def failed(self) -> int:
        return sum(r.failed for r in self._replicas)

    def stats(self) -> dict:
        return {
            "replicas": len(self._replicas),
            "served": self.served,
            "failed": self.failed,
            "queue_depth": self.batcher.depth,
            "trace_count": self.trace_count,
            "restored_step": self.restored_step,
            "lattice": self.lattice.describe(),
            "sequence": self.sequence,
        }
