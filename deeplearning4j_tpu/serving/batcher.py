"""Dynamic batching: single requests coalesce into bucket-shaped
batches under a max-wait deadline.

The state machine (documented in ARCHITECTURE §Serving):

    submit() appends a PendingRequest to a FIFO ->
    the dispatcher blocks in next_batch() ->
      CUT a batch when the compatible FIFO prefix fills the largest
      batch bucket, OR when the OLDEST pending request has waited
      max_wait (latency bound beats batch efficiency), OR on drain
      (close() flushes leftovers) ->
    assemble() pads the group into its lattice bucket (zero padding +
    a validity mask) and hands a Batch to the engine.

`plan_batch` — the cut decision — is a pure function of (pending, now),
so the deadline/coalescing logic is unit-tested with a fake clock and
no real sleeps; the Batcher wraps it in a condition variable for the
live threaded path. Assembly is host-side numpy only: the device never
sees a per-request array, just the padded bucket batch.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from deeplearning4j_tpu.serving.buckets import Bucket, BucketLattice

_req_counter = itertools.count()


@dataclass
class PendingRequest:
    """One admitted request: the raw (unpadded) features, timing marks,
    and the completion event the front-end blocks on."""

    features: np.ndarray
    mask: np.ndarray | None = None
    request_id: str = ""
    t_enqueue: float = 0.0
    # filled by the engine on completion
    t_assembled: float = 0.0
    t_done: float = 0.0
    result: np.ndarray | None = None
    error: str | None = None
    done: threading.Event = field(default_factory=threading.Event)

    def wait(self, timeout: float | None = None) -> bool:
        return self.done.wait(timeout)

    @property
    def length(self) -> int:
        """Time length for sequence requests (first axis)."""
        return int(self.features.shape[0])


@dataclass
class Batch:
    """One assembled bucket batch: padded arrays plus the requests whose
    rows they carry (row i of `features` is requests[i] for i < n_real;
    rows beyond are padding and are sliced off after the forward)."""

    bucket: Bucket
    features: np.ndarray
    mask: np.ndarray | None
    requests: list
    t_cut: float = 0.0
    assemble_seconds: float = 0.0
    # correlation handoff (telemetry/recorder.py): the trace this batch
    # roots and the span the replica thread's `forward` parents to —
    # the cut's `queue` -> `batch_assemble` chain and the forward/
    # request events become ONE tree across the thread boundary
    trace_id: str | None = None
    parent_span: str | None = None

    @property
    def n_real(self) -> int:
        return len(self.requests)


def _compatible(a: PendingRequest, b: PendingRequest,
                sequence: bool) -> bool:
    """Whether two requests can share a batch: same dtype and same
    trailing feature dims (sequence models may differ in length — the
    first axis — which padding absorbs; fixed-shape models must match
    exactly)."""
    if a.features.dtype != b.features.dtype:
        return False
    if sequence:
        return a.features.shape[1:] == b.features.shape[1:]
    return a.features.shape == b.features.shape


def plan_batch(pending, now: float, max_wait_s: float,
               lattice: BucketLattice, *, sequence: bool = False,
               closed: bool = False) -> int:
    """The cut decision — how many requests to take off the head of the
    FIFO right now (0 = keep waiting). Pure function of its arguments so
    the deadline/coalescing logic tests with a fake clock.

    Cuts happen when (in priority order):
      1. the compatible FIFO prefix fills the LARGEST batch bucket
         (a full batch never waits);
      2. the oldest pending request has waited `max_wait_s` — the
         latency deadline beats batch efficiency;
      3. the batcher is draining (`closed`): flush what's there.
    """
    if not pending:
        return 0
    head = pending[0]
    take = 1
    for req in itertools.islice(pending, 1, None):
        if take >= lattice.max_batch:
            break
        if not _compatible(head, req, sequence):
            break  # FIFO order preserved: an incompatible request ends
            # the group rather than being skipped over
        take += 1
    if take >= lattice.max_batch:
        return lattice.max_batch
    if closed:
        return take
    if now - head.t_enqueue >= max_wait_s:
        return take
    return 0


def assemble(requests: list, lattice: BucketLattice, *,
             sequence: bool = False) -> Batch:
    """Pad a compatible group into its bucket: zero padding on the batch
    axis (rows sliced off after the forward — inference-mode forwards
    are row-independent, proven at atol 0 in tier-1) and, for sequence
    models, zero padding on the time axis with a [B, T] validity mask
    (1 = real token) so masked attention never reads a padded key."""
    if not requests:
        raise ValueError("cannot assemble an empty batch")
    n = len(requests)
    if sequence:
        max_len = max(r.length for r in requests)
        bucket = lattice.select(n, max_len)
        feat0 = requests[0].features
        shape = (bucket.batch, bucket.seq) + feat0.shape[1:]
        features = np.zeros(shape, dtype=feat0.dtype)
        mask = np.zeros((bucket.batch, bucket.seq), dtype=np.float32)
        for i, r in enumerate(requests):
            features[i, :r.length] = r.features
            if r.mask is not None:
                mask[i, :r.length] = np.asarray(r.mask, np.float32)
            else:
                mask[i, :r.length] = 1.0
        # padding ROWS keep an all-zero mask: a fully-masked row is a
        # valid (if degenerate) sequence and its output is discarded
        return Batch(bucket, features, mask, list(requests))
    bucket = lattice.select(n, None)
    feat0 = requests[0].features
    features = np.zeros((bucket.batch,) + feat0.shape, dtype=feat0.dtype)
    for i, r in enumerate(requests):
        features[i] = r.features
    return Batch(bucket, features, None, list(requests))


@dataclass
class GenRequest:
    """One admitted generation request: the raw prompt tokens, the
    output budget, timing marks, the emitted-token record, and a
    per-request stream queue the HTTP handler drains (None-terminated)
    so tokens flow to the client as they decode."""

    tokens: np.ndarray            # [L] int prompt
    max_new_tokens: int = 16
    request_id: str = ""
    t_enqueue: float = 0.0
    t_admitted: float = 0.0
    t_first_token: float = 0.0    # TTFT mark: prefill's last chunk done
    t_done: float = 0.0
    emitted: list = field(default_factory=list)
    error: str | None = None
    done: threading.Event = field(default_factory=threading.Event)
    stream: queue.Queue = field(default_factory=queue.Queue)

    @property
    def prompt_len(self) -> int:
        return int(self.tokens.shape[0])

    def wait(self, timeout: float | None = None) -> bool:
        return self.done.wait(timeout)

    def emit(self, token: int, now: float) -> None:
        if not self.emitted:
            self.t_first_token = now
        self.emitted.append(int(token))
        self.stream.put(int(token))

    def finish(self, now: float, error: str | None = None) -> None:
        self.error = error
        self.t_done = now
        self.stream.put(None)     # stream sentinel: no more tokens
        self.done.set()


class _Slot:
    """One decode slot's live state: the request it carries, how far its
    prompt has prefilled (`start`), the position its NEXT token writes
    (`pos`), and the pages it holds."""

    __slots__ = ("request", "start", "pos", "pages", "last_token")

    def __init__(self, request: GenRequest, pages: int):
        self.request = request
        self.start = 0            # prompt tokens already prefilled
        self.pages = pages
        self.pos = 0              # next write position once decoding
        self.last_token: int | None = None


class DecodeSlots:
    """The decode-slot state machine (ARCHITECTURE §Serving prefill/
    decode): a fixed number of slots — the decode step's batch rows —
    each FREE, PREFILLING (start < prompt_len) or DECODING (prompt in
    cache, output budget unspent). Admission binds a free slot to a
    request (the caller reserves its pages first); `next_prefill` picks
    the OLDEST prefilling slot so the engine interleaves exactly one
    prompt chunk between decode steps; completion frees the slot and
    reports the pages to release. Pure bookkeeping — no locks, no
    device state — owned by one engine worker thread."""

    def __init__(self, n_slots: int):
        if n_slots < 1:
            raise ValueError(f"need n_slots >= 1, got {n_slots}")
        self.slots: list = [None] * int(n_slots)

    @property
    def n_slots(self) -> int:
        return len(self.slots)

    def free_index(self) -> int | None:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def admit(self, index: int, request: GenRequest, pages: int) -> "_Slot":
        if self.slots[index] is not None:
            raise ValueError(f"slot {index} is occupied")
        slot = _Slot(request, pages)
        self.slots[index] = slot
        return slot

    def next_prefill(self) -> int | None:
        """Index of the oldest slot still prefilling (FIFO by admission
        time), or None."""
        best, best_t = None, None
        for i, s in enumerate(self.slots):
            if s is None or s.start >= s.request.prompt_len:
                continue
            if best_t is None or s.request.t_admitted < best_t:
                best, best_t = i, s.request.t_admitted
        return best

    def decoding(self) -> list:
        """Indices of slots with their whole prompt in cache and output
        budget left — the decode step's active rows."""
        return [i for i, s in enumerate(self.slots)
                if s is not None and s.start >= s.request.prompt_len
                and len(s.request.emitted) < s.request.max_new_tokens]

    def busy(self) -> bool:
        return any(s is not None for s in self.slots)

    def release(self, index: int) -> int:
        """Free a slot; returns the pages to hand back to the pool."""
        slot = self.slots[index]
        if slot is None:
            raise ValueError(f"slot {index} is already free")
        self.slots[index] = None
        return slot.pages


class Batcher:
    """The live threaded coalescer around `plan_batch`/`assemble`.

    One producer side (`submit`, called from HTTP handler threads) and
    one consumer side (`next_batch`, called by the engine's dispatcher).
    `clock` is injectable for tests; the default is time.monotonic."""

    def __init__(self, lattice: BucketLattice, max_wait_ms: float = 5.0,
                 *, sequence: bool = False, clock=time.monotonic,
                 recorder=None):
        self.lattice = lattice
        self.max_wait_s = float(max_wait_ms) / 1000.0
        self.sequence = sequence
        self._clock = clock
        self._recorder = recorder
        self._pending: deque[PendingRequest] = deque()
        self._cv = threading.Condition()
        self._closed = False

    # ------------------------------------------------------------ producer
    def submit(self, features, mask=None,
               request_id: str | None = None) -> PendingRequest:
        """Admit one request. Validates the shape against the lattice
        up front (a too-long prompt is the CLIENT's 400, not a retrace
        or a mid-batch crash) and wakes the dispatcher."""
        feats = np.asarray(features)
        if self.sequence:
            if feats.ndim < 1:
                raise ValueError("sequence request needs at least a "
                                 "[T] feature array")
            self.lattice.seq_bucket(int(feats.shape[0]))  # raises if too long
        req = PendingRequest(
            features=feats,
            mask=None if mask is None else np.asarray(mask),
            request_id=request_id or f"r{next(_req_counter)}",
            t_enqueue=self._clock())
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is draining; request refused")
            self._pending.append(req)
            self._cv.notify_all()
        return req

    # ------------------------------------------------------------ consumer
    def next_batch(self, timeout: float | None = None):
        """Block until a batch cuts (full bucket / deadline / drain
        flush). Returns None when draining finished (closed and empty)
        or `timeout` elapsed with nothing to cut."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cv:
            while True:
                now = self._clock()
                take = plan_batch(self._pending, now, self.max_wait_s,
                                  self.lattice, sequence=self.sequence,
                                  closed=self._closed)
                if take:
                    group = [self._pending.popleft() for _ in range(take)]
                    break
                if self._closed:
                    return None
                waits = []
                if self._pending:
                    waits.append(self._pending[0].t_enqueue
                                 + self.max_wait_s - now)
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        return None
                    waits.append(remaining)
                # bounded wait: re-plan on submit()/close() notify or when
                # the head request's deadline arrives
                self._cv.wait(timeout=max(min(waits), 0.0005)
                              if waits else None)
        t0 = time.perf_counter()
        batch = assemble(group, self.lattice, sequence=self.sequence)
        batch.t_cut = self._clock()
        batch.assemble_seconds = time.perf_counter() - t0
        for r in group:
            r.t_assembled = batch.t_cut
        if self._recorder is not None:
            # span names documented in telemetry/recorder.py: `queue` is
            # the head request's wait (the latency the deadline bounds),
            # `batch_assemble` the host-side padding cost. The cut roots
            # a TRACE: queue -> batch_assemble here, then the replica
            # thread's forward/compile/request events join the tree
            # through the Batch's correlation handoff fields.
            rec = self._recorder
            batch.trace_id = f"b{next(_req_counter)}"
            q_sid = rec.new_span_id()
            a_sid = rec.new_span_id()
            batch.parent_span = a_sid
            rec.event(
                "span", name="queue", ok=True,
                seconds=round(batch.t_cut - group[0].t_enqueue, 6),
                n_requests=len(group), trace_id=batch.trace_id,
                span_id=q_sid)
            rec.event(
                "span", name="batch_assemble", ok=True,
                seconds=round(batch.assemble_seconds, 6),
                bucket=list(batch.bucket.key()), n_real=batch.n_real,
                trace_id=batch.trace_id, span_id=a_sid, parent_id=q_sid)
        return batch

    def requeue(self, requests) -> None:
        """Put already-admitted requests BACK at the FIFO head — the
        dead-replica queue drain (serving/fleet.py): batches a reaped
        replica never ran dissolve back into pending requests, keeping
        their original enqueue times (their queue-wait telemetry stays
        honest), and live replicas pick them up on the next cut. Works
        even while draining: these requests were admitted before the
        close and the drain flush owes them a completion."""
        with self._cv:
            for r in reversed(list(requests)):
                self._pending.appendleft(r)
            self._cv.notify_all()

    # ------------------------------------------------------------- drain
    def close(self) -> None:
        """Begin draining: refuse new submits, flush pending groups on
        the next next_batch() calls (which return None once empty)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @property
    def depth(self) -> int:
        with self._cv:
            return len(self._pending)
