"""Page-block KV-cache accounting on the bucket lattice.

The generation engine's device cache is ONE static allocation per
replica — ``[n_slots, capacity, H, D]`` per attention layer — because a
jitted decode step needs a fixed shape to keep the zero-retrace promise.
What varies per request is how much of a slot's row it actually earns:
this module is the page-granular accounting overlay on that static
allocation.

* Capacities are QUANTIZED to the ``(max_seqlen_bucket, page_size)``
  grid: a slot's key budget is ``quantize(prompt_bucket + max_new,
  page_size)`` — never a raw request length — so every shape the jit
  sees is a lattice point and neither prefill nor decode ever retraces.
* A per-replica ``PagePool`` holds the page budget. Admission reserves a
  request's worst-case pages (its quantized prompt + output budget) up
  front; completion (or failure) releases them. Reserving up front means
  exhaustion can ONLY happen at admission — a mid-decode slot never
  discovers it has nowhere to write — so the failure mode is a graceful
  queue/503 at the front door, not a crash (tier-1:
  tests/test_generation.py page-pool exhaustion).
* Occupancy is on the record: the pool tracks pages in use and the
  high-water mark, and the engine emits a ``page_pool`` telemetry event
  on every reserve/release — the ``serving_generate_page_occupancy``
  headline (lower is better: the same traffic served with fewer
  resident pages is more HBM left for replicas) reconstructs from those
  events alone.

* The cache DTYPE is part of the accounting (r16): an ``int8`` paged
  cache stores 1-byte codes plus one f32 scale per (page, head), so a
  slot's HBM bill shrinks ~4x vs f32 — `bytes_per_slot` is the single
  home for that arithmetic, and the replay artifact's
  ``slots_per_hbm_byte`` uplift row (gate: >= 1.8x) is computed from
  it, not re-derived ad hoc.

Pure stdlib: importable under the graftlint AST stage's no-jax stubs.
"""

from __future__ import annotations

import threading

DEFAULT_PAGE_SIZE = 16

KV_DTYPES = ("f32", "int8")


def validate_kv_dtype(kv_dtype: str) -> str:
    """The serving cache dtype knob ('f32' | 'int8'), validated once at
    the engine front door so a typo fails at construction, not as a
    shape error mid-replay."""
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype must be one of {KV_DTYPES}, got {kv_dtype!r}")
    return kv_dtype


def bytes_per_slot(capacity: int, attention_specs, kv_dtype: str = "f32",
                   page_size: int = DEFAULT_PAGE_SIZE) -> int:
    """HBM bytes one decode slot's K+V rows cost across all attention
    layers. `attention_specs` is the nn/decode.py list of
    (name, n_heads, head_dim). f32: capacity*H*D*4 per tensor. int8:
    1-byte codes plus one f32 scale per (page, head) per tensor."""
    validate_kv_dtype(kv_dtype)
    total = 0
    for _name, H, D in attention_specs:
        if kv_dtype == "f32":
            per_tensor = capacity * H * D * 4
        else:
            per_tensor = (capacity * H * D
                          + (capacity // int(page_size)) * H * 4)
        total += 2 * per_tensor  # K and V
    return total


def pages_for(n_tokens: int, page_size: int) -> int:
    """Pages covering `n_tokens` key slots (ceil)."""
    if n_tokens <= 0:
        return 0
    return -(-int(n_tokens) // int(page_size))


def quantize(n_tokens: int, page_size: int) -> int:
    """`n_tokens` rounded UP to the page grid — the only key-capacity
    shapes the device cache (and therefore the jit) ever sees."""
    return pages_for(n_tokens, page_size) * int(page_size)


class PagePool:
    """Thread-safe page budget for one replica's cache allocation.

    `try_reserve` either takes the whole reservation or none of it (no
    partial grants — a half-admitted request would deadlock the slot
    machine); `release` returns pages at completion. The high-water
    mark (`peak_in_use`) is the occupancy headline's numerator."""

    def __init__(self, n_pages: int, page_size: int = DEFAULT_PAGE_SIZE):
        if n_pages < 1 or page_size < 1:
            raise ValueError(
                f"page pool needs n_pages >= 1 and page_size >= 1; got "
                f"{n_pages} pages of {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._in_use = 0
        self.peak_in_use = 0
        self._lock = threading.Lock()

    def pages_for(self, n_tokens: int) -> int:
        return pages_for(n_tokens, self.page_size)

    def try_reserve(self, n_pages: int) -> bool:
        with self._lock:
            if self._in_use + n_pages > self.n_pages:
                return False
            self._in_use += n_pages
            self.peak_in_use = max(self.peak_in_use, self._in_use)
            return True

    def release(self, n_pages: int) -> None:
        with self._lock:
            if n_pages > self._in_use:
                raise ValueError(
                    f"releasing {n_pages} pages with only {self._in_use} "
                    "reserved — double release")
            self._in_use -= n_pages

    @property
    def in_use(self) -> int:
        with self._lock:
            return self._in_use

    @property
    def occupancy(self) -> float:
        return self.in_use / self.n_pages

    @property
    def peak_occupancy(self) -> float:
        with self._lock:
            return self.peak_in_use / self.n_pages

    def describe(self) -> dict:
        with self._lock:
            return {"pages_total": self.n_pages,
                    "page_size": self.page_size,
                    "pages_in_use": self._in_use,
                    "pages_peak": self.peak_in_use}


class CachePlan:
    """The quantized cache geometry one replica allocates: `n_slots`
    rows of `capacity` key slots, where capacity is the largest prompt
    bucket plus the output budget, rounded up to the page grid. The
    default pool budget is exactly the allocation (`n_slots` rows'
    pages); passing a smaller `pool_pages` models a tighter HBM budget
    — admission then queues before the slots run out."""

    def __init__(self, max_seq_bucket: int, max_new_tokens: int,
                 n_slots: int, page_size: int = DEFAULT_PAGE_SIZE,
                 pool_pages: int | None = None, kv_dtype: str = "f32"):
        if n_slots < 1:
            raise ValueError(f"need n_slots >= 1, got {n_slots}")
        self.page_size = int(page_size)
        self.max_new_tokens = int(max_new_tokens)
        self.n_slots = int(n_slots)
        self.kv_dtype = validate_kv_dtype(kv_dtype)
        self.capacity = quantize(max_seq_bucket + max_new_tokens,
                                 page_size)
        self.pages_per_slot = self.capacity // self.page_size
        self.pool_pages = (self.n_slots * self.pages_per_slot
                           if pool_pages is None else int(pool_pages))

    def bytes_per_slot(self, attention_specs) -> int:
        """This plan's per-slot HBM bill (see module `bytes_per_slot`)."""
        return bytes_per_slot(self.capacity, attention_specs,
                              self.kv_dtype, self.page_size)

    def make_pool(self) -> PagePool:
        return PagePool(self.pool_pages, self.page_size)

    def request_pages(self, prompt_bucket: int, max_new: int) -> int:
        """A request's worst-case reservation: its QUANTIZED prompt
        bucket plus output budget — the page-grid point, never the raw
        length, so accounting and shapes stay on the same lattice."""
        return pages_for(prompt_bucket + max_new, self.page_size)

    def describe(self) -> dict:
        return {"n_slots": self.n_slots, "capacity": self.capacity,
                "page_size": self.page_size,
                "pages_per_slot": self.pages_per_slot,
                "pool_pages": self.pool_pages,
                "max_new_tokens": self.max_new_tokens,
                "kv_dtype": self.kv_dtype}
