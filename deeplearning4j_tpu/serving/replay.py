"""Traffic replay — the serving bench core behind tools/trafficreplay.py
and bench.py's `serving_replay` mode.

Three pieces, each usable alone:

* `make_trace`  — a SEEDED mixed-length, bursty request trace: arrivals
  come in bursts (every `burst`-th request opens a new exponential gap;
  the burst shares its instant), lengths draw from a weighted set. Same
  seed -> byte-identical trace, so two rounds replay the same traffic.
* `replay_http` — drives a running ServingServer over real HTTP at the
  trace's arrival offsets (thread pool sized past the burst width), then
  drains. Nothing measured in-process: the replies are only checked for
  success.
* `reconstruct` — rebuilds the scoreboard from the telemetry JSONL
  ALONE: p50/p99 latency from `request` events' `total_s`, sustained
  QPS from first-enqueue to last-completion (both derivable from each
  event's `ts` and `total_s`), and the retrace count from non-warmup
  `compile` spans. The artifact line set ends with the gate-carrying
  summary (telemetry/artifact.build_summary), so a tail-truncated
  capture still reconstructs every number.

Latency metrics are LOWER-is-better — their lines carry
``lower_is_better: true`` and tools/benchdiff.py inverts its regression
direction for them (and for `*_p50_ms`/`*_p99_ms`-shaped names
recovered from a summary line, which drops the flag).

The GENERATION replay (r11) is the same triple for the prefill/decode
path: `make_generation_trace` (prompt-length x output-length mix),
`replay_generate_http` (streaming /generate reads), and
`reconstruct_generation` — tokens/sec, TTFT p50/p99, peak cache-page
occupancy (from `page_pool` events), and the decode-step span medians
that prove decode cost independent of prompt length. Artifact:
SERVE_r02-style, written by `run_generation_replay` /
tools/trafficreplay.py --generate / bench.py serving_generate.

The FLEET replay (r18, ISSUE 13) is the zero-downtime operations
bench: `run_fleet_replay` drives the SAME seeded bursty trace through
two arms — a fixed-replica baseline and an autoscaling arm
(serving/fleet.FleetSupervisor) that also absorbs a replica-kill chaos
spec and a mid-traffic weight hot-swap — and `reconstruct_fleet`
extends the scoreboard with `swap_ms` (the off-path restore cost),
`respawn_ms`, `failed_requests` (the chaos kill's BOUNDED in-flight
loss), autoscale occupancy (mean replicas held / max, from `autoscale`
events), and the weight generations visible in `request` events.
Artifact: SERVE_r03-style, gated by tools/benchdiff.py (all the new
rows are lower-is-better except QPS).

The SPECULATIVE replay (r04, ISSUE 16) is the decode raw-speed bench:
`run_speculative_replay` drives the SAME seeded generation trace
through three interleaved arms — **baseline** (plain greedy decode,
f32 cache), **speculative** (self-speculative n-gram drafting with one
fixed-shape verify step per k-token window), and **quantized** (int8
paged KV cache) — capturing every stream's emitted tokens so the two
parity gates (speculative == baseline, quantized == baseline, both
bit-identical under greedy) are checked against real traffic, not a
unit fixture. `reconstruct_generation` learns the `draft` events and
`verify_step` spans: `accepted_tokens_per_step` (median emitted tokens
per slot per verify step — the headline, > 1.0 means speculation beat
the one-token floor), `draft_acceptance_rate`, and `draft_overhead_us`
(host proposer cost per step). The artifact adds
`serving_sample_us` (the fused-sampling microbench row) and
`serving_quantized_slots_per_hbm_byte_x` (the f32/int8 bytes-per-slot
ratio from kvcache.bytes_per_slot — the capacity headline). Artifact:
SERVE_r04-style, written by bench.py's `serving_speculative` mode.
"""

from __future__ import annotations

import concurrent.futures
import json
import time
import urllib.error
import urllib.request

import numpy as np

# the replay's HTTP concurrency must exceed the widest burst or the
# client itself serializes the burst and the queue-wait numbers lie
_CLIENT_WORKERS = 32


def make_trace(seed: int = 0, n_requests: int = 80, *,
               mean_gap_s: float = 0.002, burst: int = 4,
               lengths=(8, 16, 32), weights=None) -> list:
    """[(arrival_offset_s, seq_len), ...] sorted by offset. Bursty:
    every `burst`-th arrival opens a fresh exponential gap scaled by the
    burst width (keeping the MEAN rate at 1/mean_gap_s); the requests
    inside a burst land at the same instant — the pile-up the batcher's
    coalescing exists for."""
    rng = np.random.default_rng(seed)
    lengths = list(lengths)
    if weights is not None:
        weights = np.asarray(weights, np.float64)
        weights = weights / weights.sum()
    t = 0.0
    trace = []
    for i in range(n_requests):
        if i % max(1, burst) == 0 and i:
            t += float(rng.exponential(mean_gap_s * burst))
        seq_len = int(rng.choice(lengths, p=weights))
        trace.append((round(t, 6), seq_len))
    return trace


def trace_stats(trace) -> dict:
    lens = [l for _, l in trace]
    return {"n_requests": len(trace),
            "span_s": trace[-1][0] if trace else 0.0,
            "len_min": min(lens), "len_max": max(lens)}


def replay_http(url: str, trace, *, make_features, time_scale: float = 1.0,
                timeout_s: float = 60.0) -> dict:
    """POST every trace entry to `url`/predict at its (scaled) arrival
    offset. `make_features(index, seq_len)` builds the request payload
    array — deterministic per index so reruns send identical bytes.
    Returns client-side success counts only; the scoreboard comes from
    `reconstruct` over the telemetry log."""
    t_start = time.monotonic()

    def one(idx_entry):
        i, (offset, seq_len) = idx_entry
        delay = offset * time_scale - (time.monotonic() - t_start)
        if delay > 0:
            time.sleep(delay)
        feats = np.asarray(make_features(i, seq_len))
        body = json.dumps({"features": feats.tolist(),
                           "id": f"replay-{i}"}).encode()
        req = urllib.request.Request(
            f"{url}/predict", data=body,
            headers={"Content-Type": "application/json"})
        # one retry: a burst can race the ThreadingHTTPServer's accept
        # backlog on a loaded host — a reset on first contact is the
        # client environment, not a serving result
        last = None
        for _attempt in range(2):
            try:
                with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                    json.loads(resp.read())
                    return None
            except Exception as exc:
                last = exc
        return f"replay-{i}: {last!r}"

    with concurrent.futures.ThreadPoolExecutor(_CLIENT_WORKERS) as pool:
        results = list(pool.map(one, enumerate(trace)))
    errors = [r for r in results if r is not None]
    return {"sent": len(results), "ok": len(results) - len(errors),
            "failed": len(errors), "errors": errors[:5],
            "wall_s": round(time.monotonic() - t_start, 3)}


# ---------------------------------------------------------- reconstruction

def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return float(sorted_vals[k])


def reconstruct(telemetry_path: str) -> dict:
    """The serving scoreboard from the telemetry JSONL ALONE — no
    in-process timer survives into these numbers, so a crashed or
    remote replay reconstructs identically from its log:

    * latency percentiles (ms) over successful `request` events'
      `total_s` (enqueue -> result, queue + assemble + forward);
    * sustained QPS = completed / (last completion - first enqueue),
      both derived from each event's `ts` (completion) and `total_s`;
    * `recompiles_after_warmup` = `compile` spans missing the warmup
      flag — any value above 0 means a shape escaped the bucket
      lattice and retraced mid-traffic.
    """
    requests, compiles, warm_compiles = [], 0, 0
    with open(telemetry_path) as fh:
        for raw in fh:
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                ev = json.loads(raw)
            except json.JSONDecodeError:
                continue
            kind = ev.get("event")
            if kind == "request":
                requests.append(ev)
            elif kind == "span" and ev.get("name") == "compile":
                if ev.get("warmup"):
                    warm_compiles += 1
                else:
                    compiles += 1
    ok = [ev for ev in requests if ev.get("ok")]
    lat_ms = sorted(1000.0 * float(ev["total_s"]) for ev in ok
                    if "total_s" in ev)
    out = {
        "n_requests": len(requests),
        "n_ok": len(ok),
        "n_failed": len(requests) - len(ok),
        "p50_ms": round(_percentile(lat_ms, 50), 3),
        "p99_ms": round(_percentile(lat_ms, 99), 3),
        "warmup_compiles": warm_compiles,
        "recompiles_after_warmup": compiles,
    }
    if ok:
        first_enqueue = min(float(ev["ts"]) - float(ev["total_s"])
                            for ev in ok)
        last_done = max(float(ev["ts"]) for ev in ok)
        span = max(last_done - first_enqueue, 1e-9)
        out["qps"] = round(len(ok) / span, 2)
        out["span_s"] = round(span, 3)
    else:
        out["qps"] = 0.0
        out["span_s"] = 0.0
    return out


def metric_lines(scoreboard: dict, prefix: str = "serving_replay") -> list:
    """The bench metric lines for a reconstructed scoreboard. QPS is
    higher-is-better (the default); the latency/retrace lines carry the
    explicit lower_is_better flag benchdiff inverts on."""
    return [
        {"metric": f"{prefix}_qps", "value": scoreboard["qps"],
         "unit": "req/sec", "n_ok": scoreboard["n_ok"],
         "n_failed": scoreboard["n_failed"]},
        {"metric": f"{prefix}_p50_ms", "value": scoreboard["p50_ms"],
         "unit": "ms", "lower_is_better": True},
        {"metric": f"{prefix}_p99_ms", "value": scoreboard["p99_ms"],
         "unit": "ms", "lower_is_better": True},
        {"metric": f"{prefix}_recompiles_after_warmup",
         "value": scoreboard["recompiles_after_warmup"], "unit": "count",
         "lower_is_better": True,
         "warmup_compiles": scoreboard["warmup_compiles"]},
    ]


def write_artifact(path: str, lines: list) -> dict:
    """Write the SERVE artifact: every metric line plus the trailing
    gate-carrying summary (the same truncation-proof shape BENCH
    artifacts use — telemetry/artifact.py parses both)."""
    from deeplearning4j_tpu.telemetry.artifact import build_summary

    summary = build_summary(lines)
    with open(path, "w") as fh:
        for line in lines:
            fh.write(json.dumps(line) + "\n")
        fh.write(json.dumps(summary) + "\n")
    return summary


# ----------------------------------------------------- generation replay

def make_generation_trace(seed: int = 0, n_requests: int = 24, *,
                          mean_gap_s: float = 0.01, burst: int = 2,
                          prompt_lengths=(8, 16, 32),
                          output_lengths=(4, 8, 16),
                          weights=None) -> list:
    """[(arrival_offset_s, prompt_len, output_len), ...] — the
    generation twin of `make_trace`: seeded, bursty arrivals with a
    prompt-length x output-length mix, so two rounds replay identical
    traffic and the prefill buckets AND decode budgets both get
    exercised."""
    rng = np.random.default_rng(seed)
    plens = list(prompt_lengths)
    olens = list(output_lengths)
    if weights is not None:
        weights = np.asarray(weights, np.float64)
        weights = weights / weights.sum()
    t = 0.0
    trace = []
    for i in range(n_requests):
        if i % max(1, burst) == 0 and i:
            t += float(rng.exponential(mean_gap_s * burst))
        plen = int(rng.choice(plens, p=weights))
        olen = int(rng.choice(olens))
        trace.append((round(t, 6), plen, olen))
    return trace


def replay_generate_http(url: str, trace, *, make_prompt,
                         time_scale: float = 1.0,
                         timeout_s: float = 120.0,
                         collect_tokens: bool = False) -> dict:
    """POST every trace entry to `url`/generate at its arrival offset
    and drain the STREAMING body (each token line arrives as the decode
    loop emits it). `make_prompt(index, prompt_len)` builds the token
    prompt — deterministic per index. Client-side counts only; the
    scoreboard reconstructs from telemetry. With `collect_tokens` the
    result carries a `tokens` dict (request index -> the summary line's
    full emitted token list) — the raw material of the speculative
    replay's greedy-parity gates."""
    t_start = time.monotonic()

    def one(idx_entry):
        i, (offset, plen, olen) = idx_entry
        delay = offset * time_scale - (time.monotonic() - t_start)
        if delay > 0:
            time.sleep(delay)
        toks = np.asarray(make_prompt(i, plen))
        body = json.dumps({"tokens": toks.tolist(),
                           "max_new_tokens": olen,
                           "id": f"gen-{i}"}).encode()
        req = urllib.request.Request(
            f"{url}/generate", data=body,
            headers={"Content-Type": "application/json"})
        last = None
        for _attempt in range(2):
            try:
                with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                    lines = [json.loads(l)
                             for l in resp.read().splitlines() if l]
                if not lines or not lines[-1].get("done"):
                    return f"gen-{i}: stream ended without summary", None
                if lines[-1].get("error"):
                    return f"gen-{i}: {lines[-1]['error']}", None
                return None, [int(t) for t in lines[-1].get("tokens", [])]
            except urllib.error.HTTPError as exc:
                # 503 = pool saturated + queue full: the graceful
                # refusal contract, reported distinctly from transport
                # errors
                return f"gen-{i}: HTTP {exc.code}", None
            except Exception as exc:
                last = exc
        return f"gen-{i}: {last!r}", None

    with concurrent.futures.ThreadPoolExecutor(_CLIENT_WORKERS) as pool:
        results = list(pool.map(one, enumerate(trace)))
    errors = [err for err, _ in results if err is not None]
    out = {"sent": len(results), "ok": len(results) - len(errors),
           "failed": len(errors), "errors": errors[:5],
           "wall_s": round(time.monotonic() - t_start, 3)}
    if collect_tokens:
        out["tokens"] = {i: toks for i, (err, toks) in enumerate(results)
                         if err is None and toks is not None}
    return out


def reconstruct_generation(telemetry_path: str) -> dict:
    """The generation scoreboard from the telemetry JSONL alone:

    * tokens/sec — total generated tokens over the serving span (first
      enqueue to last completion), from `request` events with
      kind="generate";
    * time-to-first-token p50/p99 (ms) — the `ttft_s` field (enqueue to
      the prefill's final chunk emitting the first token);
    * cache-page occupancy — the PEAK pages_in_use/pages_total across
      `page_pool` events (lower = the same traffic held fewer resident
      pages);
    * `recompiles_after_warmup` — non-warmup `compile` spans, exactly
      the predict path's zero-retrace gate;
    * decode-step timing per prompt bucket — median `decode_step` span
      seconds, the flatness evidence for "decode cost is independent of
      prompt length";
    * speculative accounting, when `draft` events are on the record —
      `accepted_tokens_per_step` (the MEDIAN of per-verify-step emitted
      tokens per active slot: 1.0 is the plain-decode floor, anything
      above it is decode steps the slots never ran),
      `draft_acceptance_rate` (accepted drafts / offered drafts), and
      `draft_overhead_us` (mean host-side proposer wall clock per
      verify step), plus the median `verify_step` span time.
    """
    requests, compiles, warm_compiles = [], 0, 0
    occupancy_peak = 0.0
    decode_spans = []
    draft_events, verify_spans = [], []
    with open(telemetry_path) as fh:
        for raw in fh:
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                ev = json.loads(raw)
            except json.JSONDecodeError:
                continue
            kind = ev.get("event")
            if kind == "request" and ev.get("kind") == "generate":
                requests.append(ev)
            elif kind == "span" and ev.get("name") == "compile":
                if ev.get("warmup"):
                    warm_compiles += 1
                else:
                    compiles += 1
            elif kind == "span" and ev.get("name") == "decode_step":
                decode_spans.append(ev)
            elif kind == "span" and ev.get("name") == "verify_step":
                verify_spans.append(ev)
            elif kind == "draft":
                draft_events.append(ev)
            elif kind == "page_pool":
                total = ev.get("pages_total") or 0
                if total:
                    occupancy_peak = max(
                        occupancy_peak,
                        float(ev.get("pages_in_use", 0)) / total)
    ok = [ev for ev in requests if ev.get("ok")]
    ttft_ms = sorted(1000.0 * float(ev["ttft_s"]) for ev in ok
                     if "ttft_s" in ev)
    total_tokens = sum(int(ev.get("new_tokens", 0)) for ev in ok)
    out = {
        "n_requests": len(requests),
        "n_ok": len(ok),
        "n_failed": len(requests) - len(ok),
        "total_tokens": total_tokens,
        "ttft_p50_ms": round(_percentile(ttft_ms, 50), 3),
        "ttft_p99_ms": round(_percentile(ttft_ms, 99), 3),
        "page_occupancy_peak": round(occupancy_peak, 4),
        "warmup_compiles": warm_compiles,
        "recompiles_after_warmup": compiles,
        "decode_steps": len(decode_spans),
    }
    if decode_spans:
        secs = sorted(float(ev.get("seconds", 0.0))
                      for ev in decode_spans)
        out["decode_step_ms_p50"] = round(
            1000.0 * _percentile(secs, 50), 3)
    if draft_events:
        per_step = sorted(
            float(ev.get("emitted", 0)) / max(int(ev.get("n_active", 1)), 1)
            for ev in draft_events)
        offered = sum(int(ev.get("drafted", 0)) for ev in draft_events)
        accepted = sum(int(ev.get("accepted", 0)) for ev in draft_events)
        out["verify_steps"] = len(draft_events)
        out["accepted_tokens_per_step"] = round(_percentile(per_step, 50), 4)
        out["draft_acceptance_rate"] = round(
            accepted / offered, 4) if offered else 0.0
        out["draft_overhead_us"] = round(
            sum(float(ev.get("overhead_us", 0.0)) for ev in draft_events)
            / len(draft_events), 2)
    if verify_spans:
        secs = sorted(float(ev.get("seconds", 0.0)) for ev in verify_spans)
        out["verify_step_ms_p50"] = round(1000.0 * _percentile(secs, 50), 3)
    if ok:
        first_enqueue = min(float(ev["ts"]) - float(ev["total_s"])
                            for ev in ok)
        last_done = max(float(ev["ts"]) for ev in ok)
        span = max(last_done - first_enqueue, 1e-9)
        out["tokens_per_sec"] = round(total_tokens / span, 2)
        out["span_s"] = round(span, 3)
    else:
        out["tokens_per_sec"] = 0.0
        out["span_s"] = 0.0
    return out


def generation_metric_lines(scoreboard: dict,
                            prefix: str = "serving_generate") -> list:
    """Bench metric lines for the generation scoreboard. tokens/sec is
    higher-is-better (the default); TTFT latency, cache-page occupancy,
    and the retrace count carry the explicit lower_is_better flag
    benchdiff inverts on. A speculative scoreboard (draft events were
    on the record) adds `accepted_tokens_per_step` (higher) and
    `draft_overhead_us` (lower — the `_us` suffix is also in
    benchdiff's name-shape fallback)."""
    lines = [
        {"metric": f"{prefix}_tokens_per_sec",
         "value": scoreboard["tokens_per_sec"], "unit": "tok/sec",
         "n_ok": scoreboard["n_ok"], "n_failed": scoreboard["n_failed"],
         "total_tokens": scoreboard["total_tokens"]},
        {"metric": f"{prefix}_ttft_p50_ms",
         "value": scoreboard["ttft_p50_ms"], "unit": "ms",
         "lower_is_better": True},
        {"metric": f"{prefix}_ttft_p99_ms",
         "value": scoreboard["ttft_p99_ms"], "unit": "ms",
         "lower_is_better": True},
        {"metric": f"{prefix}_page_occupancy",
         "value": scoreboard["page_occupancy_peak"], "unit": "fraction",
         "lower_is_better": True},
        {"metric": f"{prefix}_recompiles_after_warmup",
         "value": scoreboard["recompiles_after_warmup"], "unit": "count",
         "lower_is_better": True,
         "warmup_compiles": scoreboard["warmup_compiles"]},
    ]
    if "accepted_tokens_per_step" in scoreboard:
        lines.append(
            {"metric": f"{prefix}_accepted_tokens_per_step",
             "value": scoreboard["accepted_tokens_per_step"],
             "unit": "tokens/step",
             "verify_steps": scoreboard["verify_steps"],
             "draft_acceptance_rate": scoreboard["draft_acceptance_rate"]})
        lines.append(
            {"metric": f"{prefix}_draft_overhead_us",
             "value": scoreboard["draft_overhead_us"], "unit": "us",
             "lower_is_better": True})
    return lines


def run_generation_replay(*, seed: int = 0, n_requests: int = 24,
                          burst: int = 2, mean_gap_s: float = 0.01,
                          prompt_lengths=(8, 16, 32),
                          output_lengths=(4, 8, 16),
                          slots: int = 4, page_size: int = 16,
                          replicas: int = 1,
                          prefill_chunk: int | None = None,
                          max_queue: int = 256,
                          speculative_k: int = 0,
                          kv_dtype: str = "f32",
                          telemetry_path: str,
                          artifact_path: str | None = None,
                          checkpoint: str | None = None,
                          emit=None) -> dict:
    """End-to-end generation replay: tiny LM, GenerationEngine warmed
    over the prompt-bucket lattice, the seeded generation trace over
    real HTTP with streaming reads, drain, scoreboard from telemetry
    alone, optional SERVE artifact (the SERVE_r02 shape). Same rc
    semantics as `run_replay`. `speculative_k`/`kv_dtype` pass straight
    through to the engine (0/"f32" = the plain decode path)."""
    from deeplearning4j_tpu.serving.buckets import BucketLattice
    from deeplearning4j_tpu.serving.engine import GenerationEngine
    from deeplearning4j_tpu.serving.server import ServingServer
    from deeplearning4j_tpu.telemetry import Recorder

    rec = Recorder(telemetry_path)
    rec.meta(role="trafficreplay-generate", seed=seed,
             n_requests=n_requests, burst=burst,
             prompt_lengths=list(prompt_lengths),
             output_lengths=list(output_lengths),
             speculative_k=speculative_k, kv_dtype=kv_dtype)
    lattice = BucketLattice(batch_sizes=(1,),
                            seq_lens=sorted(set(prompt_lengths)))
    lattice.validate_attention(head_dim=16)
    net = _tiny_lm(max_seq=max(prompt_lengths) + max(output_lengths))
    vocab = 64
    prompt_rng = np.random.default_rng(seed + 1)
    prompts = prompt_rng.integers(0, vocab,
                                  (n_requests, max(prompt_lengths)))

    def make_prompt(i, plen):
        return prompts[i, :plen].astype(np.int32)

    engine = GenerationEngine(
        net, lattice, slots=slots, max_new_tokens=max(output_lengths),
        page_size=page_size, prefill_chunk=prefill_chunk,
        max_queue=max_queue, replicas=replicas, checkpoint=checkpoint,
        speculative_k=speculative_k, kv_dtype=kv_dtype,
        recorder=rec)
    warm = engine.warmup()
    server = ServingServer(engine, port=0).start()
    trace = make_generation_trace(
        seed, n_requests, mean_gap_s=mean_gap_s, burst=burst,
        prompt_lengths=prompt_lengths, output_lengths=output_lengths)
    try:
        client = replay_generate_http(server.url, trace,
                                      make_prompt=make_prompt)
    finally:
        server.stop()
        rec.close()
    scoreboard = reconstruct_generation(telemetry_path)
    scoreboard["client"] = client
    scoreboard["warmed_shapes"] = warm
    lines = generation_metric_lines(scoreboard)
    if emit is not None:
        for line in lines:
            emit(line)
    if artifact_path:
        scoreboard["summary"] = write_artifact(artifact_path, lines)
        scoreboard["artifact"] = artifact_path
    scoreboard["lines"] = lines
    return scoreboard


# -------------------------------------------------- speculative replay

def _sample_microbench_us(batch: int = 8, vocab: int = 128,
                          iters: int = 20) -> float:
    """Best-of-N wall clock (µs) for one fused_sample call — the
    `serving_sample_us` artifact row. Runs the real Pallas kernel on
    TPU and the bit-identical reference path elsewhere, so the row is
    comparable within a platform and honest about which path ran."""
    import jax

    from deeplearning4j_tpu.ops import fused_sampling

    rng = np.random.default_rng(0)
    logits = np.asarray(rng.normal(size=(batch, vocab)), np.float32)
    noise = fused_sampling.gumbel_noise(jax.random.PRNGKey(0), batch, vocab)

    # jit the wrapper: off-TPU the reference path is op-by-op eager
    # otherwise, and eager dispatch is what gets measured, not the op
    fn = jax.jit(lambda lg, nz: fused_sampling.fused_sample(
        lg, nz, temperature=1.0, top_k=8, top_p=0.9))

    def call():
        return fn(logits, noise)

    call().block_until_ready()  # compile outside the timed region
    best = float("inf")
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        call().block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return round(best * 1e6, 2)


def run_speculative_replay(*, seed: int = 0, n_requests: int = 24,
                           burst: int = 2, mean_gap_s: float = 0.01,
                           prompt_lengths=(8, 16, 32),
                           output_lengths=(4, 8, 16),
                           slots: int = 4, page_size: int = 16,
                           speculative_k: int = 4,
                           repeats: int = 2,
                           max_queue: int = 256,
                           telemetry_path: str,
                           artifact_path: str | None = None,
                           emit=None) -> dict:
    """The SERVE_r04 bench: the SAME seeded generation trace through
    three arms, INTERLEAVED round-robin across `repeats` rounds (so
    ambient host noise lands on every arm, not just the last one):

    * **baseline** — plain greedy decode, f32 cache (`serving_generate`
      rows: the same shape SERVE_r02 carries);
    * **speculative** — `speculative_k`-token windows: n-gram drafts +
      ONE fixed-shape verify step per window (`serving_speculative`
      rows, plus `accepted_tokens_per_step` and `draft_overhead_us`);
    * **quantized** — int8 paged KV cache (`serving_quantized` rows,
      plus the `slots_per_hbm_byte_x` capacity ratio).

    All three arms share ONE tiny-LM weight init and serve identical
    prompts, and every stream's emitted tokens are captured — the
    `*_parity_mismatches` rows count requests whose greedy token
    sequence diverged from the baseline's first round (the two
    bit-identity gates; both must be 0). Each arm appends every round
    to its own telemetry file (`<path>.<arm>`) and reconstructs from it
    alone. rc semantics as `run_replay`: parity failures are REPORTED
    rows, not raises — the committed-artifact gate is benchdiff's."""
    from deeplearning4j_tpu.nn.decode import attention_specs
    from deeplearning4j_tpu.serving.buckets import BucketLattice
    from deeplearning4j_tpu.serving.engine import GenerationEngine
    from deeplearning4j_tpu.serving.kvcache import CachePlan, bytes_per_slot
    from deeplearning4j_tpu.serving.server import ServingServer
    from deeplearning4j_tpu.telemetry import Recorder

    if speculative_k < 2:
        raise ValueError(
            f"need speculative_k >= 2 for the speculative arm, "
            f"got {speculative_k}")
    net = _tiny_lm(max_seq=max(prompt_lengths) + max(output_lengths))
    vocab = 64
    prompt_rng = np.random.default_rng(seed + 1)
    prompts = prompt_rng.integers(0, vocab,
                                  (n_requests, max(prompt_lengths)))

    def make_prompt(i, plen):
        return prompts[i, :plen].astype(np.int32)

    trace = make_generation_trace(
        seed, n_requests, mean_gap_s=mean_gap_s, burst=burst,
        prompt_lengths=prompt_lengths, output_lengths=output_lengths)
    arms = (("baseline", 0, "f32", "serving_generate"),
            ("speculative", speculative_k, "f32", "serving_speculative"),
            ("quantized", 0, "int8", "serving_quantized"))

    def run_arm(name, k, dtype, rnd) -> dict:
        tpath = f"{telemetry_path}.{name}"
        rec = Recorder(tpath)
        rec.meta(role="trafficreplay-speculative", arm=name, round=rnd,
                 seed=seed, n_requests=n_requests, burst=burst,
                 speculative_k=k, kv_dtype=dtype)
        lattice = BucketLattice(batch_sizes=(1,),
                                seq_lens=sorted(set(prompt_lengths)))
        lattice.validate_attention(head_dim=16)
        engine = GenerationEngine(
            net, lattice, slots=slots,
            max_new_tokens=max(output_lengths), page_size=page_size,
            max_queue=max_queue, speculative_k=k, kv_dtype=dtype,
            recorder=rec)
        engine.warmup()
        server = ServingServer(engine, port=0).start()
        try:
            client = replay_generate_http(server.url, trace,
                                          make_prompt=make_prompt,
                                          collect_tokens=True)
        finally:
            server.stop()
            rec.close()
        client["telemetry"] = tpath
        return client

    token_rounds = {name: [] for name, _, _, _ in arms}
    for rnd in range(max(1, repeats)):
        for name, k, dtype, _prefix in arms:
            client = run_arm(name, k, dtype, rnd)
            token_rounds[name].append(client.get("tokens", {}))

    # parity: every arm's every round against the baseline's FIRST
    # round — a baseline round that disagrees with itself is a
    # determinism failure and counts too
    reference = token_rounds["baseline"][0]
    mismatches = {}
    for name, _, _, _ in arms:
        bad = 0
        for tokens in token_rounds[name]:
            for i, ref in reference.items():
                if tokens.get(i) != ref:
                    bad += 1
        mismatches[name] = bad

    scoreboards, lines = {}, []
    for name, _k, _dtype, prefix in arms:
        sb = reconstruct_generation(f"{telemetry_path}.{name}")
        sb["telemetry"] = f"{telemetry_path}.{name}"
        scoreboards[name] = sb
        lines.extend(generation_metric_lines(sb, prefix=prefix))

    # the capacity headline: how many more slots fit per HBM byte with
    # the int8 cache, from the SAME plan the engines served under
    plan = CachePlan(max(prompt_lengths), max(output_lengths),
                     n_slots=slots, page_size=page_size)
    specs = attention_specs(net)
    f32_bytes = bytes_per_slot(plan.capacity, specs, "f32", page_size)
    int8_bytes = bytes_per_slot(plan.capacity, specs, "int8", page_size)
    ratio = round(f32_bytes / int8_bytes, 4)
    lines.append(
        {"metric": "serving_quantized_slots_per_hbm_byte_x",
         "value": ratio, "unit": "x", "f32_bytes_per_slot": f32_bytes,
         "int8_bytes_per_slot": int8_bytes})
    lines.append(
        {"metric": "serving_sample_us", "value": _sample_microbench_us(),
         "unit": "us", "lower_is_better": True})
    lines.append(
        {"metric": "serving_speculative_parity_mismatches",
         "value": mismatches["speculative"] + mismatches["baseline"],
         "unit": "count", "lower_is_better": True,
         "n_reference": len(reference)})
    lines.append(
        {"metric": "serving_quantized_parity_mismatches",
         "value": mismatches["quantized"], "unit": "count",
         "lower_is_better": True, "n_reference": len(reference)})
    if emit is not None:
        for line in lines:
            emit(line)
    out = {"arms": scoreboards, "parity_mismatches": mismatches,
           "lines": lines, "repeats": max(1, repeats),
           "n_ok": sum(sb["n_ok"] for sb in scoreboards.values()),
           "slots_per_hbm_byte_x": ratio}
    if artifact_path:
        out["summary"] = write_artifact(artifact_path, lines)
        out["artifact"] = artifact_path
    return out


# ----------------------------------------------------------- the harness

def _tiny_lm(max_seq: int, vocab: int = 64):
    from deeplearning4j_tpu.models.transformer import transformer_lm

    net = transformer_lm(vocab_size=vocab, d_model=32, n_heads=2,
                         n_layers=2, d_ff=64, max_length=max_seq)
    net.init()
    return net


def _tiny_mlp(n_in: int = 8, n_out: int = 4):
    from deeplearning4j_tpu.nn.conf import (DenseLayer,
                                            NeuralNetConfiguration,
                                            OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(7).list()
            .layer(DenseLayer(n_in=n_in, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=n_out, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def run_replay(*, model: str = "lm", seed: int = 0, n_requests: int = 60,
               burst: int = 4, mean_gap_s: float = 0.002,
               lengths=(8, 16, 32), batch_sizes=(1, 2, 4),
               max_wait_ms: float = 4.0, replicas: int = 1,
               telemetry_path: str, artifact_path: str | None = None,
               checkpoint: str | None = None, chaos: str | None = None,
               emit=None) -> dict:
    """End-to-end: build the tiny model, warm the bucket lattice, replay
    the seeded trace over HTTP, drain, reconstruct from the telemetry
    JSONL, optionally write the SERVE artifact. `emit` (a callable
    taking a metric-line dict) lets bench.py mirror each line through
    its own pipeline. `chaos` is a replica-scoped fault spec string
    (`r0:kill@batch3` — distributed/faults.py grammar): the faults fire
    inside the replicas and a FleetSupervisor heals them live. rc
    semantics: this function raises on setup errors; a zero-`n_ok`
    replay is reported, not raised — the caller gates on the numbers."""
    from deeplearning4j_tpu.serving.buckets import BucketLattice
    from deeplearning4j_tpu.serving.engine import InferenceEngine
    from deeplearning4j_tpu.serving.server import ServingServer
    from deeplearning4j_tpu.telemetry import Recorder

    sequence = model == "lm"
    rec = Recorder(telemetry_path)
    rec.meta(role="trafficreplay", model=model, seed=seed,
             n_requests=n_requests, burst=burst, lengths=list(lengths))
    if sequence:
        lattice = BucketLattice(batch_sizes=batch_sizes,
                                seq_lens=sorted(set(lengths)))
        net = _tiny_lm(max_seq=max(lengths))
        # long-prompt envelope check: every seq bucket must have a
        # compilable attention path (ops/flash_attention.servable_seq)
        lattice.validate_attention(head_dim=16)
        vocab = 64
        feat_rng = np.random.default_rng(seed + 1)
        tokens = feat_rng.integers(0, vocab, (n_requests, max(lengths)))

        def make_features(i, seq_len):
            return tokens[i, :seq_len].astype(np.int32)
    else:
        lattice = BucketLattice(batch_sizes=batch_sizes)
        net = _tiny_mlp()
        feat_rng = np.random.default_rng(seed + 1)
        feats = feat_rng.normal(size=(n_requests, 8)).astype(np.float32)

        def make_features(i, seq_len):
            return feats[i]

    engine = InferenceEngine(net, lattice, replicas=replicas,
                             max_wait_ms=max_wait_ms, sequence=sequence,
                             checkpoint=checkpoint, faults=chaos,
                             recorder=rec)
    example = make_features(0, max(lengths) if sequence else 0)
    warm = engine.warmup(example)
    server = ServingServer(engine, port=0).start()
    supervisor = None
    if chaos is not None:
        # chaos without a healer would just bleed: the supervisor reaps
        # the injected deaths and respawns, live, during the replay
        from deeplearning4j_tpu.serving.fleet import (FleetSupervisor,
                                                      RespawnBackoff)

        supervisor = FleetSupervisor(
            engine, death_after_s=1.0,
            backoff=RespawnBackoff(base_s=0.01, jitter_frac=0.0),
            recorder=rec).run_in_thread(0.02)
    trace = make_trace(seed, n_requests, mean_gap_s=mean_gap_s,
                       burst=burst, lengths=lengths)
    try:
        client = replay_http(server.url, trace,
                             make_features=make_features)
    finally:
        if supervisor is not None:
            supervisor.stop()
        server.stop()
        rec.close()
    scoreboard = reconstruct_fleet(telemetry_path) if chaos is not None \
        else reconstruct(telemetry_path)
    scoreboard["client"] = client
    scoreboard["warmed_buckets"] = warm
    lines = metric_lines(scoreboard)
    if emit is not None:
        for line in lines:
            emit(line)
    if artifact_path:
        scoreboard["summary"] = write_artifact(artifact_path, lines)
        scoreboard["artifact"] = artifact_path
    scoreboard["lines"] = lines
    return scoreboard


# ---------------------------------------------------------- fleet replay

def reconstruct_fleet(telemetry_path: str) -> dict:
    """The fleet-operations scoreboard — `reconstruct` plus the ISSUE 13
    rows, every one from the telemetry JSONL alone:

    * `swap_ms` — the slowest successful `weight_swap` restore (the
      off-request-path cost of picking up a new checkpoint); `n_swaps`
      counts them, `swap_rejected` the validation refusals;
    * `respawn_ms` — the slowest `replica-respawn` fault event (reap →
      re-warm → re-admit), `n_respawns` / `n_replica_deaths` alongside;
    * `autoscale_occupancy` — mean of `n_replicas / max_replicas` over
      `autoscale` events (how much fleet the traffic actually held),
      plus `scale_ups` / `scale_downs`;
    * `weight_generations` — the distinct `weight_gen` values in
      `request` events: a hot-swap's flip is visible here or it never
      reached traffic.
    """
    sb = reconstruct(telemetry_path)
    swap_ms, respawn_ms, occ = [], [], []
    swaps_rejected = deaths = ups = downs = 0
    gens = set()
    with open(telemetry_path) as fh:
        for raw in fh:
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                ev = json.loads(raw)
            except json.JSONDecodeError:
                continue
            kind = ev.get("event")
            if kind == "weight_swap":
                if ev.get("ok"):
                    swap_ms.append(float(ev.get("restore_ms", 0.0)))
                else:
                    swaps_rejected += 1
            elif kind == "fault":
                if ev.get("kind") == "replica-respawn":
                    respawn_ms.append(float(ev.get("respawn_ms", 0.0)))
                elif ev.get("kind") == "replica-dead":
                    deaths += 1
            elif kind == "autoscale":
                total = ev.get("max_replicas") or 0
                if total:
                    occ.append(float(ev.get("n_replicas", 0)) / total)
                if ev.get("action", 0) > 0:
                    ups += 1
                elif ev.get("action", 0) < 0:
                    downs += 1
            elif kind == "request" and "weight_gen" in ev:
                gens.add(int(ev["weight_gen"]))
    sb.update({
        "swap_ms": round(max(swap_ms), 3) if swap_ms else 0.0,
        "n_swaps": len(swap_ms),
        "swap_rejected": swaps_rejected,
        "respawn_ms": round(max(respawn_ms), 3) if respawn_ms else 0.0,
        "n_respawns": len(respawn_ms),
        "n_replica_deaths": deaths,
        "autoscale_occupancy": (round(sum(occ) / len(occ), 4)
                                if occ else 0.0),
        "scale_ups": ups,
        "scale_downs": downs,
        "weight_generations": sorted(gens),
    })
    return sb


def fleet_metric_lines(fixed: dict, autoscale: dict,
                       prefix: str = "fleet") -> list:
    """Bench metric lines for the two-arm fleet replay. QPS rows stay
    higher-is-better; everything the fleet SPENDS — latency, restore
    and respawn wall-clock, failed requests, held replicas, retraces —
    carries the lower_is_better flag benchdiff inverts on."""
    return [
        {"metric": f"{prefix}_fixed_qps", "value": fixed["qps"],
         "unit": "req/sec", "n_ok": fixed["n_ok"],
         "n_failed": fixed["n_failed"]},
        {"metric": f"{prefix}_fixed_p99_ms", "value": fixed["p99_ms"],
         "unit": "ms", "lower_is_better": True},
        {"metric": f"{prefix}_autoscale_qps", "value": autoscale["qps"],
         "unit": "req/sec", "n_ok": autoscale["n_ok"],
         "n_failed": autoscale["n_failed"]},
        {"metric": f"{prefix}_autoscale_p99_ms",
         "value": autoscale["p99_ms"], "unit": "ms",
         "lower_is_better": True},
        {"metric": f"{prefix}_autoscale_occupancy",
         "value": autoscale["autoscale_occupancy"], "unit": "fraction",
         "lower_is_better": True, "scale_ups": autoscale["scale_ups"],
         "scale_downs": autoscale["scale_downs"]},
        {"metric": f"{prefix}_swap_ms", "value": autoscale["swap_ms"],
         "unit": "ms", "lower_is_better": True,
         "n_swaps": autoscale["n_swaps"]},
        {"metric": f"{prefix}_respawn_ms",
         "value": autoscale["respawn_ms"], "unit": "ms",
         "lower_is_better": True,
         "n_respawns": autoscale["n_respawns"]},
        {"metric": f"{prefix}_failed_requests",
         "value": autoscale["n_failed"], "unit": "count",
         "lower_is_better": True, "n_ok": autoscale["n_ok"]},
        {"metric": f"{prefix}_recompiles_after_warmup",
         "value": (fixed["recompiles_after_warmup"]
                   + autoscale["recompiles_after_warmup"]),
         "unit": "count", "lower_is_better": True,
         "warmup_compiles": (fixed["warmup_compiles"]
                             + autoscale["warmup_compiles"])},
    ]


def run_fleet_replay(*, seed: int = 0, n_requests: int = 120,
                     burst: int = 8, mean_gap_s: float = 0.004,
                     batch_sizes=(1, 2, 4), max_wait_ms: float = 3.0,
                     autoscale_max: int = 3,
                     chaos: str | None = "r0:kill@batch4",
                     hot_swap_after: int | None = None,
                     telemetry_path: str,
                     artifact_path: str | None = None,
                     emit=None) -> dict:
    """The SERVE_r03 bench: the SAME seeded bursty trace through two
    arms —

    * **fixed** — one replica, no supervisor (the SERVE_r01-style
      baseline);
    * **autoscale** — starts at one replica under a `FleetSupervisor`
      (AutoscalePolicy up to `autoscale_max`), absorbs the replica-kill
      `chaos` spec mid-traffic, and hot-swaps a freshly published
      checkpoint (the net's own weights re-saved at a new step — the
      train-fleet-publishes handoff) after `hot_swap_after` completed
      requests (default: half the trace).

    Each arm records to its own telemetry file (`<path>.fixed` /
    `<path>.autoscale`) and reconstructs from it ALONE; the artifact is
    the combined `fleet_*` metric-line set + gate summary."""
    import tempfile

    from deeplearning4j_tpu.serving.buckets import BucketLattice
    from deeplearning4j_tpu.serving.engine import InferenceEngine
    from deeplearning4j_tpu.serving.fleet import (AutoscalePolicy,
                                                  FleetSupervisor,
                                                  RespawnBackoff,
                                                  hot_swap)
    from deeplearning4j_tpu.serving.server import ServingServer
    from deeplearning4j_tpu.telemetry import Recorder
    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer

    if hot_swap_after is None:
        hot_swap_after = n_requests // 2
    trace = make_trace(seed, n_requests, mean_gap_s=mean_gap_s,
                       burst=burst, lengths=(8,))
    feat_rng = np.random.default_rng(seed + 1)
    feats = feat_rng.normal(size=(n_requests, 8)).astype(np.float32)

    def make_features(i, seq_len):
        return feats[i]

    def run_arm(arm: str) -> dict:
        tpath = f"{telemetry_path}.{arm}"
        rec = Recorder(tpath)
        rec.meta(role="trafficreplay-fleet", arm=arm, seed=seed,
                 n_requests=n_requests, burst=burst,
                 autoscale_max=autoscale_max,
                 chaos=chaos if arm == "autoscale" else None)
        net = _tiny_mlp()
        engine = InferenceEngine(
            net, BucketLattice(batch_sizes=batch_sizes),
            max_wait_ms=max_wait_ms, replicas=1,
            faults=chaos if arm == "autoscale" else None, recorder=rec)
        engine.warmup(make_features(0, 0))
        server = ServingServer(engine, port=0).start()
        supervisor = swapper = None
        if arm == "autoscale":
            supervisor = FleetSupervisor(
                engine, death_after_s=1.0,
                policy=AutoscalePolicy(max_replicas=autoscale_max),
                backoff=RespawnBackoff(base_s=0.01, jitter_frac=0.0),
                recorder=rec).run_in_thread(0.02)
            # the "training fleet publishes a step" half of the story:
            # the serving weights re-saved under a NEW step number, hot-
            # swapped once `hot_swap_after` requests completed
            ckdir = tempfile.mkdtemp(prefix="fleet_publish_")
            publish_net = engine.net.clone()
            publish_net.iteration_count = engine.restored_step + 1
            ShardedCheckpointer(ckdir).save(
                publish_net, publish_net.iteration_count, host=True)

            def swap_when_due():
                import time as _t

                deadline = _t.monotonic() + 60.0
                while _t.monotonic() < deadline:
                    if engine.served >= hot_swap_after:
                        hot_swap(engine, ckdir)
                        return
                    # scenario driver, not a runtime component: the
                    # served counter has no notify hook to block on,
                    # and the loop is deadline-bounded
                    _t.sleep(0.002)  # graftlint: disable=G027

            import threading as _th

            swapper = _th.Thread(target=swap_when_due, daemon=True,
                                 name="fleet-replay-swap")
            swapper.start()
        try:
            client = replay_http(server.url, trace,
                                 make_features=make_features)
        finally:
            if swapper is not None:
                swapper.join(timeout=60)
            if supervisor is not None:
                supervisor.stop()
            server.stop()
            rec.close()
        sb = reconstruct_fleet(tpath)
        sb["client"] = client
        sb["telemetry"] = tpath
        return sb

    fixed = run_arm("fixed")
    autoscale = run_arm("autoscale")
    lines = fleet_metric_lines(fixed, autoscale)
    if emit is not None:
        for line in lines:
            emit(line)
    out = {"fixed": fixed, "autoscale": autoscale, "lines": lines,
           "n_ok": fixed["n_ok"] + autoscale["n_ok"]}
    if artifact_path:
        out["summary"] = write_artifact(artifact_path, lines)
        out["artifact"] = artifact_path
    return out
