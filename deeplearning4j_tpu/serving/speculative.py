"""Self-speculative n-gram draft proposal — the host half of
speculative decoding.

The serving decode loop's floor is one jitted step per emitted token
per slot. Speculative decoding raises it: a cheap DRAFT proposer
guesses the next k-1 tokens of each slot's continuation, and ONE
fixed-shape verification step (nn/decode.make_verify_fn) checks the
whole window — every accepted draft is a decode step the slot never
has to run. The proposer here is SELF-speculative: no second model, no
extra device memory — it mines the request's own token history (prompt
+ everything emitted so far) for repeating structure:

* longest-suffix n-gram match (order high to low): if the last n
  tokens of the history occurred earlier, propose whatever followed
  that earlier occurrence — the classic prompt-lookup decoder, and a
  near-perfect oracle for the loops/copies greedy decode of a small LM
  collapses into;
* fallback: repeat the last token (the degenerate order-0 guess, which
  still wins whenever greedy decode has entered a fixed point).

The proposer is pure host-side bookkeeping over PYTHON INTS — it never
touches logits or device arrays (that's exactly what graftlint G024
polices; the device-side sampling path is ops/fused_sampling.py). Its
cost is the `draft_overhead_us` bench row; acceptance feeds the
`accepted_tokens_per_step` headline.

Acceptance (greedy): the verify step returns the model's argmax m_i
after each window row; the drafts d_1..d_{k-1} rode along. The
accepted window is the longest prefix where each draft matches the
argmax BEFORE it (d_{i+1} == m_i), plus the bonus token m_a that ends
it — a pure mask over the k verification rows, computed here in
`accept_greedy`, so the emitted sequence is BIT-IDENTICAL to
non-speculative greedy decode by construction: every emitted token is
a model argmax given exactly the tokens before it.
"""

from __future__ import annotations


class NgramProposer:
    """Draft proposer over one slot's token history.

    `propose(history, n)` -> list of n draft ints. `history` is the
    slot's full token context (prompt + emitted), oldest first.
    Stateless across calls — all signal is in the history itself — so
    slot reuse needs no reset and replica respawn loses nothing."""

    def __init__(self, max_order: int = 3):
        if max_order < 1:
            raise ValueError(f"need max_order >= 1, got {max_order}")
        self.max_order = int(max_order)

    def propose(self, history, n: int) -> list[int]:
        if n <= 0:
            return []
        hist = [int(t) for t in history]
        if not hist:
            return [0] * n
        out = self._ngram_continuation(hist, n)
        if out is None:
            out = [hist[-1]] * n  # order-0: greedy fixed-point guess
        return out

    def _ngram_continuation(self, hist, n: int):
        """Longest-suffix match: find the most recent earlier
        occurrence of the last `order` tokens (highest order first) and
        propose what followed it, extending cyclically from the match
        if the continuation runs off the end."""
        L = len(hist)
        for order in range(min(self.max_order, L - 1), 0, -1):
            suffix = hist[L - order:]
            # scan right-to-left: the most recent precedent is the
            # best predictor of what comes next
            for i in range(L - order - 1, -1, -1):
                if hist[i:i + order] == suffix:
                    cont = hist[i + order:i + order + n]
                    j = i
                    while len(cont) < n:
                        cont.append(hist[j % L])
                        j += 1
                    return cont[:n]
        return None


def accept_greedy(drafts, model_argmax) -> tuple[int, list[int]]:
    """The greedy acceptance mask for one slot's verify window.

    drafts: the k-1 proposed tokens d_1..d_{k-1} (window rows 1..k-1);
    model_argmax: the k verify-row argmaxes m_0..m_{k-1}. Returns
    (n_accepted, emitted): the longest prefix a with d_{i+1} == m_i for
    all i < a, and the a+1 tokens to emit — m_0..m_a (each one a model
    argmax given exactly its true prefix, so the emitted stream is
    bit-identical to non-speculative greedy). n_accepted counts the
    accepted DRAFTS (0..k-1); len(emitted) == n_accepted + 1."""
    m = [int(t) for t in model_argmax]
    d = [int(t) for t in drafts]
    if len(d) != len(m) - 1:
        raise ValueError(
            f"window mismatch: {len(d)} drafts vs {len(m)} verify rows")
    a = 0
    while a < len(d) and d[a] == m[a]:
        a += 1
    return a, m[:a + 1]
