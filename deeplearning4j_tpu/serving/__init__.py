"""Continuous-batching inference serving (ROADMAP "the million-user
path"): an async HTTP front-end over the jitted forward path.

The reference ships a REST UI and a CLI `predict` that loads the model
in-process (SURVEY L9/L10); this package is the high-throughput serving
story neither provides:

* `buckets.py`  — the padding-bucket lattice: a FIXED batch x seq shape
  grid every request is padded into, so the jitted forward never
  retraces after warmup (validated against the ops/ attention dispatch
  for long prompts).
* `batcher.py`  — dynamic batching: single requests coalesce into
  bucket-shaped batches under a max-wait deadline (injectable clock —
  the planner is a pure function, testable without sleeps); plus the
  generation-side `GenRequest`/`DecodeSlots` decode-slot state machine.
* `kvcache.py`  — page-block KV-cache accounting on the bucket lattice:
  capacities quantized to the (seq-bucket, page) grid, per-replica
  `PagePool` budgets with occupancy accounting and freed-on-completion
  semantics (exhaustion queues or 503s, never crashes).
* `engine.py`   — replica dispatch: one jitted forward worker per
  replica, round-robin batch assignment, checkpoint resume at startup,
  graceful drain on shutdown, zero-retrace accounting. Since r11 also
  `GenerationEngine`: prefill/decode-split autoregressive serving —
  chunked prefills interleaved into running decode batches over the
  KV cache (nn/decode.py steps), same zero-retrace discipline.
* `fleet.py`    — zero-downtime fleet operations (ISSUE 13): live
  weight hot-swap through a double-buffered `WeightStore` (reshard-
  aware restore off the request path, atomic flip between batches,
  typed `weight_swap` telemetry), replica self-healing (chaos specs
  from distributed/faults.py, heartbeat-driven reap/requeue/respawn
  with zero retraces), and telemetry-driven autoscaling (pure
  hysteresis decisions over queue depth + recent p99).
* `server.py`   — the stdlib ThreadingHTTPServer front door
  (`POST /predict`, streaming `POST /generate`), same lifecycle idiom
  as `ui/server.py`.
* `replay.py`   — the traffic-replay bench: a seeded mixed-length /
  bursty trace, with p50/p99/QPS reconstructed from telemetry
  `request` events ALONE (tools/trafficreplay.py is the CLI); the
  generation replay adds tokens/sec, TTFT percentiles, and cache-page
  occupancy.

Imports stay lazy/stdlib at package level so the graftlint AST stage's
no-jax stubs can walk the files.
"""

from deeplearning4j_tpu.serving.batcher import (
    Batcher,
    DecodeSlots,
    GenRequest,
    PendingRequest,
    plan_batch,
)
from deeplearning4j_tpu.serving.buckets import Bucket, BucketLattice
from deeplearning4j_tpu.serving.engine import (
    GenerationEngine,
    InferenceEngine,
    QueueFullError,
)
from deeplearning4j_tpu.serving.fleet import (
    AutoscalePolicy,
    CheckpointWatcher,
    FleetSupervisor,
    ReplicaFaultInjector,
    WeightStore,
    WeightSwapError,
    hot_swap,
)
from deeplearning4j_tpu.serving.kvcache import CachePlan, PagePool
from deeplearning4j_tpu.serving.server import ServingServer

__all__ = [
    "AutoscalePolicy",
    "Batcher",
    "Bucket",
    "BucketLattice",
    "CachePlan",
    "CheckpointWatcher",
    "DecodeSlots",
    "FleetSupervisor",
    "GenRequest",
    "GenerationEngine",
    "InferenceEngine",
    "PagePool",
    "PendingRequest",
    "QueueFullError",
    "ReplicaFaultInjector",
    "ServingServer",
    "WeightStore",
    "WeightSwapError",
    "hot_swap",
    "plan_batch",
]
