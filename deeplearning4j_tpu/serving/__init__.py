"""Continuous-batching inference serving (ROADMAP "the million-user
path"): an async HTTP front-end over the jitted forward path.

The reference ships a REST UI and a CLI `predict` that loads the model
in-process (SURVEY L9/L10); this package is the high-throughput serving
story neither provides:

* `buckets.py`  — the padding-bucket lattice: a FIXED batch x seq shape
  grid every request is padded into, so the jitted forward never
  retraces after warmup (validated against the ops/ attention dispatch
  for long prompts).
* `batcher.py`  — dynamic batching: single requests coalesce into
  bucket-shaped batches under a max-wait deadline (injectable clock —
  the planner is a pure function, testable without sleeps).
* `engine.py`   — replica dispatch: one jitted forward worker per
  replica, round-robin batch assignment, checkpoint resume at startup,
  graceful drain on shutdown, zero-retrace accounting.
* `server.py`   — the stdlib ThreadingHTTPServer front door
  (`POST /predict`), same lifecycle idiom as `ui/server.py`.
* `replay.py`   — the traffic-replay bench: a seeded mixed-length /
  bursty trace, with p50/p99/QPS reconstructed from telemetry
  `request` events ALONE (tools/trafficreplay.py is the CLI).

Imports stay lazy/stdlib at package level so the graftlint AST stage's
no-jax stubs can walk the files.
"""

from deeplearning4j_tpu.serving.batcher import Batcher, PendingRequest, plan_batch
from deeplearning4j_tpu.serving.buckets import Bucket, BucketLattice
from deeplearning4j_tpu.serving.engine import InferenceEngine
from deeplearning4j_tpu.serving.server import ServingServer

__all__ = [
    "Batcher",
    "Bucket",
    "BucketLattice",
    "InferenceEngine",
    "PendingRequest",
    "ServingServer",
    "plan_batch",
]
