"""The padding-bucket lattice — the shape contract between the batcher
and the jitted forward workers.

XLA compiles one program per input shape, so a serving path that feeds
raw request shapes into jit retraces on every new (batch, length) pair —
at mixed-length traffic that is a compile per request class, each worth
seconds of latency. The lattice fixes a small grid of (batch, seq)
shapes up front; every assembled batch is padded UP to the smallest
bucket that fits, the engine warms each bucket once, and after warmup
the compile count is provably frozen (tier-1 asserts zero retraces over
a replayed mixed-length trace).

Selection is a pure function of the request shapes (no clock, no
state), so bucket choice is deterministic and the batcher's planning is
unit-testable. Long-prompt buckets are validated against the ops/
attention dispatch envelope (`flash_attention.servable_seq`) at lattice
construction — a seq bucket the chunked flash path cannot tile fails at
startup with the dispatch's own reason string, not mid-traffic.

Pure stdlib: importable under the graftlint AST stage's no-jax stubs.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Bucket:
    """One lattice point: the padded batch size and (for sequence
    models) the padded time length. `seq is None` means the model takes
    fixed-shape features and only the batch dimension is bucketed."""

    batch: int
    seq: int | None = None

    def key(self) -> tuple:
        return (self.batch, self.seq)


class BucketLattice:
    """The fixed (batch, seq) grid. `batch_sizes` sorted ascending;
    `seq_lens` is None for fixed-shape (non-sequence) models."""

    def __init__(self, batch_sizes=(1, 2, 4, 8), seq_lens=None):
        sizes = sorted({int(b) for b in batch_sizes})
        if not sizes or sizes[0] < 1:
            raise ValueError(f"batch sizes must be >= 1, got {batch_sizes}")
        self.batch_sizes = tuple(sizes)
        self.seq_lens = None
        if seq_lens is not None:
            lens = sorted({int(t) for t in seq_lens})
            if not lens or lens[0] < 1:
                raise ValueError(f"seq lens must be >= 1, got {seq_lens}")
            self.seq_lens = tuple(lens)

    # ------------------------------------------------------- construction
    @classmethod
    def from_spec(cls, spec: str) -> "BucketLattice":
        """Parse a CLI `--buckets` spec. Two grammars:

        * ``"1,2,4,8"``          — batch sizes only (fixed-shape model)
        * ``"1x64,4x64,4x256"``  — explicit BxT pairs; the lattice is the
          cross product of the batch sizes and seq lens named.
        """
        entries = [e.strip() for e in spec.split(",") if e.strip()]
        if not entries:
            raise ValueError(f"empty bucket spec {spec!r}")
        if any("x" in e for e in entries):
            if not all("x" in e for e in entries):
                raise ValueError(
                    f"bucket spec {spec!r} mixes BxT pairs with bare batch "
                    "sizes; use one grammar")
            batches, seqs = [], []
            for e in entries:
                b, _, t = e.partition("x")
                batches.append(int(b))
                seqs.append(int(t))
            return cls(batch_sizes=batches, seq_lens=seqs)
        return cls(batch_sizes=[int(e) for e in entries])

    # --------------------------------------------------------- selection
    @property
    def max_batch(self) -> int:
        return self.batch_sizes[-1]

    @property
    def max_seq(self) -> int | None:
        return None if self.seq_lens is None else self.seq_lens[-1]

    def batch_bucket(self, n: int) -> int:
        """Smallest lattice batch size >= n (n never exceeds max_batch:
        the batcher caps a cut at max_batch)."""
        if n > self.max_batch:
            raise ValueError(f"batch {n} exceeds lattice max "
                             f"{self.max_batch}")
        for b in self.batch_sizes:
            if b >= n:
                return b
        raise AssertionError  # unreachable: guarded above

    def seq_bucket(self, t: int) -> int:
        """Smallest lattice seq len >= t; a prompt longer than the
        lattice max is a client error (HTTP 400), not a retrace."""
        if self.seq_lens is None:
            raise ValueError("lattice has no seq dimension (fixed-shape "
                             "model); construct with seq_lens to serve "
                             "sequences")
        if t > self.seq_lens[-1]:
            raise ValueError(f"sequence length {t} exceeds lattice max "
                             f"{self.seq_lens[-1]}")
        for s in self.seq_lens:
            if s >= t:
                return s
        raise AssertionError  # unreachable: guarded above

    def select(self, n_requests: int, max_len: int | None = None) -> Bucket:
        """The bucket for a group of `n_requests` whose longest sequence
        is `max_len` (None for fixed-shape models). Deterministic: a
        pure function of the two scalars."""
        seq = None
        if self.seq_lens is not None:
            if max_len is None:
                raise ValueError("sequence lattice needs the group's "
                                 "max length")
            seq = self.seq_bucket(max_len)
        return Bucket(self.batch_bucket(n_requests), seq)

    def shapes(self) -> list[Bucket]:
        """Every lattice point — the warmup set. One compile per entry;
        after warmup the engine's trace count must not move."""
        if self.seq_lens is None:
            return [Bucket(b) for b in self.batch_sizes]
        return [Bucket(b, s) for b in self.batch_sizes
                for s in self.seq_lens]

    def prefill_buckets(self, chunk: int) -> list[int]:
        """The generation engine's prefill warmup set: every seq bucket
        up to the chunk length (a long prompt arrives as a sequence of
        exactly these shapes, so warming them freezes the prefill trace
        count — the decode-side zero-retrace contract). The chunk must
        itself be a lattice point: an unwarmed chunk shape would be a
        guaranteed mid-traffic retrace."""
        if self.seq_lens is None:
            raise ValueError("generation needs a sequence lattice "
                             "(construct with seq_lens)")
        if chunk not in self.seq_lens:
            raise ValueError(
                f"prefill chunk {chunk} must be a lattice seq bucket "
                f"{list(self.seq_lens)} — chunks are warmed shapes")
        return [t for t in self.seq_lens if t <= chunk]

    # -------------------------------------------------------- validation
    def validate_attention(self, head_dim: int, *, causal: bool = True,
                           dropout: bool = False,
                           masked: bool = True) -> None:
        """Check every seq bucket against the ops/ attention dispatch
        envelope so a long-prompt bucket the chunked flash path cannot
        tile fails at server startup (with the dispatch's own reason)
        instead of erroring mid-traffic. No-op for fixed-shape lattices
        and a no-op import-wise until called (keeps this module
        stdlib-only for the lint stubs)."""
        if self.seq_lens is None:
            return
        from deeplearning4j_tpu.ops import flash_attention as fa

        for t in self.seq_lens:
            if not fa.servable_seq(t, head_dim, causal=causal,
                                   dropout=dropout, mask=masked):
                raise ValueError(
                    f"seq bucket {t} is outside the attention dispatch "
                    "envelope: "
                    + fa.chunked_unsupported_reason(
                        t, dropout=dropout, mask=masked, causal=causal,
                        head_dim=head_dim))

    def describe(self) -> dict:
        """JSON-able summary for /healthz and telemetry meta."""
        return {"batch_sizes": list(self.batch_sizes),
                "seq_lens": (None if self.seq_lens is None
                             else list(self.seq_lens))}


# The default serving lattice: powers of two up to batch 8; sequence
# models get their lattice from the CLI / engine config instead (seq
# grids are model-dependent).
DEFAULT_BATCH_SIZES = (1, 2, 4, 8)
