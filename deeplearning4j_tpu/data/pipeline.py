"""The pipelined input loader the fit loops ride.

`iter_prefetched(it, convert)` replaces the synchronous step-loop shape

    while it.has_next():
        ds = it.next()
        batch = net._batch_dict(ds)      # host conversion + device put
        step(batch)                      # ...only now does compute start

with a producer thread that runs ``convert`` (for the containers:
`_batch_dict` — jnp conversion plus the process-spanning
`globalize_batch` device put) ahead of the step loop, double-buffering
into a depth-k bounded `Channel` of *device-resident* batches. The step
thread dequeues under a typed ``input_wait`` telemetry span: at steady
state on a compute-bound workload the span's seconds are ~0 — the
starve-proof the bench's `input_pipeline` mode gates on — while on an
input-bound workload the wall win is overlap itself
(sync step = convert + compute; pipelined = max(convert, compute)).

Ordering is the sync loop's: one producer, FIFO channel, so batch k is
converted before batch k+1 and consumed in order — pipelined `fit` is
bit-identical to synchronous `fit` (asserted off-TPU in
tests/test_data_pipeline.py). A producer exception is re-raised in the
step loop at the point its batch would have been consumed.

The queue-depth knob: ``depth`` argument > `set_prefetch_depth` >
``DL4J_TPU_PREFETCH_DEPTH`` env > DEFAULT_DEPTH (2 — classic double
buffering). Depth 0 is the synchronous fallback (the bench's `sync`
arm, and the path taken when an iterator declares
``async_supported() == False``); it runs in THIS module so graftlint
G020's data/ allowlist covers the one blessed synchronous conversion
site.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu.data.prefetcher import EOS, Prefetcher
from deeplearning4j_tpu.data.sharding import ShardAssignment, local_rows
from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

DEFAULT_DEPTH = 2
ENV_DEPTH = "DL4J_TPU_PREFETCH_DEPTH"

_depth_override: Optional[int] = None


def set_prefetch_depth(depth: Optional[int]) -> Optional[int]:
    """Process-wide prefetch depth override (the CLI's
    ``--prefetch-depth`` and the bench's arm toggle). ``None`` restores
    the env/default resolution; returns the previous override."""
    global _depth_override
    prev, _depth_override = _depth_override, depth
    return prev


def prefetch_depth(depth: Optional[int] = None) -> int:
    """Resolve the queue-depth knob: explicit arg > `set_prefetch_depth`
    override > ``DL4J_TPU_PREFETCH_DEPTH`` > DEFAULT_DEPTH."""
    if depth is not None:
        return int(depth)
    if _depth_override is not None:
        return int(_depth_override)
    env = os.environ.get(ENV_DEPTH)
    if env:
        try:
            return int(env)
        except ValueError:
            raise ValueError(
                f"{ENV_DEPTH}={env!r} is not an integer queue depth")
    return DEFAULT_DEPTH


def iter_prefetched(it, convert: Callable, *, depth: Optional[int] = None,
                    recorder=None):
    """Yield ``(ds, convert(ds))`` over a DataSetIterator with
    ``convert`` running on a background prefetch thread.

    ``convert`` must be order-deterministic and thread-compatible (the
    containers' `_batch_dict` is both: pure conversion + device put).
    Every dequeue is timed under an ``input_wait`` span carrying
    ``pipelined`` and the post-dequeue ``buffered`` count. Generator
    close / step-loop exception stops the producer and joins its thread
    — no orphan producers across epochs.
    """
    k = prefetch_depth(depth)
    if recorder is None:
        from deeplearning4j_tpu.telemetry import get_default

        recorder = get_default()
    if k <= 0 or not it.async_supported():
        # the blessed synchronous fallback: the input stall IS the
        # conversion, so the span wraps it
        while it.has_next():
            ds = it.next()
            with recorder.span("input_wait", pipelined=False):
                batch = convert(ds)
            yield ds, batch
        return

    def source():
        while it.has_next():
            yield it.next()

    pf = Prefetcher(source, depth=k, transform=lambda ds: (ds, convert(ds)),
                    name="input-pipeline")
    try:
        while True:
            with recorder.span("input_wait", pipelined=True) as span:
                item = pf.get()
                span["buffered"] = pf.buffered()
            if item is EOS:
                return
            yield item
    finally:
        pf.stop()


class ShardedDataSetIterator(DataSetIterator):
    """A DataSetIterator over this process's shard of a full in-memory
    dataset, driven by `ShardAssignment` — the loader a fleet member
    feeds `fit` so every process walks the SAME global batch sequence
    at any fleet size.

    ``set_epoch(e)`` re-keys the permutation (epoch-boundary reshuffle);
    ``reset()`` rewinds the CURRENT epoch — fit's per-epoch reset replays
    deterministically, and callers that want fresh shuffles advance the
    epoch explicitly (the elastic step loop derives it from the global
    step counter).
    """

    def __init__(self, features, labels, global_batch: int, *,
                 process_index: int = 0, process_count: int = 1,
                 seed: int = 0, epoch: int = 0):
        super().__init__()
        self._x = np.asarray(features)
        self._y = np.asarray(labels)
        self.assignment = ShardAssignment(
            self._x.shape[0], global_batch,
            process_index=process_index, process_count=process_count,
            seed=seed)
        self._epoch = int(epoch)
        self._step = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)
        self._step = 0

    @property
    def epoch(self) -> int:
        return self._epoch

    def has_next(self) -> bool:
        return self._step < self.assignment.steps_per_epoch

    def next(self, num=None):
        idx = self.assignment.local_indices(self._epoch, self._step)
        self._step += 1
        return self._apply_pre(DataSet(self._x[idx], self._y[idx]))

    def reset(self) -> None:
        self._step = 0

    def batch(self) -> int:
        return (self.assignment.global_batch
                // self.assignment.process_count)

    def total_examples(self) -> int:
        return (self.assignment.steps_per_epoch * self.batch())


__all__ = ["DEFAULT_DEPTH", "ENV_DEPTH", "ShardedDataSetIterator",
           "iter_prefetched", "local_rows", "prefetch_depth",
           "set_prefetch_depth"]
