"""Event-driven background prefetch — the one implementation in the tree.

The r6 `AsyncDataSetIterator` and nlp's `PrefetchingSentenceIterator`
each hand-rolled a producer thread over a `queue.Queue` with polling
waits (`put(timeout=0.1)` / `get(timeout=0.5)` spin loops): an idle
prefetcher burned a core re-arming timeouts, and the shutdown protocol
had a real hole — a producer that died after `put_nowait(_SENTINEL)`
hit `queue.Full` left the consumer's drain loop spinning against a
queue that would never carry the sentinel.

`Channel` replaces both with a Condition-based bounded buffer where
every wait is event-driven (zero CPU while blocked) and every shutdown
path wakes the other side exactly once:

- producer EOS / error → ``close()`` marks the channel; a consumer
  blocked in ``get()`` wakes and sees EOS (or the producer's exception)
  the moment the buffer drains — no sentinel item that can fail to fit.
- consumer ``stop()`` (reset/teardown) → a producer blocked on a full
  buffer wakes, observes the stop, and exits; buffered items are
  discarded under the same lock, so a reset can never race a late put.

Pure stdlib — no jax, no numpy — so the module (and everything that
adapts onto it) stays importable under graftlint's no-jax stubs.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Iterable, Optional


class _Eos:
    """End-of-stream marker returned by ``Channel.get``/``Prefetcher.get``
    (an identity token, never a valid item)."""

    def __repr__(self):  # pragma: no cover - debugging nicety
        return "<EOS>"


EOS = _Eos()


class Channel:
    """Bounded producer/consumer buffer with event-driven blocking.

    One producer, one consumer (the prefetch topology). ``put`` blocks
    on a Condition while the buffer is full; ``get`` blocks while it is
    empty and neither closed nor stopped. There are no timeouts anywhere
    — wakeups come only from the opposite side's notify.
    """

    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError(f"channel depth must be >= 1, got {depth}")
        self._buf: deque = deque()
        self._depth = depth
        lock = threading.Lock()
        self._not_full = threading.Condition(lock)
        self._not_empty = threading.Condition(lock)
        self._closed = False
        self._stopped = False
        self._error: Optional[BaseException] = None

    def put(self, item) -> bool:
        """Producer side: block until there is room (or the consumer
        stopped the channel). Returns False when stopped — the producer
        must exit without retrying."""
        with self._not_full:
            while len(self._buf) >= self._depth and not self._stopped:
                self._not_full.wait()
            if self._stopped:
                return False
            self._buf.append(item)
            self._not_empty.notify()
            return True

    def close(self, error: Optional[BaseException] = None) -> None:
        """Producer side: no more items will arrive. With ``error``, the
        consumer re-raises it (once) after draining what's buffered —
        the step loop sees the producer's exception at the point it
        would have consumed the missing batch."""
        with self._not_full:
            self._error = error
            self._closed = True
            self._not_empty.notify_all()

    def get(self):
        """Consumer side: next item, else the producer's exception, else
        EOS. Blocks event-driven while the channel is open and empty."""
        with self._not_empty:
            while not self._buf and not self._closed and not self._stopped:
                self._not_empty.wait()
            if self._buf and not self._stopped:
                item = self._buf.popleft()
                self._not_full.notify()
                return item
            if self._error is not None:
                error, self._error = self._error, None
                raise error
            return EOS

    def stop(self) -> None:
        """Consumer side: abort the producer and discard the buffer —
        the reset path. Wakes a producer blocked on a full buffer (it
        observes the stop and exits) and any concurrent ``get``."""
        with self._not_full:
            self._stopped = True
            self._buf.clear()
            self._not_full.notify_all()
            self._not_empty.notify_all()

    def __len__(self) -> int:
        with self._not_full:
            return len(self._buf)


class Prefetcher:
    """A daemon thread filling a `Channel` from ``source``.

    ``source``: an iterable, or a zero-arg callable returning one (the
    callable form defers work — e.g. a backend ``reset()`` — into the
    thread). ``transform`` runs per item ON the prefetch thread; for the
    input pipeline that's where `_batch_dict` conversion and the
    `globalize_batch` device put live, overlapping step compute.

    ``get()`` returns the next (transformed) item, raises the producer's
    exception, or returns EOS. ``stop()`` aborts the producer, discards
    buffered items, and joins the thread — the reset protocol every
    adapter (AsyncDataSetIterator, PrefetchingSentenceIterator) shares.
    """

    def __init__(self, source: Iterable | Callable[[], Iterable], *,
                 depth: int = 2, transform: Optional[Callable] = None,
                 name: str = "prefetch"):
        self._chan = Channel(depth)
        chan = self._chan

        def run():
            try:
                items = source() if callable(source) else source
                for item in items:
                    if transform is not None:
                        item = transform(item)
                    if not chan.put(item):
                        return  # stopped by the consumer
            except BaseException as exc:  # surfaced at the next get()
                chan.close(error=exc)
                return
            chan.close()

        self._thread = threading.Thread(target=run, daemon=True, name=name)
        self._thread.start()

    def get(self):
        return self._chan.get()

    def buffered(self) -> int:
        """Items currently queued (the bench's queue-occupancy signal)."""
        return len(self._chan)

    def stop(self, join_timeout: float = 5.0) -> bool:
        """Abort the producer and join its thread; True when the thread
        exited within ``join_timeout``."""
        self._chan.stop()
        self._thread.join(timeout=join_timeout)
        return not self._thread.is_alive()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()
