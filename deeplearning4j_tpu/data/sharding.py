"""Deterministic per-process shard assignment.

The contract (the elastic-compatibility property PR 6's `batch_for_step`
gestured at, made a subsystem):

1. The GLOBAL example order for an epoch is a seeded permutation keyed
   off ``(seed, epoch)`` only — never the process count — so every
   fleet size walks the identical global batch sequence.
2. Step ``s`` (0-based within the epoch) owns the contiguous window
   ``perm[s*B : (s+1)*B]`` of that order (``B`` = global batch).
3. Process ``p`` of ``N`` owns the contiguous process-major rows
   ``[p*B/N, (p+1)*B/N)`` of its step's window — the same split
   `distributed/global_mesh.local_shard` applies to host arrays and
   `make_global_mesh`'s device enumeration implies.

Consequences, both asserted in tests/test_data_pipeline.py:

- **Reconstruction**: concatenating the N processes' local index sets
  for a step, in process order, is exactly the global window — no
  example skipped or duplicated at any N.
- **Elastic bit-identity**: a fleet re-formed N→N' that resumes at step
  ``s`` sees the same remaining global windows an uninterrupted run
  would, because nothing in the mapping depends on N.

Pure numpy + stdlib; importable under graftlint's no-jax stubs.
"""

from __future__ import annotations

import numpy as np


def process_slice(n_rows: int, process_index: int,
                  process_count: int) -> slice:
    """The process-major contiguous row slice ``[p*n/N, (p+1)*n/N)`` —
    the one split rule shared by `ShardAssignment`,
    `distributed/global_mesh.local_shard`, and the CLI's per-process
    batch cutter. Raises when the rows don't divide evenly (an uneven
    shard would desync the fleet's lockstep batch shapes)."""
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} out of range for "
            f"process_count {process_count}")
    if n_rows % process_count:
        raise ValueError(
            f"{n_rows} rows do not split over {process_count} processes")
    per = n_rows // process_count
    return slice(process_index * per, (process_index + 1) * per)


def local_rows(array, process_index: int, process_count: int,
               axis: int = 0):
    """This process's contiguous slice of a full host array along
    ``axis`` (the `process_slice` rule applied to data)."""
    arr = np.asarray(array)
    sl = process_slice(arr.shape[axis], process_index, process_count)
    idx = [slice(None)] * arr.ndim
    idx[axis] = sl
    return arr[tuple(idx)]


def epoch_permutation(n_examples: int, epoch: int, seed: int) -> np.ndarray:
    """The global example order for one epoch: a PhiloxSeedSequence-fed
    permutation keyed off ``(seed, epoch)`` ONLY. Identical on every
    process of every fleet size — the root determinism the whole
    assignment contract rests on."""
    rng = np.random.default_rng(np.random.SeedSequence([int(seed),
                                                        int(epoch)]))
    return rng.permutation(n_examples)


class ShardAssignment:
    """Stable global example→process mapping for an epoch-structured run.

    ``global_batch`` must divide by ``process_count`` (rule 3) and
    ``n_examples`` truncates to whole global batches (the ragged tail is
    dropped deterministically — the same tail at every N, so no fleet
    shape ever trains on rows another shape skipped).
    """

    def __init__(self, n_examples: int, global_batch: int, *,
                 process_index: int = 0, process_count: int = 1,
                 seed: int = 0):
        if global_batch < 1:
            raise ValueError(f"global_batch must be >= 1, got {global_batch}")
        if global_batch > n_examples:
            raise ValueError(
                f"global_batch {global_batch} exceeds {n_examples} examples")
        # validates index/count and divisibility up front
        self._local = process_slice(global_batch, process_index,
                                    process_count)
        self.n_examples = int(n_examples)
        self.global_batch = int(global_batch)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.seed = int(seed)
        self.steps_per_epoch = self.n_examples // self.global_batch

    def global_indices(self, epoch: int, step: int) -> np.ndarray:
        """The global batch window for 0-based ``step`` of ``epoch`` —
        process-count independent by construction."""
        if not 0 <= step < self.steps_per_epoch:
            raise ValueError(
                f"step {step} out of range [0, {self.steps_per_epoch})")
        perm = epoch_permutation(self.n_examples, epoch, self.seed)
        b = self.global_batch
        return perm[step * b:(step + 1) * b]

    def local_indices(self, epoch: int, step: int) -> np.ndarray:
        """This process's rows of the step's global window (rule 3)."""
        return self.global_indices(epoch, step)[self._local]

    def for_process(self, process_index: int,
                    process_count: int) -> "ShardAssignment":
        """The same assignment viewed from another fleet shape — what an
        elastic re-form constructs after N→N'."""
        return ShardAssignment(
            self.n_examples, self.global_batch,
            process_index=process_index, process_count=process_count,
            seed=self.seed)
