"""Fleet worker for the input-pipeline bench (bench.py `input_pipeline`).

Run under `distributed/launcher.launch_local` as a 2-process x 4-device
fleet (or standalone single-process for the tier-1 structure test):
every process trains the SAME tiny MLP through the stock `fit()` path
over its `ShardedDataSetIterator` shard, alternating two arms —

- **sync**: prefetch depth 0 — batch decode + `_batch_dict`
  globalization run inline in the step loop (the pre-ISSUE-12 shape);
- **pipelined**: depth-k bounded queue — decode + conversion + device
  put on the prefetch thread, overlapping step compute.

Two workloads bracket the regimes the ISSUE names. The record-fetch
stand-in has two honest components: an IO-latency wait (the blocking
read every real record reader pays — storage/network latency holds no
core and no GIL, and hiding it is the input pipeline's first job) plus
numpy decode passes (which need a FREE core to overlap; on this
repo's 1-core CI container the IO component is what the pipeline
provably hides, and the decode component rides along on real hosts):

- **input-bound**: per-batch fetch+decode costs more than the step;
  the headline is the pipelined/sync wall ratio (sync = fetch + compute
  per step, pipelined = max(fetch, compute)).
- **compute-bound**: trivial fetch; the proof obligation is
  steady-state `input_wait` p99 ~= 0 (the dequeue never stalls because
  the producer is always ahead) — reconstructed from the in-memory
  telemetry `input_wait` spans alone.

Arms are interleaved A/B (sync, pipelined, sync, ...) per repeat so
shared-host contention drift hits both arms equally (the r3
bench_resnet_dp discipline); the headline is the MEDIAN of per-repeat
ratios. Process 0 prints one ``RESULT {json}`` line the bench mode
parses.
"""

from __future__ import annotations

import json
import statistics
import sys
import time

import numpy as np


def _percentile(vals, q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


def _build_net(seed: int = 5):
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
        Updater,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(0.01)
        .updater(Updater.SGD)
        .list()
        .layer(DenseLayer(n_in=64, n_out=128, activation="tanh"))
        .layer(DenseLayer(n_in=128, n_out=128, activation="tanh"))
        .layer(OutputLayer(n_in=128, n_out=10, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _decode_preprocessor(passes: int, io_s: float, seed: int = 17):
    """The record fetch+decode stand-in: a blocking IO-latency wait of
    ``io_s`` seconds (the storage/network read — releases the core) then
    ``passes`` host-side numpy decode passes over the batch features
    (normalize + mix), mutating the DataSet in place (the
    DataSetPreProcessor contract). Runs wherever the iterator's `next()`
    runs — the step thread in the sync arm, the prefetch thread in the
    pipelined arm."""
    rng = np.random.default_rng(seed)
    mix = rng.standard_normal((64, 64)).astype(np.float32) * 0.1

    def pre(ds):
        if io_s > 0:
            time.sleep(io_s)
        f = ds.features
        for _ in range(passes):
            f = np.tanh(f @ mix)
            f = (f - f.mean()) / (f.std() + 1e-6)
        ds.features = f.astype(np.float32)

    return pre


def _make_iterator(global_batch, steps, decode_passes, io_s, *,
                   process_index, process_count, seed):
    from deeplearning4j_tpu.data.pipeline import ShardedDataSetIterator

    n = global_batch * steps
    rng = np.random.default_rng(seed)
    x = rng.random((n, 64), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
    it = ShardedDataSetIterator(x, y, global_batch,
                                process_index=process_index,
                                process_count=process_count, seed=seed)
    it.set_pre_processor(_decode_preprocessor(decode_passes, io_s))
    return it


def _sync_params(net) -> float:
    import jax

    leaf = jax.tree.leaves(net.params)[0]
    return float(np.asarray(leaf).ravel()[0])


def _timed_fit(net, it) -> float:
    t0 = time.perf_counter()
    net.fit(it, epochs=1)
    _sync_params(net)  # force execution of the whole dispatched chain
    return time.perf_counter() - t0


def run_bench(*, process_index: int = 0, process_count: int = 1,
              mesh=None, steps: int = 12, repeats: int = 3,
              global_batch: int = 32, depth: int = 2,
              input_bound_passes: int = 8, input_bound_io_s: float = 0.075,
              compute_bound_passes: int = 1,
              compute_bound_io_s: float = 0.0002, seed: int = 23) -> dict:
    """Both workloads, both arms, interleaved. Returns the result dict
    process 0 prints (every process computes it — fleets must run the
    identical step sequence to keep collectives in lockstep)."""
    from deeplearning4j_tpu.data.pipeline import set_prefetch_depth
    from deeplearning4j_tpu.telemetry.recorder import (
        Recorder,
        set_default,
    )

    net = _build_net()
    if mesh is not None:
        net.set_mesh(mesh)

    def fresh_it(passes, io_s=0.0):
        return _make_iterator(global_batch, steps, passes, io_s,
                              process_index=process_index,
                              process_count=process_count, seed=seed)

    # local recorder: the input_wait spans this run's percentiles come
    # from (restored afterwards so the sweep's shared file recorder is
    # untouched by the hot loop)
    rec = Recorder(path=None, keep=16384)
    prev_rec = set_default(rec)
    result = {"process_id": process_index, "n_processes": process_count,
              "steps": steps, "repeats": repeats, "depth": depth,
              "global_batch": global_batch}
    try:
        # warmup: compile the train step once, outside every timing
        prev = set_prefetch_depth(0)
        net.fit(fresh_it(1), epochs=1)
        for name, passes, io_s in (
                ("input_bound", input_bound_passes, input_bound_io_s),
                ("compute_bound", compute_bound_passes,
                 compute_bound_io_s)):
            sync_s, pipe_s = [], []
            wait_events = []
            for _ in range(repeats):
                set_prefetch_depth(0)
                sync_s.append(_timed_fit(net, fresh_it(passes, io_s)))
                set_prefetch_depth(depth)
                n0 = len(rec.events)
                pipe_s.append(_timed_fit(net, fresh_it(passes, io_s)))
                wait_events.extend(
                    e for e in list(rec.events)[n0:]
                    if e.get("event") == "span"
                    and e.get("name") == "input_wait"
                    and e.get("pipelined"))
            ratios = sorted(s / p for s, p in zip(sync_s, pipe_s))
            # steady state: drop each repeat's FIRST dequeue (the cold
            # fill before the producer gets ahead); with `steps`
            # dequeues + EOS per repeat the slice math stays simple
            waits = [e["seconds"] for i, e in enumerate(wait_events)
                     if i % (steps + 1) != 0]
            result[name] = {
                "sync_s": [round(s, 4) for s in sync_s],
                "pipelined_s": [round(s, 4) for s in pipe_s],
                "speedup": round(statistics.median(ratios), 4),
                "ratio_spread": [round(ratios[0], 4),
                                 round(ratios[-1], 4)],
                "sync_step_ms": round(
                    1000 * statistics.median(sync_s) / steps, 3),
                "pipelined_step_ms": round(
                    1000 * statistics.median(pipe_s) / steps, 3),
                "input_wait_p50_ms": round(
                    1000 * _percentile(waits, 0.50), 3),
                "input_wait_p99_ms": round(
                    1000 * _percentile(waits, 0.99), 3),
                "n_wait_spans": len(waits),
            }
    finally:
        set_prefetch_depth(prev)
        set_default(prev_rec)
    return result


def main(argv=None) -> int:
    """``python -m deeplearning4j_tpu.data.bench_worker ['{json}']`` —
    the optional json argument overrides `run_bench` keywords (the slow
    fleet test runs a reduced matrix; the bench mode takes defaults).
    Every fleet member must receive the SAME overrides: the arms/steps
    sequence is the collective program."""
    from deeplearning4j_tpu.distributed import bootstrap

    argv = sys.argv[1:] if argv is None else argv
    overrides = json.loads(argv[0]) if argv else {}
    process_index, process_count, mesh = 0, 1, None
    if bootstrap.env_contract_present():
        info = bootstrap.initialize()
        process_index = info["process_id"]
        process_count = info["num_processes"]
        from deeplearning4j_tpu.distributed.global_mesh import (
            make_global_mesh,
        )

        mesh = make_global_mesh({"data": -1})
    result = run_bench(process_index=process_index,
                       process_count=process_count, mesh=mesh,
                       **overrides)
    if process_index == 0:
        print("RESULT " + json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
