"""`data/` — the async sharded input subsystem (ISSUE 12).

The reference ships a dedicated input layer (AsyncDataSetIterator +
Canova record readers, SURVEY §1 L4); this package is its TPU-native
replacement, built from three pieces:

- ``prefetcher``: the ONE background-prefetch implementation in the
  tree — an event-driven bounded channel (zero CPU while idle; no
  polling timeouts) plus the producer-thread wrapper every other
  prefetching façade (datasets/async_iterator.py,
  nlp/text.PrefetchingSentenceIterator) adapts onto.
- ``sharding``: deterministic global example→process assignment keyed
  off ``(process_index, process_count, epoch, seed)`` — the global
  batch sequence is process-count-INDEPENDENT, so an elastic re-form
  at N→N' resumes with no example skipped or duplicated.
- ``pipeline``: the pipelined loader the fit loops ride —
  ``_batch_dict`` conversion and ``globalize_batch`` device placement
  run on a prefetch thread feeding a depth-k bounded queue of
  *device-resident* batches, overlapping host input work with step
  compute; every dequeue is timed under an ``input_wait`` telemetry
  span (the starvation proof).

Everything here imports jax lazily (or not at all): the package must
stay importable under graftlint's no-jax stubs.
"""

from deeplearning4j_tpu.data.prefetcher import EOS, Channel, Prefetcher
from deeplearning4j_tpu.data.sharding import (
    ShardAssignment,
    epoch_permutation,
    local_rows,
    process_slice,
)
from deeplearning4j_tpu.data.pipeline import (
    ShardedDataSetIterator,
    iter_prefetched,
    prefetch_depth,
    set_prefetch_depth,
)

__all__ = [
    "EOS", "Channel", "Prefetcher",
    "ShardAssignment", "epoch_permutation", "local_rows", "process_slice",
    "ShardedDataSetIterator", "iter_prefetched", "prefetch_depth",
    "set_prefetch_depth",
]
