"""Checkpointing — zip-format model serialization.

Reference: util/ModelSerializer.java:37-78 — a zip holding
`configuration.json` (Jackson), `coefficients.bin` (flat params), and the
updater blob; restoreMultiLayerNetwork/restoreComputationGraph.

Same logical format here: a zip with
- configuration.json   (serde config JSON, includes net kind)
- params.npz           (param pytree as named numpy arrays)
- state.npz            (mutable state: BatchNorm running stats, ...)
- updater.npz          (optax opt_state leaves)
- meta.json            (iteration/epoch counters, format version)

Restoring rebuilds the network from config and loads the pytrees — resume
continues training bit-exactly (updater state + step counter preserved,
which the reference also stores).
"""

from __future__ import annotations

import io
import json
import zipfile

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf import serde

_FORMAT_VERSION = 1


def _save_tree(zf: zipfile.ZipFile, name: str, tree):
    leaves, treedef = jax.tree.flatten(tree)
    buf = io.BytesIO()
    np.savez(buf, *[np.asarray(l) for l in leaves])
    zf.writestr(name, buf.getvalue())
    return str(treedef)


def _load_leaves(zf: zipfile.ZipFile, name: str):
    data = zf.read(name)
    npz = np.load(io.BytesIO(data), allow_pickle=False)
    return [npz[k] for k in npz.files]


def _restore_tree(template, leaves):
    _, treedef = jax.tree.flatten(template)
    t_leaves = jax.tree.leaves(template)
    if len(t_leaves) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} arrays but model expects {len(t_leaves)}")
    cast = [jnp.asarray(l, t.dtype) for l, t in zip(leaves, t_leaves)]
    return jax.tree.unflatten(treedef, cast)


class ModelSerializer:
    @staticmethod
    def write_model(net, path, save_updater: bool = True):
        """Serialize a MultiLayerNetwork or ComputationGraph to a zip."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        kind = "ComputationGraph" if isinstance(net, ComputationGraph) else "MultiLayerNetwork"
        # an active pipeline mesh keeps params in the stacked-stage layout;
        # checkpoints always store the portable canonical per-layer tree
        params = net.params
        opt_state = net.opt_state
        plan = getattr(net, "_pp_plan", None)
        if plan is not None:
            from deeplearning4j_tpu.parallel.placement import _map_param_shaped

            canonical = plan.to_canonical(params)
            if opt_state is not None:
                opt_state = _map_param_shaped(opt_state, params,
                                              plan.to_canonical)
            params = canonical
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("configuration.json", net.conf.to_json())
            _save_tree(zf, "params.npz", params)
            _save_tree(zf, "state.npz", net.state)
            if save_updater and opt_state is not None:
                _save_tree(zf, "updater.npz", opt_state)
            from deeplearning4j_tpu.nn.updater import FLAT_LAYOUT_VERSION

            zf.writestr("meta.json", json.dumps({
                "format_version": _FORMAT_VERSION,
                "kind": kind,
                "iteration": net.iteration_count,
                "epoch": getattr(net, "epoch_count", 0),
                # layout of any flat-view optimizer vectors in
                # updater.npz (see nn/updater.upgrade_flat_layout)
                "flat_layout": FLAT_LAYOUT_VERSION,
            }))

    @staticmethod
    def restore(path, expected_kind=None):
        """Restore either network kind (dispatches on stored metadata);
        expected_kind rejects the other kind with a named error."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(path, "r") as zf:
            meta = json.loads(zf.read("meta.json"))
            if expected_kind is not None and meta["kind"] != expected_kind:
                raise ValueError(
                    f"{path} holds a {meta['kind']}, not a {expected_kind} "
                    f"(reference restore{expected_kind} rejects the wrong "
                    f"model kind)")
            conf = serde.from_json(zf.read("configuration.json").decode())
            if meta["kind"] == "ComputationGraph":
                net = ComputationGraph(conf)
            else:
                net = MultiLayerNetwork(conf)
            net.init()
            net.params = _restore_tree(net.params, _load_leaves(zf, "params.npz"))
            net.state = _restore_tree(net.state, _load_leaves(zf, "state.npz"))
            if "updater.npz" in zf.namelist():
                leaves = _load_leaves(zf, "updater.npz")
                if meta.get("flat_layout", 1) < 2:
                    # pre-r5 checkpoints flattened every leaf row-major;
                    # v2 stores lane-hostile leaves axis-rotated — reorder
                    # any full-length flat vectors (adam m/v, momentum)
                    # so resumed moments line up with today's layout
                    from deeplearning4j_tpu.nn.updater import (
                        FlatViewTransform,
                        flat_state_size,
                        upgrade_flat_layout,
                    )

                    if isinstance(net.tx, FlatViewTransform):
                        total = flat_state_size(net.params)
                        leaves = [
                            np.asarray(upgrade_flat_layout(
                                jnp.asarray(l), net.params))
                            if l.ndim == 1 and l.size == total else l
                            for l in leaves]
                try:
                    net.opt_state = _restore_tree(net.opt_state, leaves)
                except (ValueError, TypeError, KeyError):
                    # layout bridge: the checkpoint's updater state may be
                    # in the other optimizer layout (per-leaf tree vs the
                    # flat-view fused state) — rebuild and retry (`net` is
                    # local to this restore, so mutating is safe). A
                    # mismatch can surface as TypeError/KeyError too
                    # (pytree structure vs leaf-count differences raise
                    # different types)
                    from deeplearning4j_tpu.nn.updater import (
                        rebuild_other_layout,
                    )

                    net.tx = rebuild_other_layout(net)
                    net.opt_state = _restore_tree(
                        net.tx.init(net.params), leaves)
            net.iteration_count = meta.get("iteration", 0)
            if hasattr(net, "epoch_count"):
                net.epoch_count = meta.get("epoch", 0)
        return net

    @staticmethod
    def restore_multi_layer_network(path):
        return ModelSerializer.restore(path, "MultiLayerNetwork")

    @staticmethod
    def restore_computation_graph(path):
        return ModelSerializer.restore(path, "ComputationGraph")
