"""Time-series helpers (reference: util/TimeSeriesUtils.java —
movingAverage:39, reshapeTimeSeriesMaskToVector:53)."""

from __future__ import annotations

import numpy as np


def moving_average(to_avg, n: int) -> np.ndarray:
    """Simple moving average with window n; output length len-n+1
    (TimeSeriesUtils.movingAverage — cumsum formulation)."""
    arr = np.asarray(to_avg, dtype=np.float64).ravel()
    if n <= 0 or n > arr.size:
        raise ValueError("window out of range")
    c = np.concatenate([[0.0], np.cumsum(arr)])
    return (c[n:] - c[:-n]) / n


def reshape_time_series_mask_to_vector(mask) -> np.ndarray:
    """[batch, time] mask → flat [batch*time] vector, batch-major
    (TimeSeriesUtils.reshapeTimeSeriesMaskToVector)."""
    mask = np.asarray(mask)
    if mask.ndim != 2:
        raise ValueError("expected [batch, time] mask")
    return mask.reshape(-1)
