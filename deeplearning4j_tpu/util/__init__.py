from deeplearning4j_tpu.util.model_serializer import ModelSerializer  # noqa: F401
