"""Image file → array loading (reference util/ImageLoader.java).

The reference flattens images row-major into INDArrays with optional
resize (`ImageLoader.java: asRowVector/asMatrix/toImage`); here images load
into NHWC float32 arrays in [0, 1] — the layout every conv layer in this
framework consumes directly (XLA's native TPU conv layout), instead of the
reference's NCHW.

Backed by PIL when present; a built-in decoder covers PPM/PGM so the
pipeline still works with zero dependencies.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

try:
    from PIL import Image

    _HAVE_PIL = True
except Exception:  # pragma: no cover - PIL is in the base image
    _HAVE_PIL = False


def _read_pnm(path: str) -> np.ndarray:
    """Minimal PPM (P6) / PGM (P5) decoder — the no-dependency fallback."""
    with open(path, "rb") as f:
        data = f.read()
    fields: list = []
    i = 0
    while len(fields) < 4:
        if data[i:i + 1] == b"#":
            while data[i:i + 1] not in (b"\n", b""):
                i += 1
        elif data[i:i + 1].isspace():
            i += 1
        else:
            j = i
            while not data[j:j + 1].isspace():
                j += 1
            fields.append(data[i:j])
            i = j
    magic, w, h, maxval = fields[0], int(fields[1]), int(fields[2]), int(fields[3])
    if maxval != 255:
        raise ValueError(
            f"unsupported PNM maxval {maxval} in {path} (only 8-bit, "
            f"maxval 255, is supported)")
    i += 1  # single whitespace after maxval
    if magic == b"P6":
        arr = np.frombuffer(data, np.uint8, count=w * h * 3, offset=i)
        return arr.reshape(h, w, 3)
    if magic == b"P5":
        arr = np.frombuffer(data, np.uint8, count=w * h, offset=i)
        return arr.reshape(h, w, 1)
    raise ValueError(f"unsupported PNM magic {magic!r} in {path}")


def _resize_nearest(img: np.ndarray, h: int, w: int) -> np.ndarray:
    ih, iw = img.shape[:2]
    ri = (np.arange(h) * ih // h).clip(0, ih - 1)
    ci = (np.arange(w) * iw // w).clip(0, iw - 1)
    return img[ri][:, ci]


class ImageLoader:
    """Loads image files as [H, W, C] float32 arrays in [0, 1].

    height/width: optional resize target; channels: 1 (grayscale) or 3.
    """

    def __init__(self, height: Optional[int] = None,
                 width: Optional[int] = None, channels: int = 3):
        if channels not in (1, 3):
            raise ValueError("channels must be 1 or 3")
        if (height is None) != (width is None):
            raise ValueError("height and width must be set together "
                             "(or both omitted for no resize)")
        self.height = height
        self.width = width
        self.channels = channels

    # ------------------------------------------------------------- loading
    def as_array(self, path: str) -> np.ndarray:
        ext = os.path.splitext(path)[1].lower()
        if _HAVE_PIL and ext not in (".ppm", ".pgm"):
            with Image.open(path) as im:
                im = im.convert("L" if self.channels == 1 else "RGB")
                if self.height and self.width:
                    im = im.resize((self.width, self.height),
                                   Image.BILINEAR)
                arr = np.asarray(im, np.uint8)
        else:
            arr = _read_pnm(path)
            if self.channels == 1 and arr.shape[-1] == 3:
                arr = (arr @ np.array([0.299, 0.587, 0.114]))[..., None]
            elif self.channels == 3 and arr.shape[-1] == 1:
                arr = np.repeat(arr, 3, axis=-1)
            if self.height and self.width:
                arr = _resize_nearest(arr, self.height, self.width)
        if arr.ndim == 2:
            arr = arr[..., None]
        if self.channels == 3 and arr.shape[-1] == 1:
            arr = np.repeat(arr, 3, axis=-1)
        return np.asarray(arr, np.float32) / 255.0

    def as_row_vector(self, path: str) -> np.ndarray:
        """Flattened [H*W*C] vector (reference asRowVector)."""
        return self.as_array(path).reshape(-1)

    def as_matrix(self, paths) -> np.ndarray:
        """Stack many files into one [N, H, W, C] batch (reference asMatrix)."""
        return np.stack([self.as_array(p) for p in paths])

    # -------------------------------------------------------------- saving
    @staticmethod
    def save(arr: np.ndarray, path: str) -> None:
        """Write a [H, W, C] float array in [0,1] back to an image file."""
        a = np.clip(np.asarray(arr), 0.0, 1.0)
        u8 = (a * 255.0 + 0.5).astype(np.uint8)
        ext = os.path.splitext(path)[1].lower()
        if _HAVE_PIL and ext not in (".ppm", ".pgm"):
            mode = "L" if u8.shape[-1] == 1 else "RGB"
            Image.fromarray(u8[..., 0] if mode == "L" else u8, mode).save(path)
            return
        h, w, c = u8.shape
        with open(path, "wb") as f:
            if c == 1:
                f.write(b"P5\n%d %d\n255\n" % (w, h))
                f.write(u8[..., 0].tobytes())
            else:
                f.write(b"P6\n%d %d\n255\n" % (w, h))
                f.write(u8.tobytes())


def crop_to_square(arr: np.ndarray) -> np.ndarray:
    """Center-crop to square (reference LFW pipeline crops faces)."""
    h, w = arr.shape[:2]
    s = min(h, w)
    top, left = (h - s) // 2, (w - s) // 2
    return arr[top:top + s, left:left + s]
