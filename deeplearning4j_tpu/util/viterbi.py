"""Viterbi label-sequence smoothing (reference: util/Viterbi.java — decodes
the most likely true label chain from noisy per-frame classifier outputs
under a sticky transition model: metaStability 0.9 self-transition,
pCorrect 0.99 emission).

The reference's DP never fills its backpointer matrix (Viterbi.java:77-110
writes `pointers` nowhere), so its backtrace returns zeros; this
implementation keeps the same model and API shape but does the standard
correct backtrace. Vectorised over states per frame — sequence decode is
tiny host work.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class Viterbi:
    """decode(labels) → (log-likelihood, most-likely state sequence)."""

    def __init__(self, possible_labels, meta_stability: float = 0.9,
                 p_correct: float = 0.99):
        self.possible_labels = np.asarray(possible_labels)
        self.states = int(len(self.possible_labels))
        if self.states < 2:
            raise ValueError("need >= 2 states")
        self.meta_stability = meta_stability
        self.p_correct = p_correct

    def _log_trans(self) -> np.ndarray:
        off = (1.0 - self.meta_stability) / (self.states - 1)
        t = np.full((self.states, self.states), np.log(off))
        np.fill_diagonal(t, np.log(self.meta_stability))
        return t

    def _log_emit(self, obs: np.ndarray) -> np.ndarray:
        """[frames, states] log P(observed label | true state)."""
        off = (1.0 - self.p_correct) / (self.states - 1)
        e = np.full((len(obs), self.states), np.log(off))
        e[np.arange(len(obs)), obs] = np.log(self.p_correct)
        return e

    def decode(self, labels, binary_label_matrix: bool = None) -> Tuple[float, np.ndarray]:
        """labels: int sequence of observed outcomes, or a one-hot
        [frames, states] matrix (reference decode(labels, true))."""
        labels = np.asarray(labels)
        if binary_label_matrix is None:
            binary_label_matrix = labels.ndim == 2
        obs = (np.argmax(labels, axis=1) if binary_label_matrix
               else labels.astype(int).ravel())
        frames = len(obs)
        if frames == 0:
            return 0.0, np.array([], dtype=int)
        log_t = self._log_trans()
        log_e = self._log_emit(obs)

        v = np.full((frames, self.states), -np.inf)
        ptr = np.zeros((frames, self.states), dtype=int)
        v[0] = -np.log(self.states) + log_e[0]
        for t in range(1, frames):
            scores = v[t - 1][:, None] + log_t          # [from, to]
            ptr[t] = np.argmax(scores, axis=0)
            v[t] = scores[ptr[t], np.arange(self.states)] + log_e[t]

        path = np.zeros(frames, dtype=int)
        path[-1] = int(np.argmax(v[-1]))
        for t in range(frames - 2, -1, -1):
            path[t] = ptr[t + 1][path[t + 1]]
        return float(v[-1].max()), path
