"""Numerically-tolerant math helpers (reference berkeley/SloppyMath.java).

The reference vendors Berkeley NLP's scalar helpers (logAdd with a
truncation tolerance, logNormalize, nChooseK, ...). Here they are thin
vectorized numpy forms — anything heavier already lives in jax/numpy, so
only the semantics the reference actually exposes are kept.
"""

from __future__ import annotations

import math
from typing import Sequence, Union

import numpy as np

ArrayLike = Union[float, Sequence[float], np.ndarray]

# logAdd treats summands more than this many nats below the max as zero
# (reference SloppyMath.LOGTOLERANCE = 30.0)
LOG_TOLERANCE = 30.0


def is_dangerous(d: float) -> bool:
    """NaN, infinite, or exactly zero (reference isDangerous)."""
    return math.isnan(d) or math.isinf(d) or d == 0.0


def is_very_dangerous(d: float) -> bool:
    return math.isnan(d) or math.isinf(d)


def relative_difference(a: float, b: float) -> float:
    absolute = abs(a - b)
    scale = max(abs(a), abs(b))
    return absolute / scale if scale > 0 else absolute


def is_discrete_prob(d: float, tol: float = 1e-6) -> bool:
    return abs(1.0 - d) < tol


def log_add(lx: ArrayLike, ly: float = None) -> float:
    """log(exp(lx) + exp(ly)) — or over a vector when ly is omitted —
    truncating summands > LOG_TOLERANCE nats below the max, exactly the
    reference's speed/robustness trade (SloppyMath.logAdd:246-358)."""
    if ly is not None:
        v = np.array([lx, ly], dtype=np.float64)
    else:
        v = np.asarray(lx, dtype=np.float64)
    if v.size == 0:
        return float("-inf")
    m = float(np.max(v))
    if math.isinf(m):
        return m
    keep = v >= m - LOG_TOLERANCE
    return m + math.log(float(np.sum(np.exp(v[keep] - m))))


def log_subtract(lx: float, ly: float) -> float:
    """log(exp(lx) - exp(ly)); requires lx >= ly."""
    if ly > lx:
        raise ValueError("log_subtract requires lx >= ly")
    if lx == ly:
        return float("-inf")
    return lx + math.log1p(-math.exp(ly - lx))


def log_normalize(log_v: ArrayLike) -> np.ndarray:
    """Shift log-weights so they sum (in probability space) to 1
    (reference logNormalize mutates in place; here a new array returns)."""
    v = np.asarray(log_v, dtype=np.float64)
    return v - log_add(v)


def add_exp(log_v: ArrayLike) -> float:
    """sum(exp(v)) computed via the shifted form (reference addExp)."""
    return math.exp(log_add(log_v))


def n_choose_k(n: int, k: int) -> int:
    return math.comb(n, k)


def int_pow(b: Union[int, float], e: int) -> Union[int, float]:
    """b**e by squaring for non-negative integer e (reference intPow)."""
    if e < 0:
        raise ValueError("int_pow requires e >= 0")
    result = 1
    base = b
    while e:
        if e & 1:
            result = result * base
        base = base * base
        e >>= 1
    return result


def approx_log(x: float) -> float:
    """The reference ships bit-twiddling approx exp/log for JVM speed;
    numpy's exact forms are faster here, so approx == exact."""
    return math.log(x)


def approx_exp(x: float) -> float:
    return math.exp(x)


def sloppy_max(*xs: float) -> float:
    return max(xs)


def sloppy_min(*xs: float) -> float:
    return min(xs)
