"""Sharded / async checkpointing via Orbax — the large-model path.

`util/model_serializer.py` keeps the reference's zip format
(ModelSerializer.java:64-78: config JSON + params + updater) and
materializes everything on host — right for single-host models, wrong
for sharded ones. This module checkpoints the param/updater/state
pytrees through Orbax: each array saved with its sharding (no host
gather), restored onto the CURRENT mesh layout, optionally async so the
training loop overlaps the write. A TPU-first capability with no
reference analogue.

Layout: <dir>/step_<N>/{model/ (orbax pytree), config.json, meta.json}.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax

from deeplearning4j_tpu.nn.conf import serde


def _tree(net):
    return {"params": net.params, "opt_state": net.opt_state,
            "state": net.state}


def host_materialize(tree):
    """The tree with every leaf as a host numpy array — the
    process-count-portable checkpoint form for the elastic fleet
    (distributed/elastic.py): a checkpoint written as host values under
    an N-process mesh restores onto N' processes (or one). Restore-side
    mesh changes now route through the portable resharding engine
    (`reshard/`, via ``restore(net, target_mesh=...)``) instead of
    relying on host values alone.

    A process can only read its addressable shards, so this supports the
    leaves a data-parallel fleet actually holds: fully-addressable
    arrays, and process-spanning REPLICATED arrays (each process's first
    addressable shard is the whole value). Cross-process *sharded* state
    (ZeRO-1 moments over a spanning mesh) must reshard through the
    checkpoint path (`reshard/executor.checkpoint_template`) and raises
    here.

    Telemetry: materializing a leaf that is genuinely SHARDED across
    devices (not replicated) is a full-value host gather — one
    `host_gather` event records the count and bytes, so the elastic
    timeline test can assert the resharded paths never did it.
    """
    import numpy as np

    gathered = {"n": 0, "bytes": 0}

    def leaf(x):
        if not isinstance(x, jax.Array):
            return np.asarray(x) if hasattr(x, "shape") else x
        if x.is_fully_addressable:
            if len(x.sharding.device_set) > 1 and not x.is_fully_replicated:
                gathered["n"] += 1
                gathered["bytes"] += int(getattr(x, "nbytes", 0) or 0)
            return np.asarray(x)
        if x.is_fully_replicated:
            return np.asarray(x.addressable_data(0))
        raise NotImplementedError(
            f"cannot host-materialize a cross-process sharded leaf "
            f"{x.shape} ({x.sharding}) — restore through the portable "
            "resharding engine instead (ShardedCheckpointer.restore("
            "net, target_mesh=...), reshard/)")

    out = jax.tree.map(leaf, tree)
    if gathered["n"]:
        from deeplearning4j_tpu.telemetry import get_default as _telemetry

        _telemetry().event("host_gather", n_leaves=gathered["n"],
                           bytes=gathered["bytes"])
    return out


class ShardedCheckpointer:
    """Save/restore sharded networks without host gathering.

    save(net, step): writes a new step directory (and prunes to
    `keep` most recent). restore(net, step=None): loads the latest (or
    given) step INTO net, placing each array with net's current
    shardings — restoring onto a different mesh layout than the save is
    supported (orbax reshards on read).
    """

    def __init__(self, directory: str, keep: int = 3,
                 use_async: bool = False):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self.keep = keep
        self.use_async = use_async
        # StandardCheckpointer commits asynchronously in recent orbax:
        # save() returns before files exist; sync mode waits per save
        self._ckptr = ocp.StandardCheckpointer()
        self._solo_ckptr = None
        os.makedirs(self.directory, exist_ok=True)

    def _solo(self):
        """A checkpointer whose barriers involve ONLY this process.

        Host-mode checkpoints in a multi-process fleet must not sync the
        world: the default checkpointer broadcasts across every process
        on save/restore, which deadlocks the elastic rescue path (the
        peer whose death triggered the checkpoint can never join the
        barrier) and couples N' restore processes that each hold the
        full host values anyway."""
        import orbax.checkpoint as ocp

        if self._solo_ckptr is None:
            me = jax.process_index()
            self._solo_ckptr = ocp.StandardCheckpointer(
                multiprocessing_options=ocp.options.MultiprocessingOptions(
                    primary_host=me, active_processes={me},
                    # N concurrent solo restores would otherwise hit the
                    # coordination service with the SAME barrier key and
                    # conflicting process sets (INVALID_ARGUMENT)
                    barrier_sync_key_prefix=f"solo_p{me}"))
        return self._solo_ckptr

    # ------------------------------------------------------------- listing
    def steps(self):
        out = []
        for d in os.listdir(self.directory):
            # only fully committed steps count (meta.json is written last)
            if d.startswith("step_") and os.path.exists(
                    os.path.join(self.directory, d, "meta.json")):
                try:
                    out.append(int(d.split("_", 1)[1]))
                except ValueError:
                    pass
        return sorted(out)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    # ---------------------------------------------------------------- save
    def save(self, net, step: Optional[int] = None, *,
             host: bool = False) -> str:
        """host=True writes HOST-materialized values (see
        `host_materialize`) — the elastic-fleet form. Every process of a
        multi-process fleet calls this in lockstep (materialization syncs
        all ranks identically), but only process 0 touches the directory:
        N writers racing one step dir would corrupt it, and for
        replicated state one copy IS the checkpoint."""
        step = net.iteration_count if step is None else step
        d = self._step_dir(step)
        tree = _tree(net)
        ckptr = self._ckptr
        if host:
            tree = host_materialize(tree)
            if jax.process_count() > 1:
                if jax.process_index() != 0:
                    return d
                ckptr = self._solo()
        if getattr(self, "_pending", None) is not None:
            # an earlier async save is still uncommitted: finalize it first
            # or its meta.json would never be written (invisible + unpruned)
            self.wait()
        # meta/config go to a staging name and rename AFTER the orbax
        # commit: restore() only selects steps whose meta.json exists, so
        # a crash mid-save can never surface a partial step as "latest"
        from deeplearning4j_tpu.nn.updater import FLAT_LAYOUT_VERSION
        from deeplearning4j_tpu.reshard.executor import net_placement

        self._pending = (d, {
            "iteration": net.iteration_count,
            "epoch": getattr(net, "epoch_count", 0),
            "kind": type(net).__name__,
            # layout of flat-view optimizer vectors (see
            # nn/updater.upgrade_flat_layout)
            "flat_layout": FLAT_LAYOUT_VERSION,
            # the SOURCE placement this checkpoint was written under —
            # what restore(target_mesh=...) plans the redistribution
            # from (reshard/planner.Placement)
            "placement": net_placement(net).to_json(),
        }, serde.to_json(net.conf))
        ckptr.save(os.path.join(d, "model"), tree, force=True)
        if not self.use_async:
            self.wait()
        return d

    def _commit_pending(self):
        if getattr(self, "_pending", None) is None:
            return
        d, meta, conf_json = self._pending
        self._pending = None
        with open(os.path.join(d, "config.json"), "w") as f:
            f.write(conf_json)
        tmp = os.path.join(d, ".meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(d, "meta.json"))
        for old in self.steps()[:-self.keep or None]:
            import shutil

            shutil.rmtree(self._step_dir(old), ignore_errors=True)

    def wait(self):
        """Block until pending saves have committed; finalizes the step's
        meta/config and prunes retention afterwards."""
        for ck in (self._ckptr, self._solo_ckptr):
            if ck is not None and hasattr(ck, "wait_until_finished"):
                ck.wait_until_finished()
        self._commit_pending()

    # ------------------------------------------------------------- restore
    def restore(self, net, step: Optional[int] = None, *,
                target_mesh=None, target_axes=None):
        """Load a step into `net` (which must be built with a matching
        config and init()'d so the target structure/shardings exist).

        target_mesh/target_axes: restore THROUGH the portable resharding
        engine (`reshard/`) onto a mesh different from (or identically
        shaped to) the one that wrote the checkpoint. The plan maps the
        checkpoint's recorded source placement (meta.json "placement")
        to the target placement; orbax then reads only the shard slices
        each target process's addressable devices need — a spanning-mesh
        restore never materializes full params on host. Emits a
        `reshard_plan` telemetry event and wraps the read in a `reshard`
        span (bytes moved vs the plan's lower bound)."""
        import orbax.checkpoint as ocp

        self.wait()
        steps = self.steps()
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        if step is None:
            step = steps[-1]
        elif step not in steps:
            raise FileNotFoundError(
                f"no checkpoint for step {step} under {self.directory} "
                f"(have {steps})")
        d = self._step_dir(step)
        if net.params is None:
            net.init()
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)

        def _abstract(tree):
            return jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                               sharding=getattr(
                                                   x, "sharding", None)),
                tree)

        # in a fleet every process reads the checkpoint independently
        # (host-value checkpoints are replicated by construction) — the
        # default checkpointer would barrier-sync the restore instead
        ckptr = self._solo() if jax.process_count() > 1 \
            else ocp.StandardCheckpointer()
        if target_mesh is not None:
            restored = self._restore_resharded(
                net, d, meta, ckptr, target_mesh, target_axes, _abstract)
        else:
            try:
                restored = ckptr.restore(os.path.join(d, "model"),
                                         _abstract(_tree(net)))
            except ValueError:
                # optimizer-layout bridge (updater.rebuild_other_layout):
                # the checkpoint may hold the OTHER updater-state layout
                # (per-leaf tree vs the flat-view fused state). Retry
                # against the opposite layout's template WITHOUT touching
                # the net — only on success does set_optimizer swap the
                # transform in (which also invalidates any cached jitted
                # train step built over the old one); a genuinely corrupt
                # checkpoint re-raises with the net unchanged.
                from deeplearning4j_tpu.nn.updater import \
                    rebuild_other_layout

                alt_tx = rebuild_other_layout(net)
                tmpl = dict(_tree(net), opt_state=alt_tx.init(net.params))
                restored = ckptr.restore(os.path.join(d, "model"),
                                         _abstract(tmpl))
                net.set_optimizer(alt_tx)
        net.params = restored["params"]
        net.opt_state = restored["opt_state"]
        net.state = restored["state"]
        if meta.get("flat_layout", 1) < 2:
            # pre-r5 flat vectors were all-row-major; reorder to the v2
            # (lane-rotated) layout so resumed moments stay aligned
            from deeplearning4j_tpu.nn.updater import (
                FlatViewTransform,
                flat_state_size,
                upgrade_flat_layout,
            )

            if isinstance(net.tx, FlatViewTransform):
                total = flat_state_size(net.params)
                net.opt_state = jax.tree.map(
                    lambda l: (upgrade_flat_layout(l, net.params)
                               if getattr(l, "ndim", None) == 1
                               and l.size == total else l),
                    net.opt_state)
        net.iteration_count = meta.get("iteration", 0)
        if hasattr(net, "epoch_count"):
            net.epoch_count = meta.get("epoch", 0)
        return net

    def _restore_resharded(self, net, d, meta, ckptr, target_mesh,
                           target_axes, _abstract):
        """The reshard/ checkpoint executor: plan source->target, put
        the plan on the telemetry record, hand orbax an abstract tree
        carrying TARGET shardings (it reads only the byte ranges each
        target shard needs), bridging optimizer layouts like the legacy
        path."""
        from deeplearning4j_tpu.reshard.executor import checkpoint_template
        from deeplearning4j_tpu.reshard.planner import Placement
        from deeplearning4j_tpu.telemetry import get_default as _telemetry

        src = (Placement.from_json(meta["placement"])
               if meta.get("placement") else Placement.solo())
        plan, tmpl = checkpoint_template(
            net, src, target_mesh, target_axes,
            zero1=bool(getattr(net, "_zero1", False)))
        rec = _telemetry()
        rec.event("reshard_plan", path="checkpoint",
                  step=meta.get("iteration"), **plan.summary())
        with rec.span("reshard", path="checkpoint",
                      bytes_moved=plan.bytes_moved,
                      bytes_lower_bound=plan.bytes_lower_bound):
            try:
                return ckptr.restore(os.path.join(d, "model"), tmpl)
            except ValueError:
                # optimizer-layout bridge, reshard flavor: the moments in
                # the checkpoint use the other updater layout. zero1/TP
                # placements always use the tree layout on both sides, so
                # a bridged restore is a plain-DP/serving case — the alt
                # moments restore replicated on the target mesh.
                from deeplearning4j_tpu.nn.updater import \
                    rebuild_other_layout
                from jax.sharding import NamedSharding, PartitionSpec as P

                alt_tx = rebuild_other_layout(net)
                repl = NamedSharding(target_mesh, P())
                alt_opt = jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                   sharding=repl),
                    alt_tx.init(net.params))
                restored = ckptr.restore(os.path.join(d, "model"),
                                         dict(tmpl, opt_state=alt_opt))
                net.set_optimizer(alt_tx)
                return restored
