"""Profiling helpers (SURVEY.md §5: the reference has no tracing at all;
the TPU build gets jax.profiler traces + the per-step PerformanceListener).

`trace(logdir)` wraps a training region in a jax.profiler trace whose
output loads in TensorBoard/XProf (op-level TPU timelines, HBM usage);
`ProfilerIterationListener` starts the trace at a given iteration and
stops it N iterations later, so users profile a steady-state window of
`fit()` without modifying their loop.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from deeplearning4j_tpu.optimize.listeners import IterationListener


@contextlib.contextmanager
def trace(logdir: str):
    """Context manager: jax.profiler trace over the enclosed region."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


class ProfilerIterationListener(IterationListener):
    """Trace a steady-state window of fit(): [start_iteration,
    start_iteration + n_iterations)."""

    def __init__(self, logdir: str, start_iteration: int = 10,
                 n_iterations: int = 5):
        self.logdir = logdir
        self.start_iteration = start_iteration
        self.n_iterations = n_iterations
        self._active = False
        self.done = False

    def iteration_done(self, model, iteration):
        import jax

        if (not self._active and not self.done
                and iteration >= self.start_iteration):
            jax.profiler.start_trace(self.logdir)
            self._active = True
            self._stop_at = iteration + self.n_iterations
        elif self._active and iteration >= self._stop_at:
            self.close()

    def close(self):
        """Flush an open trace. Call after fit() if training might end
        inside the window — an unstopped trace is lost AND leaves the
        process-global profiler started (later traces would fail)."""
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self.done = True

    def __del__(self):  # best-effort flush
        try:
            self.close()
        except Exception:
            pass
