"""Profiling helpers (SURVEY.md §5: the reference has no tracing at all;
the TPU build gets jax.profiler traces + the per-step PerformanceListener
+ the run-telemetry recorder in deeplearning4j_tpu/telemetry/).

`trace(logdir)` wraps a training region in a jax.profiler trace whose
output loads in TensorBoard/XProf (op-level TPU timelines, HBM usage);
`ProfilerIterationListener` starts the trace at a given iteration and
stops it N iterations later, so users profile a steady-state window of
`fit()` without modifying their loop. Both leave a `span` event named
`profiler_trace` in the run-telemetry log (a NullRecorder no-op unless
telemetry is enabled), so the coarse wall-clock of each profiled window
survives even when nobody opens the XProf dump.
"""

from __future__ import annotations

import contextlib
import time

from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.telemetry.recorder import get_default


@contextlib.contextmanager
def trace(logdir: str):
    """Context manager: jax.profiler trace over the enclosed region."""
    import jax

    jax.profiler.start_trace(logdir)
    t0 = time.perf_counter()
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()
        get_default().event(
            "span", name="profiler_trace", logdir=logdir,
            seconds=round(time.perf_counter() - t0, 6))


class ProfilerIterationListener(IterationListener):
    """Trace a steady-state window of fit(): [start_iteration,
    start_iteration + n_iterations)."""

    def __init__(self, logdir: str, start_iteration: int = 10,
                 n_iterations: int = 5, recorder=None):
        self.logdir = logdir
        self.start_iteration = start_iteration
        self.n_iterations = n_iterations
        self.recorder = recorder
        self._active = False
        self.done = False

    def iteration_done(self, model, iteration):
        import jax

        if (not self._active and not self.done
                and iteration >= self.start_iteration):
            jax.profiler.start_trace(self.logdir)
            self._active = True
            self._t0 = time.perf_counter()
            self._start_iter = iteration
            self._stop_at = iteration + self.n_iterations
        elif self._active and iteration >= self._stop_at:
            self.close()

    def close(self):
        """Flush an open trace. Call after fit() if training might end
        inside the window — an unstopped trace is lost AND leaves the
        process-global profiler started (later traces would fail)."""
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self.done = True
            rec = self.recorder if self.recorder is not None \
                else get_default()
            rec.event("span", name="profiler_trace", logdir=self.logdir,
                      start_iteration=self._start_iter,
                      seconds=round(time.perf_counter() - self._t0, 6))

    def __del__(self):  # best-effort flush
        try:
            self.close()
        except Exception:
            pass
