"""jax version compatibility shims.

The repo runs on two jax generations: the TPU driver container (jax >=
0.5, where `jax.shard_map` and `pltpu.CompilerParams` are public) and the
CPU test container (jax 0.4.x, where they live at
`jax.experimental.shard_map.shard_map` / `pltpu.TPUCompilerParams`). The
r5 `transformer_large` bench crash was this exact failure class — a
binary that ran in the author's session died under driver capture with an
AttributeError before emitting its metric — so every version-moved symbol
is resolved HERE, once, instead of at each call site.
"""

from __future__ import annotations

try:  # jax >= 0.5
    from jax import shard_map as _shard_map

    _SHARD_MAP_VMA_KW = True
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_VMA_KW = False


def shard_map(f, **kwargs):
    """jax.shard_map across versions: 0.4.x spells the replication-check
    opt-out `check_rep` (>= 0.5: `check_vma`) and the partial-manual
    selector `auto` = non-manual axes (>= 0.5: `axis_names` = manual
    axes). Callers use the new spellings; this translates down."""
    if not _SHARD_MAP_VMA_KW:
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if "axis_names" in kwargs:
            manual = set(kwargs.pop("axis_names"))
            mesh_axes = set(kwargs["mesh"].axis_names)
            if manual != mesh_axes:
                kwargs["auto"] = frozenset(mesh_axes - manual)
    return _shard_map(f, **kwargs)

import jax as _jax
from jax.experimental.pallas import tpu as _pltpu

# renamed TPUCompilerParams -> CompilerParams in jax 0.5
_COMPILER_PARAMS_CLS = getattr(_pltpu, "CompilerParams", None) or getattr(
    _pltpu, "TPUCompilerParams")


def tpu_compiler_params(**kwargs):
    """pltpu CompilerParams across the rename (vmem_limit_bytes etc.)."""
    return _COMPILER_PARAMS_CLS(**kwargs)


def pcast_varying(x, axis_names):
    """lax.pcast(x, axis_names, to="varying") where it exists (the vma
    varying-axis type system of newer jax); identity on 0.4.x, whose
    shard_map (check_rep=False) has no varying-axis types to cast
    between — the cast is purely a type-system annotation there."""
    pcast = getattr(_jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axis_names, to="varying")
