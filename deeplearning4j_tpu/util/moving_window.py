"""Sliding 2-D windows over a matrix (reference: util/MovingWindowMatrix.java
— windows(boolean flattened), optional 90° rotations via addRotate).

Vectorised with stride tricks — no per-window copies until the caller asks
for the list."""

from __future__ import annotations

from typing import List

import numpy as np


class MovingWindowMatrix:
    """All windowRowSize×windowColumnSize sub-matrices, stride 1
    (MovingWindowMatrix.java:55)."""

    def __init__(self, to_slice: np.ndarray, window_row_size: int,
                 window_column_size: int, add_rotate: bool = False):
        self.arr = np.asarray(to_slice)
        if self.arr.ndim != 2:
            raise ValueError("expected a 2-D matrix")
        if (window_row_size > self.arr.shape[0]
                or window_column_size > self.arr.shape[1]):
            raise ValueError("window larger than matrix")
        self.rows = window_row_size
        self.cols = window_column_size
        self.add_rotate = add_rotate

    def windows(self, flattened: bool = False) -> List[np.ndarray]:
        view = np.lib.stride_tricks.sliding_window_view(
            self.arr, (self.rows, self.cols))
        out: List[np.ndarray] = []
        for i in range(view.shape[0]):
            for j in range(view.shape[1]):
                w = view[i, j]
                variants = [w]
                if self.add_rotate:
                    # three extra 90° rotations (reference addRotate)
                    variants += [np.rot90(w, k) for k in (1, 2, 3)]
                for v in variants:
                    out.append(v.ravel().copy() if flattened else v.copy())
        return out
