"""Virtual-device platform forcing for cluster simulation.

The reference simulates clusters with Spark ``local[*]`` executors inside one
JVM (SURVEY.md §4.5); the JAX analogue is the XLA host platform with N
virtual CPU devices. One shared helper so tests, the driver entry point, and
multi-process launchers all do the same (fragile, jax-internals-touching)
dance: set JAX_PLATFORMS=cpu + ``--xla_force_host_platform_device_count=N``
and de-register the environment's `axon` TPU backend factory before any
backend initialization (its get_backend hook otherwise initializes the TPU
tunnel on first lookup).
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def cpu_device_flags(n: int, existing: str = "") -> str:
    """An XLA_FLAGS value forcing >= ``n`` virtual host devices — a pure
    string operation (no jax import, no backend touch), so the
    multi-process bootstrap can set it BEFORE jax.distributed.initialize
    without tripping the backends-already-initialized check."""
    flags = existing
    m = re.search(rf"--{_FLAG}=(\d+)", flags)
    if m is None:
        flags = (flags + f" --{_FLAG}={n}").strip()
    elif int(m.group(1)) < n:
        flags = flags.replace(m.group(0), f"--{_FLAG}={n}")
    return flags


def ensure_cpu_devices(n: int) -> None:
    """Force a pure-CPU JAX platform with at least ``n`` virtual devices.

    Must run before jax initializes its backends; if they are already
    initialized with >= n devices (of any platform) this is a no-op, and if
    they are initialized with fewer an AssertionError explains the ordering
    problem.

    In a fresh process there is no way to count real accelerators without
    initializing the backend (which cannot be undone), so the default is to
    force the virtual CPU platform. On a host that really has >= n chips,
    set ``DL4J_TPU_REAL_DEVICES=1`` to skip the forcing and run on hardware.
    """
    import jax

    try:
        from jax._src import xla_bridge as _xb

        initialized = _xb.backends_are_initialized()
    except Exception:  # pragma: no cover - jax internals moved
        _xb = None
        initialized = True

    if os.environ.get("DL4J_TPU_REAL_DEVICES") == "1":
        initialized = True  # trust whatever platform jax picks
    if initialized and len(jax.devices()) >= n:
        return

    os.environ["XLA_FLAGS"] = cpu_device_flags(
        n, os.environ.get("XLA_FLAGS", ""))
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    if _xb is not None and not _xb.backends_are_initialized():
        _xb._backend_factories.pop("axon", None)

    assert len(jax.devices()) >= n, (
        f"need {n} devices, have {len(jax.devices())} "
        f"(jax backends were initialized before ensure_cpu_devices({n}) "
        f"could force the virtual CPU platform — call it earlier)")
