"""String grid / cluster dedupe utilities (reference util/StringGrid.java,
util/StringCluster.java).

A StringGrid is a list of string rows (split from CSV-ish lines) with
column-wise cleanup operations; StringCluster groups strings by an
order/case/punctuation-insensitive fingerprint so near-duplicate values
("Two words", "TWO words", "words two") land in one cluster. Host-side
tooling — no device arrays involved.
"""

from __future__ import annotations

import difflib
import re
from collections import defaultdict
from typing import Dict, Iterable, List, Optional

_PUNCT = re.compile(r"[^\w\s]", re.UNICODE)


def fingerprint(s: str) -> str:
    """Case/punctuation/order-insensitive key (reference FingerPrintKeyer:
    trim, lowercase, strip punctuation, unique-sort the tokens, rejoin)."""
    toks = _PUNCT.sub("", s.strip().lower()).split()
    return " ".join(sorted(set(toks)))


class StringCluster:
    """fingerprint -> {original string -> count} (reference
    StringCluster.java:36-61); clusters sort largest-first."""

    def __init__(self, strings: Iterable[str]):
        self.clusters: Dict[str, Dict[str, int]] = defaultdict(dict)
        for s in strings:
            m = self.clusters[fingerprint(s)]
            m[s] = m.get(s, 0) + 1

    def get_clusters(self) -> List[Dict[str, int]]:
        return sorted(
            self.clusters.values(),
            key=lambda m: (-len(m), -sum(m.values())))

    def __getitem__(self, key: str) -> Dict[str, int]:
        return self.clusters[key]

    def __len__(self) -> int:
        return len(self.clusters)


class StringGrid:
    """Rows of string columns with cleanup/dedupe ops (reference
    StringGrid.java). Construct via from_lines/from_file or with explicit
    rows; `sep` is a literal separator, not a regex."""

    NONE = "NONE"

    def __init__(self, sep: str, num_columns: Optional[int] = None,
                 rows: Optional[List[List[str]]] = None):
        self.sep = sep
        self.rows: List[List[str]] = [list(r) for r in (rows or [])]
        if num_columns is None:
            num_columns = len(self.rows[0]) if self.rows else 0
        self.num_columns = num_columns
        for i, row in enumerate(self.rows):
            if len(row) != self.num_columns:
                raise ValueError(
                    f"row {i} has {len(row)} columns, expected "
                    f"{self.num_columns}")

    @classmethod
    def from_lines(cls, lines: Iterable[str], sep: str) -> "StringGrid":
        rows = [line.rstrip("\n").split(sep) for line in lines
                if line.strip()]
        return cls(sep, rows=rows)

    @classmethod
    def from_file(cls, path: str, sep: str) -> "StringGrid":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_lines(f, sep)

    # ----------------------------------------------------------- accessors
    def get_column(self, column: int) -> List[str]:
        return [row[column] for row in self.rows]

    def get_row(self, i: int) -> List[str]:
        return self.rows[i]

    def to_lines(self) -> List[str]:
        return [self.sep.join(row) for row in self.rows]

    def write_lines_to(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write("\n".join(self.to_lines()) + "\n")

    def __len__(self) -> int:
        return len(self.rows)

    # ------------------------------------------------------------- cleanup
    def head(self, num: int) -> "StringGrid":
        return StringGrid(self.sep, self.num_columns, self.rows[:num])

    def remove_rows_with_empty_column(self, column: int,
                                      missing_value: str = "") -> None:
        self.rows = [r for r in self.rows if r[column] != missing_value]

    def remove_columns(self, *columns: int) -> None:
        drop = set(columns)
        bad = [c for c in drop if not 0 <= c < self.num_columns]
        if bad:
            raise IndexError(f"column(s) {bad} out of range "
                             f"(grid has {self.num_columns})")
        self.rows = [[c for j, c in enumerate(r) if j not in drop]
                     for r in self.rows]
        self.num_columns -= len(drop)

    def filter_rows_by_column(self, column: int,
                              values: Iterable[str]) -> None:
        keep = set(values)
        self.rows = [r for r in self.rows if r[column] in keep]

    def select(self, column: int, value: str) -> "StringGrid":
        return StringGrid(self.sep, self.num_columns,
                          [r for r in self.rows if r[column] == value])

    def sort_by(self, column: int) -> None:
        self.rows.sort(key=lambda r: r[column])

    def swap(self, column1: int, column2: int) -> None:
        for r in self.rows:
            r[column1], r[column2] = r[column2], r[column1]

    def merge(self, column1: int, column2: int,
              join_with: str = " ") -> None:
        """Join two columns, dropping the second. Joins with a space by
        default — joining with the grid separator (as the reference does)
        would make write/read round-trips silently re-split the column."""
        bad = [c for c in (column1, column2)
               if not 0 <= c < self.num_columns]
        if bad:  # validate before mutating any row
            raise IndexError(f"column(s) {bad} out of range "
                             f"(grid has {self.num_columns})")
        for r in self.rows:
            r[column1] = r[column1] + join_with + r[column2]
        self.remove_columns(column2)

    def split(self, column: int, sep_by: str) -> None:
        """Split a column in place, widening the grid."""
        new_rows = []
        width = None
        for r in self.rows:
            parts = r[column].split(sep_by)
            row = r[:column] + parts + r[column + 1:]
            if width is None:
                width = len(row)
            elif len(row) != width:
                raise ValueError("split produced ragged rows")
            new_rows.append(row)
        self.rows = new_rows
        self.num_columns = width or self.num_columns

    def fill_down(self, value: str, column: int) -> None:
        for r in self.rows:
            r[column] = value

    def prepend_to_each(self, prepend: str, column: int) -> None:
        for r in self.rows:
            r[column] = prepend + r[column]

    def append_to_each(self, append: str, column: int) -> None:
        for r in self.rows:
            r[column] = r[column] + append

    def add_row(self, row: List[str]) -> None:
        if len(row) != self.num_columns:
            raise ValueError("row width mismatch")
        self.rows.append(list(row))

    def add_column(self, column: List[str]) -> None:
        if len(column) != len(self.rows):
            raise ValueError("column length mismatch")
        for r, v in zip(self.rows, column):
            r.append(v)
        self.num_columns += 1

    def map_by_primary_key(self, column: int) -> Dict[str, List[List[str]]]:
        out: Dict[str, List[List[str]]] = defaultdict(list)
        for r in self.rows:
            out[r[column]].append(r)
        return dict(out)

    # -------------------------------------------------------------- dedupe
    def cluster_column(self, column: int) -> StringCluster:
        return StringCluster(self.get_column(column))

    def get_rows_with_duplicate_values_in_column(
            self, column: int) -> "StringGrid":
        counts: Dict[str, int] = defaultdict(int)
        for r in self.rows:
            counts[r[column]] += 1
        return StringGrid(self.sep, self.num_columns,
                          [r for r in self.rows if counts[r[column]] > 1])

    def dedupe_by_cluster(self, column: int) -> None:
        """Keep one row per fingerprint cluster of the column (the most
        frequent spelling wins — reference dedupeByCluster keeps the
        cluster representative)."""
        cluster = self.cluster_column(column)
        chosen = {}
        for key, spellings in cluster.clusters.items():
            chosen[key] = max(spellings.items(), key=lambda kv: kv[1])[0]
        seen = set()
        kept = []
        for r in self.rows:
            key = fingerprint(r[column])
            if key in seen:
                continue
            seen.add(key)
            r = list(r)
            r[column] = chosen[key]
            kept.append(r)
        self.rows = kept

    def dedupe_by_cluster_all(self) -> None:
        for c in range(self.num_columns):
            self.dedupe_by_cluster(c)

    # ---------------------------------------------------------- similarity
    def get_all_with_similarity(self, threshold: float, first_column: int,
                                second_column: int) -> "StringGrid":
        """Rows whose two columns are at least `threshold` similar
        (difflib ratio in [0,1] replaces the reference's JaroWinkler)."""
        rows = [r for r in self.rows
                if difflib.SequenceMatcher(
                    None, r[first_column], r[second_column]).ratio()
                >= threshold]
        return StringGrid(self.sep, self.num_columns, rows)

    def filter_by_similarity(self, threshold: float, first_column: int,
                             second_column: int) -> None:
        self.rows = self.get_all_with_similarity(
            threshold, first_column, second_column).rows
