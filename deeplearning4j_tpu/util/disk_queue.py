"""Disk-backed FIFO queue (reference util/DiskBasedQueue.java).

Spills queued items to one pickle file each so arbitrarily large streams
(e.g. pre-tokenized corpora feeding a fit loop) don't live in RAM. The
reference drains adds to disk on a background thread with a 1s poll; here
writes are synchronous — simpler, race-free, and fast enough for the
host-side data path this serves.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import uuid
from collections import deque
from typing import Any, Iterable, Optional


class DiskBasedQueue:
    """add/offer + poll/peek FIFO; items round-trip through pickle."""

    _MARKER = ".dl4j-queue"

    def __init__(self, path: Optional[str] = None):
        if path is None:
            path = tempfile.mkdtemp(prefix="dl4j-queue-")
        self.dir = path
        if os.path.exists(self.dir) and not os.path.isdir(self.dir):
            raise ValueError("queue path must be a directory")
        if os.path.isdir(self.dir) and os.listdir(self.dir):
            # only reclaim a directory a previous queue created (marker
            # file present) — never wipe arbitrary user data
            if not os.path.exists(os.path.join(self.dir, self._MARKER)):
                raise ValueError(
                    f"refusing to clear non-empty directory {self.dir!r}: "
                    f"not a {type(self).__name__} directory")
            shutil.rmtree(self.dir)
        os.makedirs(self.dir, exist_ok=True)
        with open(os.path.join(self.dir, self._MARKER), "w"):
            pass
        self._paths: deque = deque()

    # -------------------------------------------------------------- writes
    def add(self, item: Any) -> bool:
        p = os.path.join(self.dir, uuid.uuid4().hex)
        with open(p, "wb") as f:
            pickle.dump(item, f, protocol=pickle.HIGHEST_PROTOCOL)
        self._paths.append(p)
        return True

    offer = add

    def add_all(self, items: Iterable[Any]) -> bool:
        for it in items:
            self.add(it)
        return True

    # --------------------------------------------------------------- reads
    def __len__(self) -> int:
        return len(self._paths)

    def is_empty(self) -> bool:
        return not self._paths

    def peek(self) -> Any:
        if not self._paths:
            return None
        with open(self._paths[0], "rb") as f:
            return pickle.load(f)

    def poll(self) -> Any:
        """Remove and return the head, or None when empty."""
        if not self._paths:
            return None
        p = self._paths.popleft()
        with open(p, "rb") as f:
            item = pickle.load(f)
        os.remove(p)
        return item

    def clear(self) -> None:
        while self._paths:
            os.remove(self._paths.popleft())

    def close(self) -> None:
        self.clear()
        shutil.rmtree(self.dir, ignore_errors=True)
