"""Structured run telemetry — typed JSONL events for every phase a
training or bench run passes through.

VERDICT r5 demonstrated what the repo loses without this: the round
artifact's gate fields vanished to the driver's 2000-byte tail
truncation, the `transformer_large` traceback was unrecoverable, and the
DP-speedup swing had no spread data to diagnose it. The reference stack
has no tracing at all (SURVEY §5); this module is the TPU build's
equivalent of the per-phase characterization methodology of
Awan et al. (arXiv:1810.11112) — record every phase, keep distributions,
never let a crash or a truncation destroy the evidence.

Event schema — one JSON object per line, every event carrying
``{"event": <type>, "ts": <unix seconds>, "run": <run id>, "seq": <n>}``:

| event    | payload |
|---|---|
| `meta`   | run header: argv, platform, pid, free-form fields |
| `step`   | per-iteration training metrics: `iteration`, `score`, throughput fields (fed by `TelemetryListener` without hot-path host syncs) |
| `span`   | a timed region: `name` ("compile", "step", "mode:vgg16", ...), `seconds` wall-clock, `ok`, caller fields |
| `metric` | a bench metric line verbatim (same dict `bench._emit` prints) |
| `eval`   | evaluation results (accuracy/f1/stats dict) |
| `memory` | device-memory snapshot: `live_array_bytes`, `live_array_count`, per-device `memory_stats` when the backend exposes them (`bytes_in_use`, `peak_bytes_in_use`, `bytes_limit`; CPU backends return None — live-array accounting only). Ledger-attributed snapshots (telemetry/memstat.py) additionally carry `ledger` (per-subsystem `{params, opt_state, kv_pages, prefetch, activations, other}` byte map summing to `ledger_total_bytes`) and `source` ("fit" / "stats_tick" / "sampler") — emitted strictly at batch boundaries or on the sampler thread, never inside a jitted region (G029) |
| `error`  | `where`, `error` (repr), `traceback` (FULL string — never truncated at the source) |
| `fault`  | fault-injection / elastic-recovery record: `kind` (an injected fault kind from distributed/faults.py or a launcher exit class), `process_id`, `step`, free-form fields — written BEFORE the fault acts, so even a SIGKILL leaves its line |
| `bucket_plan` | the DP-overlap bucket schedule a net was configured with (parallel/placement.py): `axis`, `n_buckets`, `bucket_bytes`, `mode`, per-bucket `{index, n_leaves, bytes}` — the per-rank collective sequence on the record before any step runs; the bench's per-bucket micro-timings ride `span` events named `bucket_reduce` (`bucket`, `bytes`, `n_leaves`, `seconds`) |
| `kernel_tune` | one kernel-autotune micro-bench measurement (tools/kerneltune.py): `kernel`, `key` (the ops/autotune.py config key), `params` (the candidate block sizes), `seconds` (per-call wall clock), `role` ("default" / "candidate" / "chosen"), free-form fields — the provenance trail behind every tuning_table.json entry |
| `request` | one served inference request (serving/engine.py): `id`, `ok`, `bucket` ([batch, seq]), `replica`, `queue_s` (enqueue -> batch cut), `batch_assemble_s` (host-side padding), `forward_s` (jitted forward incl. batch-boundary fetch), `total_s` (enqueue -> result), `seq_len`/`padded_seq` for sequence models, `weight_gen` (the published weight generation the batch served against — serving/fleet.py), `error` on a failed batch — the ONLY record serving/replay.py reconstructs p50/p99/QPS from. Generation requests carry `kind: "generate"` plus `prompt_len`, `prompt_bucket`, `new_tokens`, and `ttft_s` (enqueue -> first token, i.e. the prefill's final chunk) — the rows tokens/sec and TTFT percentiles reconstruct from |
| `page_pool` | KV-cache page accounting snapshot (serving/kvcache.py), emitted on every reserve/release: `replica`, `pages_total`, `page_size`, `pages_in_use`, `pages_peak` — the cache-occupancy headline's only source |
| `draft` | one speculative verify step's draft accounting (serving/engine.py): `replica`, `k` (window width), `n_active`, `emitted` (tokens emitted this step across slots), `accepted` (accepted drafts = emitted minus the per-slot bonus token), `drafted` ((k-1) * n_active proposals offered), `overhead_us` (host-side proposer wall clock) — the `accepted_tokens_per_step` and `draft_overhead_us` bench rows reconstruct from exactly these |
| `reshard_plan` | a portable-resharding plan (reshard/) put on the record BEFORE any transfer: `path` ("live" / "checkpoint"), `src`/`dst` placement descriptions, `n_leaves`, per-action counts, `bytes_total`, `bytes_moved`, `bytes_lower_bound`; the transfer itself runs inside a `span` named `reshard` carrying the same byte fields |
| `placement_search` | one automatic-placement-search run (reshard/search.py) put on the record BEFORE any mesh is built: `path` ("cli" = the `plan` dry-run, "elastic" = a worker's per-generation re-plan, "reform" = the supervisor's pre-relaunch search, "bench" = the placement_search bench), `fleet` ("2x4"), `profile`, `candidates_considered` / `candidates_feasible` / `pruned`, `winner` (the placement description), the winner's score breakdown (`winner_score`, `winner_memory_bytes`, `winner_collective_bytes`, `winner_bubble_cost`, `winner_idle_cost`), and `search_ms` — the elastic timeline test asserts one per worker per generation |
| `host_gather` | a full-value host materialization of genuinely SHARDED leaves (util/orbax_checkpoint.host_materialize): `n_leaves`, `bytes` — resharded restore paths must show ZERO of these (asserted by the elastic timeline test) |
| `weight_swap` | one live hot-swap attempt (serving/fleet.hot_swap): `ok`, `step` (the checkpoint step restored), `restore_ms` (shadow-net restore + validation, all OFF the request path), `generation` (the WeightStore generation after a flip / still serving after a rejection), `error` on rejection — paired with the `weight_gen` field every serving `request` event carries, the flip's visibility in the traffic record |
| `autoscale` | one fleet-supervisor autoscale tick (serving/fleet.FleetSupervisor): `n_serving`, `n_replicas`, `queue_depth`, `p99_ms` (the decision inputs), `action` (+1 grew / -1 drained / 0), `max_replicas` — the occupancy bench row's only source; replica self-healing rides `fault` events (`replica-kill`/`replica-hang` when an injected fault fires, `replica-dead` with the requeued count when the supervisor reaps, `replica-respawn` with `respawn_ms` on re-admission) |
| `anomaly` | one detector finding (telemetry/trace.py) put on the record by whoever ran the detector — the elastic supervisor's straggler watch, `tracetool check`, or the bench sweep: `kind` ("straggler" / "retrace" / "input_wait_spike" / "queue_spike" / "leak" / "headroom" / "cost_drift"), `process`, and the kind's evidence fields (`step`+`skew_ms` for stragglers, the offending span's name/seconds for retraces and spikes, byte counts + growth/ratio fields for the memory kinds) |
| `cost` | one compiled executable's cost-book entry (telemetry/costbook.py), harvested at warmup/compile time from XLA's own `cost_analysis()` / `memory_analysis()` — NEVER on the hot path (it rides the existing `compile` spans): `entry` (the jit wrapper's name: "forward", "prefill", "decode", "verify", "fit_scanned", ...), `shape` (the warmed shape key), `flops`, `bytes_accessed`, `peak_temp_bytes`, `argument_bytes`, `output_bytes`, `generated_code_bytes` — the denominators behind the MFU gauge and the capacity planner's measured-cost side |
| `cost_drift` | one predicted-vs-measured reconciliation of the placement cost model (reshard/search.py `winner_memory_bytes` vs a measured per-device peak from later `memory`/`cost` events): `predicted_bytes`, `measured_bytes`, `ratio` (measured/predicted), `factor` (the documented tolerance band — outside [1/factor, factor] is an anomaly), `source` — emitted once after the first real step, the calibration loop closing over the search's exact-rational predictions |

**Correlation fields** (the fleet-timeline contract, tools/tracetool.py):
every event MAY carry `trace_id` / `span_id` / `parent_id`. `span()`
allocates a fresh `span_id` per region and stamps `parent_id` from the
thread-local span stack, so nested spans (`forward` → `compile`) become
real trees without caller plumbing; `trace(trace_id, parent_id=...)`
installs a thread-local trace context so work handed across threads
(batch cut on the dispatcher, forward on a replica) stays one tree —
the serving batcher roots a trace per cut batch (`queue` →
`batch_assemble` → `forward`/`request`), generation requests trace by
their request id, and `step` events carry `trace_id: "step-<n>"` so the
SAME global step correlates across fleet processes by id join. Events
emitted outside any context carry no correlation fields (the process
run id is the implicit root).

**Registered schema** (graftlint G023): `EVENT_KINDS` and `SPAN_NAMES`
below are the ONLY event kinds / span names code outside `telemetry/`
may emit as string literals — an unknown literal is a lint finding, so
the fleet-timeline tooling (merge, stats, anomaly detection, Perfetto
export) never meets a name it cannot classify. Dynamic names
(f-strings like the bench sweep's `mode:<name>` spans) are exempt from
the static check and parse as opaque spans.

Generation serving adds three hot-loop span names: `prefill_chunk` (one
bucket-shaped prompt chunk — `bucket`, `start`, `final`, `replica`),
`decode_step` (one fixed-shape step over every decode slot — `replica`,
`n_active`), and `verify_step` (one fixed-shape speculative
verification over every slot's k-token draft window — `replica`,
`n_active`, `k`; it REPLACES decode_step when the engine runs with
`speculative_k >= 2`, and each one pairs with a `draft` event carrying
the acceptance accounting); their first execution per shape nests a
`compile` span exactly like the predict path, and the
flat-across-prompt-buckets property of the decode_step timings is the
"decode cost independent of prompt length" gate in tier-1.

The input pipeline (data/pipeline.py) names an ``input_wait`` span
around EVERY batch dequeue in the fit loops: `pipelined` (false = the
synchronous fallback, where the span covers the whole host conversion +
device put — the stall it measures IS the input path) and `buffered`
(post-dequeue queue occupancy, pipelined only). Steady-state p99 of the
pipelined spans ~= 0 on a compute-bound workload is the starve-proof
gate the bench's `input_pipeline` mode records.

Serving also names three `span` events per batch: `queue` (the head
request's wait — what the batcher's max-wait deadline bounds),
`batch_assemble` (padding into the bucket), and `forward` (the jit call;
its FIRST execution per bucket shape nests a span named `compile`, so
the warmed compile count is reconstructable from telemetry alone — the
zero-retrace gate in tests/test_serving.py counts exactly these).

The embedding engine (embedding/) names three spans with explicit
byte accounting, surfaced in the trace timeline and the Prometheus
/metrics endpoint: `gather` (one sparse-gather embed lookup — `rows`,
`ep`, `bytes` = index + row traffic), `scatter_add` (one train step's
sparse (indices, values) update — `step` ("sgns"/"hs"), `rows`,
`bytes` = the COO pair's wire bytes, `ep`, `ep_gather_bytes` = the
forward gather's cross-rank row traffic at the ep axis), and
`ann_probe` (one batched partition-then-refine ANN lookup — `queries`,
`k`, `nprobe`, `bytes` = the probed partitions' candidate rows).

The file format is append-only JSONL so concurrent writers (bench runs
every mode in a subprocess) can share one log: each process appends
whole lines to the path named by the ``DL4J_TPU_TELEMETRY`` env var.

jax is imported lazily (only `memory()` needs it) so the module stays
importable under the graftlint AST stage's no-jax package stubs and adds
nothing to tools' startup.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import threading
import time
import traceback as _tb
from collections import deque

ENV_VAR = "DL4J_TPU_TELEMETRY"

# ------------------------------------------------------ registered schema
# The closed set of event kinds and span names the package emits —
# graftlint G023 holds every string-literal `event("...")`/`span("...")`
# outside telemetry/ to these sets, so the fleet-timeline tooling
# (telemetry/trace.py) can classify every record it merges. New kinds
# and names are REGISTERED HERE first, alongside their docstring row.
EVENT_KINDS = frozenset({
    "meta", "step", "span", "metric", "eval", "memory", "error", "fault",
    "bucket_plan", "kernel_tune", "request", "page_pool", "draft",
    "reshard_plan",
    "placement_search", "host_gather", "weight_swap", "autoscale",
    "anomaly", "cost", "cost_drift",
})

SPAN_NAMES = frozenset({
    # compile/step spine (nn/, bench)
    "compile", "step_scan", "profiler_trace",
    # serving batch pipeline (serving/batcher.py, engine.py)
    "queue", "batch_assemble", "forward", "prefill_chunk", "decode_step",
    "verify_step", "drain",
    # input pipeline (data/pipeline.py)
    "input_wait",
    # resharding + placement (reshard/)
    "reshard",
    # distributed runtime + elastic recovery (distributed/)
    "distributed_init", "distributed_launch", "elastic_generation",
    "elastic_resume",
    # bench harness (bench.py)
    "bucket_reduce", "bucket_reduce_capped", "overlap_sweep", "ab_repeat",
    # embedding engine + ANN serving (embedding/)
    "gather", "scatter_add", "ann_probe",
})

# Ring-buffer length for the in-memory mirror of emitted events; large
# enough for a full bench sweep, bounded so a long fit() can't grow RSS.
DEFAULT_KEEP = 4096


class Recorder:
    """Appends typed JSONL events to a per-run file (and an in-memory
    ring buffer, inspectable as `.events`). `path=None` records in
    memory only — the unit-test and interactive mode."""

    def __init__(self, path: str | None = None, run_id: str | None = None,
                 keep: int = DEFAULT_KEEP):
        self.path = path
        self.run_id = run_id or f"{os.getpid():x}-{int(time.time()):x}"
        self.events: deque[dict] = deque(maxlen=keep)
        # serializes seq assignment, the ring buffer and the file
        # handle: replica/dispatcher/supervisor threads all emit
        # through one Recorder. Sinks fan out OUTSIDE the lock — a
        # sink that takes its own lock (the /metrics registry) must
        # never run under this one (D002 sink reentrancy).
        self._lock = threading.Lock()
        self._seq = 0
        self._span_seq = 0
        self._fh: io.TextIOBase | None = None
        # thread-local correlation context: the current trace id and the
        # open-span stack (span_id of each enclosing `span()` region on
        # THIS thread) — cross-thread handoff goes through `trace()`
        self._tloc = threading.local()
        # live event sinks (the /metrics registry subscribes here); a
        # sink failure never poisons the recording path
        self._sinks: list = []

    # ------------------------------------------------- correlation context
    def _stack(self) -> list:
        stack = getattr(self._tloc, "stack", None)
        if stack is None:
            stack = self._tloc.stack = []
        return stack

    def new_span_id(self) -> str:
        """A process-unique span id (unique within this run; merged
        timelines key spans by (process, span_id))."""
        with self._lock:
            self._span_seq += 1
            return f"s{self._span_seq:x}"

    @contextlib.contextmanager
    def trace(self, trace_id: str | None, parent_id: str | None = None):
        """Install a trace context on THIS thread: events emitted inside
        carry `trace_id` (and `parent_id` from the span stack —
        `parent_id` here seeds the stack with a foreign span, the
        cross-thread handoff: the batcher's `batch_assemble` span parents
        the replica thread's `forward`). `trace_id=None` is a no-op so
        un-traced callers (warmup batches) need no branching."""
        if trace_id is None:
            yield
            return
        prev = getattr(self._tloc, "trace_id", None)
        self._tloc.trace_id = trace_id
        stack = self._stack()
        pushed = parent_id is not None
        if pushed:
            stack.append(parent_id)
        try:
            yield
        finally:
            if pushed and stack and stack[-1] == parent_id:
                stack.pop()
            self._tloc.trace_id = prev

    def add_sink(self, fn) -> None:
        """Subscribe a live event callback (called with each emitted
        event dict, on the emitting thread). The /metrics registry feeds
        its rolling histograms through one of these."""
        with self._lock:
            self._sinks.append(fn)

    # ------------------------------------------------------------- core
    # `kind` is positional-only so a payload field may itself be named
    # "kind" (the `fault` events carry one)
    def event(self, kind: str, /, **fields) -> dict:
        rec = {"event": kind, "ts": round(time.time(), 3),
               "run": self.run_id}
        # ambient correlation: an active trace()/span() context stamps
        # its ids unless the caller passed explicit ones
        trace_id = getattr(self._tloc, "trace_id", None)
        if trace_id is not None and "trace_id" not in fields:
            rec["trace_id"] = trace_id
        stack = getattr(self._tloc, "stack", None)
        if stack and "parent_id" not in fields and "span_id" not in fields:
            rec["parent_id"] = stack[-1]
        rec.update(fields)
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            self.events.append(rec)
            self._write(rec)
            sinks = list(self._sinks)
        # fan out AFTER releasing: a sink acquiring its own lock (the
        # /metrics histogram update) must not run under `_lock`
        for sink in sinks:
            try:
                sink(rec)
            except Exception:
                pass  # a broken sink must never break recording
        return rec

    def _write(self, rec: dict) -> None:
        # caller holds `_lock` — seq order on disk matches assignment
        if self.path is None:
            return
        if self._fh is None:
            self._fh = open(self.path, "a")
        # one whole line per write: O_APPEND keeps concurrent bench
        # subprocesses' lines intact in the shared log
        self._fh.write(json.dumps(rec, default=_jsonable) + "\n")
        self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # ------------------------------------------------------ typed events
    def meta(self, **fields) -> dict:
        fields.setdefault("argv", list(sys.argv))
        fields.setdefault("pid", os.getpid())
        return self.event("meta", **fields)

    def step(self, iteration: int, score=None, **fields) -> dict:
        if score is not None:
            fields["score"] = float(score)
        # the cross-process correlation key: every fleet member's step N
        # carries the same trace id, so the merged timeline joins step
        # completions by id (the straggler detector's input)
        fields.setdefault("trace_id", f"step-{int(iteration)}")
        return self.event("step", iteration=int(iteration), **fields)

    def metric(self, line: dict) -> dict:
        """Record a bench metric line verbatim (flattened into the event
        so artifact parsers treat telemetry logs and bench stdout
        uniformly — any dict with a `metric` key is a metric line)."""
        return self.event("metric", **line)

    def eval(self, stats, **fields) -> dict:
        if not isinstance(stats, dict):
            # Evaluation-like object: take its scalar summary methods
            # (best-effort — a half-filled Evaluation must not crash the
            # recording path)
            summary = {}
            for name in ("accuracy", "precision", "recall", "f1"):
                fn = getattr(stats, name, None)
                if callable(fn):
                    try:
                        summary[name] = float(fn())
                    except Exception:
                        pass
            stats = summary
        return self.event("eval", stats=stats, **fields)

    def error(self, where: str, exc: BaseException | None = None,
              traceback_str: str | None = None, **fields) -> dict:
        """An `error` event carries the FULL traceback string — the
        telemetry log is the truncation-proof home for what the driver's
        2000-byte stdout tail destroys (VERDICT r5 #1)."""
        if traceback_str is None and exc is not None:
            traceback_str = "".join(_tb.format_exception(
                type(exc), exc, exc.__traceback__))
        return self.event(
            "error", where=where,
            error=repr(exc) if exc is not None else fields.pop("error", ""),
            traceback=traceback_str or "", **fields)

    def fault(self, kind: str, **fields) -> dict:
        """A `fault` event: an injected failure firing
        (distributed/faults.py), a launcher exit classification, or an
        elastic-recovery lifecycle record. Emitted BEFORE the fault acts
        (`_write` flushes per line) so the full fault→recovery timeline
        is reconstructable from the JSONL even across SIGKILLs."""
        return self.event("fault", kind=kind, **fields)

    def anomaly(self, kind: str, **fields) -> dict:
        """An `anomaly` event: one detector finding (telemetry/trace.py)
        put on the record live — the elastic supervisor's straggler
        watch emits these on its heartbeat path so a skewing fleet is
        visible in the journal BEFORE the generation dies."""
        return self.event("anomaly", kind=kind, **fields)

    def kernel_tune(self, kernel: str, key: str, params: dict,
                    seconds: float | None = None, role: str = "candidate",
                    **fields) -> dict:
        """A `kernel_tune` event: one micro-bench measurement of a
        kernel block-size variant (tools/kerneltune.py). The telemetry
        log is the provenance trail behind tuning_table.json — every
        candidate's timing survives even if the sweep crashes before
        writing the table."""
        if seconds is not None:
            fields["seconds"] = round(float(seconds), 9)
        return self.event("kernel_tune", kernel=kernel, key=key,
                          params=dict(params), role=role, **fields)

    def request(self, request_id: str, *, ok: bool = True,
                **fields) -> dict:
        """A `request` event: one served inference request with its
        queue/batch_assemble/forward span breakdown
        (serving/engine.py). The traffic-replay bench reconstructs
        p50/p99 latency and sustained QPS from these events ALONE — the
        telemetry log, not in-process timers, is the serving
        scoreboard's source of truth."""
        return self.event("request", id=request_id, ok=bool(ok), **fields)

    def memory(self, **fields) -> dict:
        """Device-memory snapshot: bytes held by live jax arrays plus
        the backend's own memory_stats when exposed (TPU HBM; CPU
        backends return None). Costs a host-side walk only — no device
        sync — so it is safe between steps."""
        import jax

        live_bytes = 0
        count = 0
        for arr in jax.live_arrays():
            live_bytes += getattr(arr, "nbytes", 0) or 0
            count += 1
        devices = {}
        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if stats:
                devices[str(dev.id)] = {
                    k: stats[k] for k in ("bytes_in_use", "peak_bytes_in_use",
                                          "bytes_limit") if k in stats}
        return self.event("memory", live_array_bytes=int(live_bytes),
                          live_array_count=count, devices=devices, **fields)

    def cost(self, entry: str, shape, **fields) -> dict:
        """A `cost` event: one warmed executable's XLA cost book entry
        (telemetry/costbook.py harvests flops / bytes accessed / peak
        temp at compile time — zero hot-path cost)."""
        return self.event("cost", entry=entry, shape=shape, **fields)

    def cost_drift(self, *, predicted_bytes: int, measured_bytes: int,
                   factor: float, source: str = "placement",
                   **fields) -> dict:
        """A `cost_drift` event: the placement cost model's predicted
        per-device memory reconciled against a measured peak. `ratio`
        (measured/predicted) outside [1/factor, factor] is the
        detector's trigger."""
        predicted = max(1, int(predicted_bytes))
        ratio = float(measured_bytes) / float(predicted)
        return self.event("cost_drift",
                          predicted_bytes=int(predicted_bytes),
                          measured_bytes=int(measured_bytes),
                          ratio=round(ratio, 6), factor=float(factor),
                          source=source, **fields)

    # -------------------------------------------------------------- spans
    @contextlib.contextmanager
    def span(self, name: str, **fields):
        """Time a region: `with rec.span("compile"): ...` emits a `span`
        event with wall-clock `seconds` on exit. The yielded dict can be
        mutated to attach result fields. An exception inside the span
        emits an `error` event (full traceback) plus the span with
        `ok: false`, then re-raises.

        Correlation: the region gets a fresh `span_id`, its `parent_id`
        is the enclosing open span on this thread (or the foreign parent
        a `trace()` context seeded), and events emitted INSIDE the
        region — nested spans, errors, page_pool snapshots — parent to
        it automatically."""
        stack = self._stack()
        parent = fields.pop("parent_id", None) or (stack[-1] if stack
                                                   else None)
        sid = fields.pop("span_id", None) or self.new_span_id()
        ids = {"span_id": sid}
        if parent is not None:
            ids["parent_id"] = parent
        t0 = time.perf_counter()
        stack.append(sid)
        try:
            yield fields
        except BaseException as exc:
            self.error(f"span:{name}", exc=exc)
            stack.pop()
            self.event("span", name=name, ok=False,
                       seconds=round(time.perf_counter() - t0, 6),
                       **ids, **fields)
            raise
        stack.pop()
        self.event("span", name=name, ok=True,
                   seconds=round(time.perf_counter() - t0, 6),
                   **ids, **fields)


class NullRecorder(Recorder):
    """Telemetry disabled: every emit is a no-op so hooks threaded
    through hot loops (fused_fit, listeners) cost one attribute lookup.
    ``span`` still runs the body, recording nothing."""

    def __init__(self):
        super().__init__(path=None, run_id="null", keep=1)

    def event(self, kind: str, /, **fields) -> dict:  # noqa: D102
        return {}

    def eval(self, stats, **fields) -> dict:
        return {}  # skip the stats-dict materialization, not just the write

    def memory(self, **fields) -> dict:
        return {}  # skip the live-array walk

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        yield fields


def _jsonable(obj):
    """json.dumps fallback: device scalars/arrays stringify via float/
    repr instead of crashing the log write."""
    try:
        return float(obj)
    except Exception:
        return repr(obj)


# ------------------------------------------------------- process default
_NULL = NullRecorder()
_default: Recorder | None = None


def set_default(recorder: Recorder | None) -> Recorder | None:
    """Install the process-global recorder; returns the previous one
    (None if the env-var/null fallback was in effect)."""
    global _default
    prev, _default = _default, recorder
    return prev


def _process_scoped(path: str) -> str:
    """Multi-process safety: N fleet processes inherit ONE
    `DL4J_TPU_TELEMETRY` value from their launcher, and while O_APPEND
    keeps whole lines intact, N interleaved event streams in one file are
    unattributable (and a `requote` recovery can't tell whose crash it is
    reading). When the rendezvous contract names a process id
    (distributed/bootstrap.py), each process appends to its own
    `<path>.p<id>` instead — two writers, two parseable logs."""
    try:
        from deeplearning4j_tpu.distributed.bootstrap import ENV_PROCESS_ID
    except Exception:  # pragma: no cover - stubbed package layouts
        return path
    process_id = os.environ.get(ENV_PROCESS_ID)
    if process_id is None:
        return path
    return f"{path}.p{process_id}"


def get_default() -> Recorder:
    """The process-global recorder. Resolution order: an explicit
    `set_default`, else a file recorder appending to `$DL4J_TPU_TELEMETRY`
    (created on first use; suffixed per process when the distributed
    rendezvous contract is active), else a no-op NullRecorder."""
    global _default
    if _default is not None:
        return _default
    path = os.environ.get(ENV_VAR)
    if path:
        _default = Recorder(_process_scoped(path))
        return _default
    return _NULL
