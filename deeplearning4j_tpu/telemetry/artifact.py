"""Bench-artifact parsing + the truncation-proof summary line.

The driver keeps only the last ~2000 bytes of captured stdout, so a
round artifact routinely loses its early metric lines (r5 lost lenet/
vgg/word2vec/resnet/flagship) and — before this module — every gate
field with them (VERDICT r5 #6: `quality_ratio_vs_host`, `gate_scale`,
`vs_dense_ratio`, `mfu_vs_achievable` and WHICH metric regressed were
unverifiable from the committed artifact). The contract here:

* `build_summary` folds every gate field into the one summary line
  bench.py prints LAST, under `gates[<metric>]`, plus the
  `regressed_metrics` name list — so a tail cut that spares only the
  final line loses no gate decision.
* `parse_metric_lines` + `merge_summary` reconstruct per-metric rows
  from whatever survived: full JSONL, a driver `{"tail": ...}` wrapper,
  a telemetry JSONL log (`metric` events carry the same dict), or a
  bare summary line.

Shared by `tools/requote_bench.py` (doc regeneration) and
`tools/benchdiff.py` (cross-round regression detection). Pure stdlib —
importable under the tools' no-jax package stubs.
"""

from __future__ import annotations

import json

# Per-metric fields that carry a GATE decision (or the context needed to
# audit one). Everything listed here survives truncation via the summary
# line's `gates` object.
GATE_FIELDS = (
    "quality_ratio_vs_host", "quality_gate_min_ratio",
    "gate_scale", "vs_dense_ratio", "ratio_floor",
    "mfu_vs_achievable", "mfu_executed",
    "ratio_median", "ratio_spread",
)

# Summary-line bookkeeping keys that are NOT metric names (parsers must
# skip them when recovering per-metric rows) — includes telemetry event
# envelope keys so a telemetry log parses identically.
SUMMARY_BOOKKEEPING = {"metric", "value", "unit", "vs_baseline",
                       "regressions", "regressed_metrics", "gates",
                       "event", "ts", "run", "seq"}


def read_artifact_text(path: str) -> str:
    """File -> raw metric-line text. Accepts bench.py stdout (JSONL),
    a telemetry log, or the driver's wrapper object whose `tail` field
    holds the captured stdout.

    Sharded inputs: a multi-process fleet leaves `<path>.pN` shards and
    often NO unsuffixed file (telemetry/recorder._process_scoped) —
    when `path` is absent, the shards are read and concatenated in
    process order instead (JSONL concatenation is parse-equivalent to
    one shared log; the committed `telemetry_bench.jsonl.p0/.p1` pair
    is the fixture)."""
    try:
        with open(path) as fh:
            text = fh.read()
    except FileNotFoundError:
        text = _read_shards(path)
    try:
        wrapper = json.loads(text)
        if isinstance(wrapper, dict) and "tail" in wrapper:
            return wrapper["tail"]
    except json.JSONDecodeError:
        pass
    return text


def _read_shards(path: str) -> str:
    """Concatenated `<path>.p*` shard text, numeric process order.
    Raises the original FileNotFoundError shape when no shards exist
    either."""
    import glob as _glob
    import re as _re

    shards = []
    for cand in _glob.glob(_glob.escape(path) + ".p*"):
        m = _re.match(r"\.p(\d+)$", cand[len(path):])
        if m:
            shards.append((int(m.group(1)), cand))
    if not shards:
        raise FileNotFoundError(
            f"no artifact at {path} (and no {path}.p* shards)")
    parts = []
    for _, shard in sorted(shards):
        with open(shard) as fh:
            text = fh.read()
        parts.append(text if text.endswith("\n") or not text
                     else text + "\n")
    return "".join(parts)


def parse_metric_lines(text: str):
    """-> ({metric: line}, summary_line_or_None). Non-JSON lines, partial
    (truncated) lines, and non-metric telemetry events are skipped; a
    telemetry `metric` event parses as the bench line it carries."""
    lines: dict[str, dict] = {}
    summary = None
    for raw in text.splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            line = json.loads(raw)
        except json.JSONDecodeError:
            continue
        if line.get("event") not in (None, "metric"):
            continue
        if line.get("metric") == "summary":
            summary = line
        elif "metric" in line:
            lines[line["metric"]] = line
    return lines, summary


def merge_summary(lines: dict, summary: dict | None) -> dict:
    """Reconstruct truncated rows from the summary line, in place.

    Numeric summary keys become bare `{value, from_summary}` rows for
    metrics the tail lost; `gates[<metric>]` fields and the
    `regressed_metrics` flags merge non-destructively (a surviving
    detail line always wins over its summary restatement)."""
    if not summary:
        return lines
    for key, val in summary.items():
        if key not in SUMMARY_BOOKKEEPING and key not in lines \
                and isinstance(val, (int, float)) \
                and not isinstance(val, bool):
            lines[key] = {"metric": key, "value": val, "from_summary": True}
    for metric, gate in (summary.get("gates") or {}).items():
        row = lines.setdefault(metric, {"metric": metric,
                                        "from_summary": True})
        for k, v in gate.items():
            row.setdefault(k, v)
    for metric in summary.get("regressed_metrics") or []:
        row = lines.setdefault(metric, {"metric": metric,
                                        "from_summary": True})
        row.setdefault("regression", True)
    return lines


def load(path: str) -> dict:
    """Artifact path -> {metric: line} with summary recovery applied —
    the one loader both tools share."""
    lines, summary = parse_metric_lines(read_artifact_text(path))
    return merge_summary(lines, summary)


def build_summary(collected) -> dict:
    """Fold a run's metric lines (dicts or raw JSON strings) into the
    single gate-carrying summary line. bench.py prints this LAST so the
    driver's tail always keeps it; `merge_summary` is its inverse."""
    summary = {"metric": "summary", "value": None, "unit": "",
               "vs_baseline": None, "regressions": 0,
               "regressed_metrics": [], "gates": {}}
    for item in collected:
        if isinstance(item, str):
            try:
                line = json.loads(item)
            except json.JSONDecodeError:
                continue
        else:
            line = item
        metric = line.get("metric")
        if not metric or metric == "summary":
            continue
        if isinstance(line.get("value"), (int, float)):
            summary[metric] = line["value"]
        if line.get("regression"):
            summary["regressions"] += 1
            summary["regressed_metrics"].append(metric)
        gate = {k: line[k] for k in GATE_FIELDS if k in line}
        if line.get("regression"):
            gate["regression"] = True
        if gate:
            summary["gates"][metric] = gate
        if str(metric).startswith("transformer_lm_mfu"):
            # headline fields: the north-star MFU metric, so a parser
            # taking the LAST line still sees a well-formed metric
            summary["value"] = line.get("value")
            summary["unit"] = line.get("unit", "")
            summary["vs_baseline"] = line.get("vs_baseline")
    return summary
