"""Run telemetry: typed JSONL event recording for training and bench.

`recorder` (Recorder/span API, process default) and `artifact` (bench
summary/parsing) are stdlib-only and import eagerly; `TelemetryListener`
pulls in the listener protocol and resolves lazily so the tools' no-jax
package stubs can import this package.
"""

from deeplearning4j_tpu.telemetry.recorder import (  # noqa: F401
    ENV_VAR,
    NullRecorder,
    Recorder,
    get_default,
    set_default,
)


def __getattr__(name):
    if name == "TelemetryListener":
        from deeplearning4j_tpu.telemetry.listener import TelemetryListener
        return TelemetryListener
    raise AttributeError(name)
