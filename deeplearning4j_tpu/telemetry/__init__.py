"""Run telemetry: typed JSONL event recording for training and bench,
plus the fleet-wide trace timeline built on top of it.

`recorder` (Recorder/span API, correlation fields, process default) and
`artifact` (bench summary/parsing) are stdlib-only and import eagerly;
`trace` (shard merge / span stats / anomaly detection / Perfetto
export) and `metrics` (the Prometheus /metrics registry) are
stdlib-only too and resolve lazily alongside `TelemetryListener` so
the tools' no-jax package stubs can import this package.
"""

from deeplearning4j_tpu.telemetry.recorder import (  # noqa: F401
    ENV_VAR,
    EVENT_KINDS,
    SPAN_NAMES,
    NullRecorder,
    Recorder,
    get_default,
    set_default,
)


def __getattr__(name):
    if name == "TelemetryListener":
        from deeplearning4j_tpu.telemetry.listener import TelemetryListener
        return TelemetryListener
    if name in ("MemoryLedger", "MemorySampler"):
        from deeplearning4j_tpu.telemetry import memstat
        return getattr(memstat, name)
    if name == "CostBook":
        from deeplearning4j_tpu.telemetry.costbook import CostBook
        return CostBook
    if name in ("trace", "metrics", "memstat", "costbook"):
        import importlib
        return importlib.import_module(
            f"deeplearning4j_tpu.telemetry.{name}")
    raise AttributeError(name)
